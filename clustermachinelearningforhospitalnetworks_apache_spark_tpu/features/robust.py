"""RobustScaler and MaxAbsScaler.

Parity with ``pyspark.ml.feature.RobustScaler`` (center by median, scale
by the IQR — outlier-resistant standardization) and ``MaxAbsScaler``
(scale to [-1, 1] by the per-column max |x|, preserving sparsity/signs).

MaxAbsScaler's statistic is one fused device min/max reduction
(``ops.reductions.moment_stats``).  RobustScaler's quantiles come from a
bounded host sample of valid rows (the same estimator the tree binning
uses, ``parallel.sharding.sample_valid_rows``) — Spark likewise computes
them with approxQuantile rather than an exact distributed sort.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.reductions import moment_stats
from ..parallel.sharding import DeviceDataset
from .scaler import _is_assembled


@register_model("MaxAbsScalerModel")
@dataclass(frozen=True)
class MaxAbsScalerModel:
    max_abs: np.ndarray

    def _artifacts(self):
        return ("MaxAbsScalerModel", {}, {"max_abs": np.asarray(self.max_abs)})

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(arrays["max_abs"])

    def transform(self, x):
        if _is_assembled(x):
            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            return DeviceDataset(
                x=self.transform(x.x) * (x.w[:, None] > 0), y=x.y, w=x.w
            )
        xp = jnp if isinstance(x, jax.Array) else np
        m = xp.asarray(self.max_abs, x.dtype)
        safe = xp.where(m > 0, m, 1.0)   # all-zero column stays zero
        return x / safe[None, :]


@dataclass(frozen=True)
class MaxAbsScaler:
    def fit(self, data) -> MaxAbsScalerModel:
        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            s = moment_stats(data.x, data.w)
            if float(s["count"]) == 0.0:
                raise ValueError("MaxAbsScaler fit on an empty dataset")
            lo, hi = np.asarray(s["min"], np.float64), np.asarray(s["max"], np.float64)
        else:
            x = np.asarray(data, np.float64)
            if x.shape[0] == 0:
                raise ValueError("MaxAbsScaler fit on an empty dataset")
            lo, hi = x.min(axis=0), x.max(axis=0)
        return MaxAbsScalerModel(np.maximum(np.abs(lo), np.abs(hi)))

    def fit_transform(self, data):
        return self.fit(data).transform(data)


@register_model("RobustScalerModel")
@dataclass(frozen=True)
class RobustScalerModel:
    median: np.ndarray     # per-column q50
    iqr: np.ndarray        # per-column q(upper) − q(lower)
    with_centering: bool = False
    with_scaling: bool = True

    def _artifacts(self):
        return (
            "RobustScalerModel",
            {
                "with_centering": self.with_centering,
                "with_scaling": self.with_scaling,
            },
            {"median": np.asarray(self.median), "iqr": np.asarray(self.iqr)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            arrays["median"], arrays["iqr"],
            bool(params.get("with_centering", False)),
            bool(params.get("with_scaling", True)),
        )

    def transform(self, x):
        if _is_assembled(x):
            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            return DeviceDataset(
                x=self.transform(x.x) * (x.w[:, None] > 0), y=x.y, w=x.w
            )
        xp = jnp if isinstance(x, jax.Array) else np
        out = x
        if self.with_centering:
            out = out - xp.asarray(self.median, x.dtype)[None, :]
        if self.with_scaling:
            s = xp.asarray(self.iqr, x.dtype)
            out = out / xp.where(s > 0, s, 1.0)[None, :]  # constant col unscaled
        return out


@dataclass(frozen=True)
class RobustScaler:
    """Spark defaults: lower=0.25, upper=0.75, withCentering=False,
    withScaling=True."""

    lower: float = 0.25
    upper: float = 0.75
    with_centering: bool = False
    with_scaling: bool = True
    sample_size: int = 65536

    def __post_init__(self):
        if not 0.0 <= self.lower < self.upper <= 1.0:
            raise ValueError(
                f"need 0 <= lower < upper <= 1; got ({self.lower}, {self.upper})"
            )

    def fit(self, data) -> RobustScalerModel:
        from ..parallel.sharding import sample_valid_rows

        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            sample = sample_valid_rows(data, self.sample_size, seed=0)
        else:
            sample = np.asarray(data, np.float64)
            if sample.shape[0] > self.sample_size:
                rng = np.random.default_rng(0)
                sample = sample[
                    np.sort(
                        rng.choice(sample.shape[0], self.sample_size, replace=False)
                    )
                ]
        if sample.shape[0] == 0:
            raise ValueError("RobustScaler fit on an empty dataset")
        q = np.quantile(sample, [self.lower, 0.5, self.upper], axis=0)
        return RobustScalerModel(
            median=q[1], iqr=q[2] - q[0],
            with_centering=self.with_centering, with_scaling=self.with_scaling,
        )

    def fit_transform(self, data):
        return self.fit(data).transform(data)
