"""RobustScaler and MaxAbsScaler.

Parity with ``pyspark.ml.feature.RobustScaler`` (center by median, scale
by the IQR — outlier-resistant standardization) and ``MaxAbsScaler``
(scale to [-1, 1] by the per-column max |x|, preserving sparsity/signs).

MaxAbsScaler's statistic is one fused device min/max reduction
(``ops.reductions.moment_stats``).  RobustScaler's quantiles come from a
bounded host sample of valid rows (the same estimator the tree binning
uses, ``parallel.sharding.sample_valid_rows``) — Spark likewise computes
them with approxQuantile rather than an exact distributed sort.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.reductions import moment_stats
from ..parallel.sharding import DeviceDataset
from .scaler import _is_assembled


@register_model("MaxAbsScalerModel")
@dataclass(frozen=True)
class MaxAbsScalerModel:
    max_abs: np.ndarray

    def _artifacts(self):
        return ("MaxAbsScalerModel", {}, {"max_abs": np.asarray(self.max_abs)})

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(arrays["max_abs"])

    def transform(self, x):
        if _is_assembled(x):
            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            return DeviceDataset(
                x=self.transform(x.x) * (x.w[:, None] > 0), y=x.y, w=x.w
            )
        xp = jnp if isinstance(x, jax.Array) else np
        m = xp.asarray(self.max_abs, x.dtype)
        safe = xp.where(m > 0, m, 1.0)   # all-zero column stays zero
        return x / safe[None, :]


@dataclass(frozen=True)
class MaxAbsScaler:
    def fit(self, data) -> MaxAbsScalerModel:
        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            s = moment_stats(data.x, data.w)
            if float(s["count"]) == 0.0:
                raise ValueError("MaxAbsScaler fit on an empty dataset")
            lo, hi = np.asarray(s["min"], np.float64), np.asarray(s["max"], np.float64)
            if not (np.isfinite(lo).all() and np.isfinite(hi).all()):
                # NaNs in the data poison the device min/max reduction;
                # redo the affected statistic NaN-aware on the host (the
                # rare firewall-accepted-missing case, not the hot path)
                xh = np.asarray(jax.device_get(data.x), np.float64)
                valid = np.asarray(jax.device_get(data.w)) > 0
                xh = xh[valid]
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    lo = np.nanmin(xh, axis=0)
                    hi = np.nanmax(xh, axis=0)
        else:
            x = np.asarray(data, np.float64)
            if x.shape[0] == 0:
                raise ValueError("MaxAbsScaler fit on an empty dataset")
            # NaN-tolerant: the data firewall accepts missing features and
            # routes them here — one NaN must not de-scale a whole column
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN col
                lo, hi = np.nanmin(x, axis=0), np.nanmax(x, axis=0)
        m = np.maximum(np.abs(lo), np.abs(hi))
        return MaxAbsScalerModel(np.where(np.isfinite(m), m, 0.0))

    def fit_transform(self, data):
        return self.fit(data).transform(data)


@register_model("RobustScalerModel")
@dataclass(frozen=True)
class RobustScalerModel:
    median: np.ndarray     # per-column q50
    iqr: np.ndarray        # per-column q(upper) − q(lower)
    with_centering: bool = False
    with_scaling: bool = True

    def _artifacts(self):
        return (
            "RobustScalerModel",
            {
                "with_centering": self.with_centering,
                "with_scaling": self.with_scaling,
            },
            {"median": np.asarray(self.median), "iqr": np.asarray(self.iqr)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            arrays["median"], arrays["iqr"],
            bool(params.get("with_centering", False)),
            bool(params.get("with_scaling", True)),
        )

    def transform(self, x):
        if _is_assembled(x):
            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            return DeviceDataset(
                x=self.transform(x.x) * (x.w[:, None] > 0), y=x.y, w=x.w
            )
        xp = jnp if isinstance(x, jax.Array) else np
        out = x
        if self.with_centering:
            out = out - xp.asarray(self.median, x.dtype)[None, :]
        if self.with_scaling:
            s = xp.asarray(self.iqr, x.dtype)
            out = out / xp.where(s > 0, s, 1.0)[None, :]  # constant col unscaled
        return out


@dataclass(frozen=True)
class RobustScaler:
    """Spark defaults: lower=0.25, upper=0.75, withCentering=False,
    withScaling=True."""

    lower: float = 0.25
    upper: float = 0.75
    with_centering: bool = False
    with_scaling: bool = True
    sample_size: int = 65536

    def __post_init__(self):
        if not 0.0 <= self.lower < self.upper <= 1.0:
            raise ValueError(
                f"need 0 <= lower < upper <= 1; got ({self.lower}, {self.upper})"
            )

    def fit(self, data) -> RobustScalerModel:
        from ..parallel.sharding import sample_valid_rows

        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            sample = sample_valid_rows(data, self.sample_size, seed=0)
        else:
            sample = np.asarray(data, np.float64)
            if sample.shape[0] > self.sample_size:
                rng = np.random.default_rng(0)
                sample = sample[
                    np.sort(
                        rng.choice(sample.shape[0], self.sample_size, replace=False)
                    )
                ]
        if sample.shape[0] == 0:
            raise ValueError("RobustScaler fit on an empty dataset")
        # nanquantile: missing values (firewall-accepted NaNs) don't poison
        # the statistic; an all-NaN column degrades to median 0 / iqr 0
        # (transform leaves it unscaled) instead of NaN-ing every row
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN col
            q = np.nanquantile(sample, [self.lower, 0.5, self.upper], axis=0)
        median = np.where(np.isfinite(q[1]), q[1], 0.0)
        iqr = np.where(np.isfinite(q[2] - q[0]), q[2] - q[0], 0.0)
        return RobustScalerModel(
            median=median, iqr=iqr,
            with_centering=self.with_centering, with_scaling=self.with_scaling,
        )

    def fit_transform(self, data):
        return self.fit(data).transform(data)
