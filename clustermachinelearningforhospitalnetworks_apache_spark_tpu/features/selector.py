"""VectorIndexer and feature selectors — the indexing/selection tail of
``pyspark.ml.feature``.

``VectorIndexer`` (Spark): scan an assembled feature matrix, decide which
columns are categorical (≤ ``max_categories`` distinct values), and
re-encode those columns to category indices.  Here it additionally
exposes the decision as a ``categorical_features`` dict — exactly the
``{index: arity}`` spec the tree estimators consume — closing the
StringIndexer → VectorIndexer → tree loop the reference's unused
StringIndexer import pointed at (``mllearnforhospitalnetwork.py:29``,
SURVEY.md D5).

``UnivariateFeatureSelector`` (Spark 3.1+): pick features by a statistical
test chosen from (featureType, labelType) — chi2 for categorical/
categorical, ANOVA F for continuous features vs categorical label, F-value
for continuous/continuous — reusing this framework's ``ChiSquareTest`` /
``ANOVATest`` / ``FValueTest`` device reductions.  ``ChiSqSelector`` is
the classic (pre-3.1) chi2-only spelling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model, save_model
from .assembler import AssembledTable


class _Saveable:
    """Direct save/write sugar for stage models (same artifact layout the
    Pipeline persistence machinery writes)."""

    def save(self, path: str, overwrite: bool = True) -> None:
        name, meta, arrays = self._artifacts()
        save_model(path, name, meta, arrays, overwrite=overwrite)

    def write(self):
        from ..models.base import _Writer

        return _Writer(self)


def _as_matrix(data: Any) -> np.ndarray:
    if isinstance(data, AssembledTable):
        return np.asarray(data.features, dtype=np.float64)
    return np.asarray(data, dtype=np.float64)


def _rewrap(data: Any, mat: np.ndarray, cols: Sequence[str] | None = None):
    """Return the transformed matrix in the caller's container shape."""
    if isinstance(data, AssembledTable):
        return AssembledTable(
            table=data.table,
            feature_cols=tuple(cols) if cols is not None else data.feature_cols,
            features=mat,
            output_col=data.output_col,
        )
    return mat


# ------------------------------------------------------------ VectorIndexer
@register_model("VectorIndexerModel")
@dataclass(frozen=True)
class VectorIndexerModel(_Saveable):
    """``category_maps``: feature index → tuple of ORIGINAL values, in
    ascending order; the value's position is its category index."""

    num_features: int
    category_maps: dict[int, tuple[float, ...]]
    handle_invalid: str = "error"   # "error" | "keep" | "skip"

    @property
    def categorical_features(self) -> dict[int, int]:
        """The ``{index: arity}`` spec the tree estimators accept —
        "keep" mode reserves one extra index for unseen values."""
        extra = 1 if self.handle_invalid == "keep" else 0
        return {f: len(v) + extra for f, v in self.category_maps.items()}

    def transform(self, data):
        x = _as_matrix(data).copy()
        drop = np.zeros(x.shape[0], dtype=bool)
        for f, values in self.category_maps.items():
            # values is ascending (np.unique at fit), so one searchsorted
            # maps the whole column — no per-row Python loop
            va = np.asarray(values)
            col = x[:, f]
            codes = np.searchsorted(va, col)
            unseen = (codes >= va.size) | (va[np.minimum(codes, va.size - 1)] != col)
            if unseen.any():
                if self.handle_invalid == "error":
                    bad = col[unseen][0]
                    raise ValueError(
                        f"unseen value {bad!r} in categorical feature {f} "
                        "(handle_invalid='error')"
                    )
                if self.handle_invalid == "skip":
                    drop |= unseen
                    codes = np.where(unseen, 0, codes)
                else:  # keep → the reserved extra category
                    codes = np.where(unseen, va.size, codes)
            x[:, f] = codes
        if self.handle_invalid == "skip" and drop.any():
            if not isinstance(data, AssembledTable):
                return x[~drop]
            return AssembledTable(
                table=data.table.mask(~drop),
                feature_cols=data.feature_cols,
                features=x[~drop],
                output_col=data.output_col,
            )
        return _rewrap(data, x)

    def _artifacts(self):
        return (
            "VectorIndexerModel",
            {
                "num_features": self.num_features,
                "handle_invalid": self.handle_invalid,
                "category_maps": {
                    str(k): list(map(float, v)) for k, v in self.category_maps.items()
                },
            },
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            num_features=int(params["num_features"]),
            category_maps={
                int(k): tuple(v) for k, v in params["category_maps"].items()
            },
            handle_invalid=params.get("handle_invalid", "error"),
        )


@dataclass(frozen=True)
class VectorIndexer:
    max_categories: int = 20        # Spark default
    handle_invalid: str = "error"

    def fit(self, data, label_col=None, mesh=None) -> VectorIndexerModel:
        if self.handle_invalid not in ("error", "keep", "skip"):
            raise ValueError(
                f"handle_invalid must be error|keep|skip, got "
                f"{self.handle_invalid!r}"
            )
        x = _as_matrix(data)
        maps: dict[int, tuple[float, ...]] = {}
        for f in range(x.shape[1]):
            distinct = np.unique(x[:, f])
            if distinct.size <= self.max_categories:
                maps[f] = tuple(float(v) for v in distinct)
        return VectorIndexerModel(
            num_features=x.shape[1],
            category_maps=maps,
            handle_invalid=self.handle_invalid,
        )


# ------------------------------------------------- UnivariateFeatureSelector
@register_model("UnivariateFeatureSelectorModel")
@dataclass(frozen=True)
class UnivariateFeatureSelectorModel(_Saveable):
    selected: tuple[int, ...]       # ascending feature indices

    def transform(self, data):
        x = _as_matrix(data)
        idx = list(self.selected)
        cols = None
        if isinstance(data, AssembledTable):
            cols = [data.feature_cols[i] for i in idx]
        return _rewrap(data, x[:, idx], cols)

    def _artifacts(self):
        return (
            "UnivariateFeatureSelectorModel",
            {"selected": list(map(int, self.selected))},
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(selected=tuple(int(i) for i in params["selected"]))


@dataclass(frozen=True)
class UnivariateFeatureSelector:
    """Spark's test matrix: (featureType, labelType) → chi2 | ANOVA F |
    F-value.  ``selection_mode``: numTopFeatures (default, Spark too),
    percentile, fpr (p-value threshold)."""

    feature_type: str = "continuous"     # "continuous" | "categorical"
    label_type: str = "categorical"      # "continuous" | "categorical"
    selection_mode: str = "numTopFeatures"
    selection_threshold: float | None = None  # mode-dependent default
    label_col: str = "LOS_binary"

    def _p_values(self, x, y, mesh):
        from ..stat import ANOVATest, ChiSquareTest, FValueTest

        ft, lt = self.feature_type, self.label_type
        if ft == "categorical" and lt == "categorical":
            return ChiSquareTest.test(x, y).p_values
        if ft == "continuous" and lt == "categorical":
            return ANOVATest.test(
                x.astype(np.float32), y.astype(np.float32), mesh=mesh
            ).p_values
        if ft == "continuous" and lt == "continuous":
            return FValueTest.test(
                x.astype(np.float32), y.astype(np.float32), mesh=mesh
            ).p_values
        raise ValueError(
            "categorical features with a continuous label have no Spark "
            "test; bucketize the label or use feature_type='continuous'"
        )

    def fit(self, data, label_col: str | None = None, mesh=None):
        x = _as_matrix(data)
        if isinstance(data, AssembledTable):
            y = data.label(label_col or self.label_col)
        else:
            raise ValueError(
                "UnivariateFeatureSelector needs an AssembledTable (the "
                "label column resolves against the table)"
            )
        p = np.asarray(self._p_values(x, y, mesh), dtype=np.float64)
        d = x.shape[1]
        mode = self.selection_mode
        if mode == "numTopFeatures":
            top = int(self.selection_threshold or 50)
            sel = np.sort(np.argsort(p, kind="stable")[: min(top, d)])
        elif mode == "percentile":
            frac = self.selection_threshold if self.selection_threshold is not None else 0.1
            keep = max(1, int(d * float(frac)))
            sel = np.sort(np.argsort(p, kind="stable")[:keep])
        elif mode == "fpr":
            alpha = self.selection_threshold if self.selection_threshold is not None else 0.05
            sel = np.flatnonzero(p < float(alpha))
        else:
            raise ValueError(
                f"selection_mode must be numTopFeatures|percentile|fpr, got "
                f"{mode!r}"
            )
        return UnivariateFeatureSelectorModel(selected=tuple(int(i) for i in sel))


@dataclass(frozen=True)
class ChiSqSelector:
    """Classic chi2 selector (Spark pre-3.1) — categorical features vs a
    categorical label, top-N by p-value."""

    num_top_features: int = 50
    label_col: str = "LOS_binary"

    def fit(self, data, label_col: str | None = None, mesh=None):
        return UnivariateFeatureSelector(
            feature_type="categorical",
            label_type="categorical",
            selection_mode="numTopFeatures",
            selection_threshold=self.num_top_features,
            label_col=label_col or self.label_col,
        ).fit(data, label_col=label_col, mesh=mesh)


# --------------------------------------------- VarianceThresholdSelector
@register_model("VarianceThresholdSelectorModel")
@dataclass(frozen=True)
class VarianceThresholdSelectorModel(_Saveable):
    selected: tuple[int, ...]

    def transform(self, data):
        from ..parallel.sharding import DeviceDataset

        idx = list(self.selected)
        if isinstance(data, DeviceDataset):
            # column subset stays device-resident (fit accepts a
            # DeviceDataset, so transform must too)
            return DeviceDataset(
                x=data.x[:, np.asarray(idx, np.int32)], y=data.y, w=data.w
            )
        x = _as_matrix(data)
        cols = None
        if isinstance(data, AssembledTable):
            cols = [data.feature_cols[i] for i in idx]
        return _rewrap(data, x[:, idx], cols)

    def _artifacts(self):
        return (
            "VarianceThresholdSelectorModel",
            {"selected": list(map(int, self.selected))},
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(selected=tuple(int(i) for i in params["selected"]))


@dataclass(frozen=True)
class VarianceThresholdSelector:
    """Drop features whose SAMPLE variance is ≤ ``variance_threshold``
    (Spark 3.1's selector; default 0 keeps everything non-constant).
    The variance comes from one fused device moment pass."""

    variance_threshold: float = 0.0

    def fit(self, data, label_col: str | None = None, mesh=None):
        from ..ops.reductions import moment_stats
        from ..parallel.sharding import DeviceDataset

        if isinstance(data, AssembledTable):
            ds = data.to_device(mesh=mesh)
        elif isinstance(data, DeviceDataset):
            ds = data
        else:
            x = np.asarray(data, np.float64)
            n = x.shape[0]
            var = x.var(axis=0, ddof=1) if n > 1 else np.zeros(x.shape[1])
            sel = np.flatnonzero(var > self.variance_threshold)
            return VarianceThresholdSelectorModel(
                selected=tuple(int(i) for i in sel)
            )
        s = {k: np.asarray(v, np.float64) for k, v in moment_stats(ds.x, ds.w).items()}
        n = s["n"]
        if n <= 1:
            raise ValueError("VarianceThresholdSelector needs at least 2 rows")
        mean = s["s1"] / n
        # weighted SAMPLE variance (ddof=1 at unit weights — Spark's)
        var = np.maximum(s["s2"] / n - mean * mean, 0.0) * (n / max(n - 1.0, 1.0))
        sel = np.flatnonzero(var > self.variance_threshold)
        return VarianceThresholdSelectorModel(selected=tuple(int(i) for i in sel))
