"""Locality-sensitive hashing — ``pyspark.ml.feature``'s two LSH families.

``BucketedRandomProjectionLSH`` (Euclidean) and ``MinHashLSH`` (Jaccard),
each with Spark's full model surface: ``transform`` (append per-table
hash values), ``approx_nearest_neighbors`` and ``approx_similarity_join``
(Spark's LSHModel methods; ``pyspark.ml.feature`` 3.x).

TPU-first split of the work:

- **Random-projection hashing is one batched matmul**: the whole hash
  family is ``floor(X @ Vᵀ / bucketLength)`` for an (n, d) matrix
  against (T, d) unit Gaussian projections — where Spark evaluates T
  dot products per row inside a UDF.  It runs in double precision
  (host BLAS) because bucket ids must be exact — see ``_hashes``.  The
  exact-distance verification pass that follows candidate generation is
  likewise one batched gather + norm reduction, not a per-pair UDF.
- **MinHash needs exact integer modular arithmetic** (products of ~2³¹
  residues: only exact in 64-bit ints, which the TPU vector unit does
  not do natively — f32 mantissas would corrupt low bits and change
  bucket ids).  The (T, d) per-index hash table is precomputed once on
  host in int64 and the per-row masked-min reduction runs at NumPy
  memory bandwidth; d and T are small (hash tables, not data).
- **Bucket bookkeeping stays on host** like FPGrowth's pattern mining:
  grouping rows by hash value is a ragged, data-dependent structure
  with no dense tensor shape.  Candidate-pair expansion is still fully
  vectorized (sort-merge via ``searchsorted`` + ``repeat``), never a
  Python loop over rows.

Spark parity notes: MinHash uses Spark's hash family
``h(j) = ((1 + j)·a + b) mod 2038074743`` (MinHashLSH.HASH_PRIME) over
the indices of non-zero entries; ``approx_nearest_neighbors`` follows
Spark's single-probe semantics — only rows sharing at least one bucket
with the key are candidates, so fewer than k rows can be returned
(Spark's docs say the same).  Replaces the Spark stages the reference
could reach through its ``pyspark.ml.feature`` imports
(mllearnforhospitalnetwork.py:29; SURVEY.md §2B E3).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model
from .assembler import AssembledTable
from .selector import _as_matrix, _Saveable

#: Spark's MinHashLSH.HASH_PRIME
_MINHASH_PRIME = 2038074743


def _candidate_pairs(ha: np.ndarray, hb: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(idx_a, idx_b) pairs sharing a bucket in ≥1 of the T hash tables.

    Vectorized sort-merge per table: sort side B's bucket ids once, then
    every A row's matching B range comes from two ``searchsorted`` calls;
    the ragged ranges expand with the standard repeat/cumsum trick.
    Pairs found by several tables dedupe through one ``np.unique`` on the
    fused pair id."""
    n_b = hb.shape[0]
    out = []
    for t in range(ha.shape[1]):
        order = np.argsort(hb[:, t], kind="stable")
        sb = hb[order, t]
        left = np.searchsorted(sb, ha[:, t], side="left")
        right = np.searchsorted(sb, ha[:, t], side="right")
        counts = right - left
        if not counts.any():
            continue
        ia = np.repeat(np.arange(ha.shape[0]), counts)
        # offsets within each run: arange minus the run's start
        starts = np.repeat(left, counts)
        within = np.arange(counts.sum()) - np.repeat(
            np.concatenate([[0], np.cumsum(counts)[:-1]]), counts
        )
        ib = order[starts + within]
        out.append(ia.astype(np.int64) * n_b + ib.astype(np.int64))
    if not out:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    fused = np.unique(np.concatenate(out))
    return fused // n_b, fused % n_b


class _LSHModelBase(_Saveable):
    """Shared candidate-generation + verification skeleton; subclasses
    supply ``_hashes(x)`` and ``_distances(xa, xb)``."""

    #: prefix for the appended per-table hash columns on Table inputs
    output_col: str = "hashes"

    def transform(self, data):
        """Raw arrays → the (n, num_hash_tables) integer hash matrix.
        ``AssembledTable`` → the SAME features with ``hashes_<t>`` columns
        appended to the underlying table — Spark's LSH transform adds
        ``outputCol`` and leaves ``inputCol`` intact, so an LSH stage
        mid-Pipeline must not replace the feature matrix with bucket
        ids."""
        h = self._hashes(_as_matrix(data))
        if not isinstance(data, AssembledTable):
            return h
        cols = dict(data.table.columns)
        for t in range(h.shape[1]):
            cols[f"{self.output_col}_{t}"] = h[:, t]
        return AssembledTable(
            table=Table.from_dict(cols),
            feature_cols=data.feature_cols,
            features=data.features,
            output_col=data.output_col,
        )

    def hash_matrix(self, data) -> np.ndarray:
        """(n, num_hash_tables) integer hash values for any input."""
        return self._hashes(_as_matrix(data))

    def approx_nearest_neighbors(
        self, data, key, k: int, *, return_distances: bool = True
    ):
        """Indices of (≤ k) nearest rows among hash-bucket candidates,
        ascending by exact distance; with ``return_distances``, a
        ``(indices, distances)`` tuple (Spark returns the joined rows +
        ``distCol``; indices into ``data`` are this framework's row
        handle)."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        x = _as_matrix(data)
        key = np.asarray(key, np.float64).reshape(1, -1)
        if key.shape[1] != x.shape[1]:
            raise ValueError(
                f"key has {key.shape[1]} features, dataset has {x.shape[1]}"
            )
        cand, _ = _candidate_pairs(self._hashes(x), self._hashes(key))
        if cand.size == 0:
            empty = np.empty(0, np.int64)
            return (empty, np.empty(0)) if return_distances else empty
        d = self._distances(x[cand], key)
        order = np.argsort(d, kind="stable")[:k]
        idx = cand[order]
        return (idx, d[order]) if return_distances else idx

    def approx_similarity_join(self, a, b, threshold: float):
        """(idx_a, idx_b, distance) for candidate pairs with exact
        distance ≤ threshold (Spark's ``approxSimilarityJoin`` with
        ``distCol`` materialized as the third array)."""
        if threshold < 0:
            raise ValueError(f"threshold must be >= 0, got {threshold}")
        xa, xb = _as_matrix(a), _as_matrix(b)
        if xa.shape[1] != xb.shape[1]:
            raise ValueError(
                f"feature widths differ: {xa.shape[1]} vs {xb.shape[1]}"
            )
        ia, ib = _candidate_pairs(self._hashes(xa), self._hashes(xb))
        if ia.size == 0:
            return ia, ib, np.empty(0)
        d = self._distances(xa[ia], xb[ib])
        keep = d <= threshold
        return ia[keep], ib[keep], d[keep]


@register_model("BucketedRandomProjectionLSHModel")
@dataclass(frozen=True)
class BucketedRandomProjectionLSHModel(_LSHModelBase):
    """``projections``: (num_hash_tables, d) unit Gaussian directions;
    hash = ⌊x·v / bucketLength⌋ (Spark's EuclideanDistance family)."""

    projections: np.ndarray
    bucket_length: float

    def _hashes(self, x: np.ndarray) -> np.ndarray:
        # ONE (n, d) @ (d, T) matmul for all tables.  Double precision on
        # host BLAS, matching Spark's double hashing: bucket ids must be
        # EXACT — at f32, features of magnitude ~1e8 have ~8-unit ULP
        # spacing, which silently collapses distinct buckets whenever
        # bucket_length < ULP.  The (n, T) hash pass is a skinny
        # bandwidth-trivial matmul next to any training fit; the exact
        # distance verification below it batches the same way either way.
        return np.floor(
            x @ self.projections.T / self.bucket_length
        ).astype(np.int64)

    def _distances(self, xa: np.ndarray, xb: np.ndarray) -> np.ndarray:
        diff = xa - xb
        return np.sqrt(np.einsum("nd,nd->n", diff, diff))

    def _artifacts(self):
        return (
            "BucketedRandomProjectionLSHModel",
            {"bucket_length": float(self.bucket_length)},
            {"projections": np.asarray(self.projections, np.float32)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            projections=np.asarray(arrays["projections"], np.float64),
            bucket_length=float(params["bucket_length"]),
        )


@dataclass(frozen=True)
class BucketedRandomProjectionLSH:
    """Spark params: ``bucket_length`` (required, > 0), ``num_hash_tables``
    (default 1), ``seed``."""

    bucket_length: float = 0.0
    num_hash_tables: int = 1
    seed: int = 0

    def fit(self, data, label_col=None, mesh=None) -> BucketedRandomProjectionLSHModel:
        if self.bucket_length <= 0:
            raise ValueError(
                f"bucket_length must be > 0, got {self.bucket_length}"
            )
        if self.num_hash_tables < 1:
            raise ValueError(
                f"num_hash_tables must be >= 1, got {self.num_hash_tables}"
            )
        d = _as_matrix(data).shape[1]
        rng = np.random.default_rng(self.seed)
        v = rng.normal(size=(self.num_hash_tables, d))
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        return BucketedRandomProjectionLSHModel(
            projections=v, bucket_length=float(self.bucket_length)
        )


@register_model("MinHashLSHModel")
@dataclass(frozen=True)
class MinHashLSHModel(_LSHModelBase):
    """``coef_a``/``coef_b``: (num_hash_tables,) ints of Spark's hash
    family; hash = min over non-zero indices j of
    ((1 + j)·a + b) mod HASH_PRIME."""

    coef_a: np.ndarray
    coef_b: np.ndarray

    def _hashes(self, x: np.ndarray) -> np.ndarray:
        if (x < 0).any():
            raise ValueError("MinHashLSH input must be non-negative (binary)")
        active = x > 0
        if not active.any(axis=1).all():
            raise ValueError(
                "MinHashLSH: every row needs at least one non-zero entry "
                "(Spark raises on empty sets too)"
            )
        d = x.shape[1]
        j = np.arange(1, d + 1, dtype=np.int64)
        # (T, d) per-index hash values — EXACT int64 modular arithmetic
        # (residue products reach ~2^62; see module docstring for why
        # this stays on host)
        table = (j[None, :] * self.coef_a[:, None] + self.coef_b[:, None]) % _MINHASH_PRIME
        big = np.int64(_MINHASH_PRIME)  # sentinel > any residue
        out = np.empty((x.shape[0], table.shape[0]), np.int64)
        for t in range(table.shape[0]):   # T is small (hash tables, not data)
            out[:, t] = np.where(active, table[t][None, :], big).min(axis=1)
        return out

    def _distances(self, xa: np.ndarray, xb: np.ndarray) -> np.ndarray:
        a, b = xa > 0, xb > 0
        inter = (a & b).sum(axis=1)
        union = (a | b).sum(axis=1)
        return 1.0 - inter / np.maximum(union, 1)

    def _artifacts(self):
        return (
            "MinHashLSHModel",
            {},
            {
                "coef_a": np.asarray(self.coef_a, np.int64),
                "coef_b": np.asarray(self.coef_b, np.int64),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coef_a=np.asarray(arrays["coef_a"], np.int64),
            coef_b=np.asarray(arrays["coef_b"], np.int64),
        )


@dataclass(frozen=True)
class MinHashLSH:
    """Spark params: ``num_hash_tables`` (default 1), ``seed``.  Input
    rows are treated as sets: the indices of the non-zero entries."""

    num_hash_tables: int = 1
    seed: int = 0

    def fit(self, data, label_col=None, mesh=None) -> MinHashLSHModel:
        if self.num_hash_tables < 1:
            raise ValueError(
                f"num_hash_tables must be >= 1, got {self.num_hash_tables}"
            )
        _ = _as_matrix(data).shape[1]  # validates rectangular numeric input
        rng = np.random.default_rng(self.seed)
        return MinHashLSHModel(
            coef_a=rng.integers(1, _MINHASH_PRIME, size=self.num_hash_tables),
            coef_b=rng.integers(0, _MINHASH_PRIME, size=self.num_hash_tables),
        )
