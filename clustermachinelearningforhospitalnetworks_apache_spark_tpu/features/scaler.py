"""StandardScaler — fit/transform with mean/std, computed on device.

The reference itself never scales (it feeds raw columns to MLlib), but the
BASELINE north star names ``StandardScaler`` in the k=256 feature path
(BASELINE.json: "StandardScaler+VectorAssembler"), so it is first-class
here.  The fit is one weighted ``psum``-reduced moment pass over the
sharded rows — the same shape of reduction MLlib's ``StandardScaler`` runs
via treeAggregate.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset


def _is_assembled(data) -> bool:
    """True for AssembledTable.  A bare ``hasattr(data, "to_device")``
    misfires on numpy≥2 ndarrays, whose array-API ``to_device`` method
    takes a device argument."""
    from .assembler import AssembledTable

    return isinstance(data, AssembledTable)


@jax.jit
def _moments(x: jax.Array, w: jax.Array):
    wcol = w[:, None]
    n = jnp.sum(w)
    s1 = jnp.sum(x * wcol, axis=0)
    s2 = jnp.sum(x * x * wcol, axis=0)
    mean = s1 / jnp.maximum(n, 1.0)
    var = s2 / jnp.maximum(n, 1.0) - mean * mean
    return mean, jnp.sqrt(jnp.maximum(var, 0.0)), n


@register_model("StandardScalerModel")
@dataclass(frozen=True)
class StandardScalerModel:
    mean: np.ndarray
    std: np.ndarray
    with_mean: bool = True
    with_std: bool = True

    def _artifacts(self):
        return (
            "StandardScalerModel",
            {"with_mean": self.with_mean, "with_std": self.with_std},
            {"mean": np.asarray(self.mean), "std": np.asarray(self.std)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            arrays["mean"],
            arrays["std"],
            bool(params.get("with_mean", True)),
            bool(params.get("with_std", True)),
        )

    def transform(self, x):
        if _is_assembled(x):
            # AssembledTable in → AssembledTable out (scaled features, source
            # table kept) so scaler stages compose inside a Pipeline chain.
            from dataclasses import replace

            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            return self.transform_dataset(x)
        xp = jnp if isinstance(x, jax.Array) else np
        out = x
        # explicit [None, :] broadcasts keep jax_numpy_rank_promotion="raise"
        # (the test sanitizer) happy on 2-D inputs
        expand = getattr(out, "ndim", 1) == 2
        if self.with_mean:
            mean = xp.asarray(self.mean, dtype=out.dtype)
            out = out - (mean[None, :] if expand else mean)
        if self.with_std:
            safe = xp.where(xp.asarray(self.std) > 0, xp.asarray(self.std), 1.0)
            safe = safe.astype(out.dtype)
            out = out / (safe[None, :] if expand else safe)
        return out

    def transform_dataset(self, ds: DeviceDataset) -> DeviceDataset:
        # Pad rows are zeros; re-zero them after the affine shift so they
        # stay inert for weighted reductions downstream.
        x = self.transform(ds.x) * (ds.w[:, None] > 0)
        return DeviceDataset(x=x, y=ds.y, w=ds.w)


@dataclass(frozen=True)
class StandardScaler:
    with_mean: bool = True
    with_std: bool = True

    def fit(self, data) -> StandardScalerModel:
        """``data``: DeviceDataset (sharded), AssembledTable, or ndarray."""
        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            mean, std, _ = _moments(data.x, data.w)
            mean, std = np.asarray(mean), np.asarray(std)
        else:
            x = np.asarray(data, dtype=np.float64)
            mean = x.mean(axis=0)
            std = x.std(axis=0)
        return StandardScalerModel(mean, std, self.with_mean, self.with_std)

    def fit_transform(self, data):
        """Fit then transform in one call.  A DeviceDataset (or
        AssembledTable) comes back as a DeviceDataset with the feature
        matrix scaled and labels/weights carried through; an ndarray comes
        back as an ndarray."""
        if _is_assembled(data):
            data = data.to_device()
        model = self.fit(data)
        if isinstance(data, DeviceDataset):
            return DeviceDataset(model.transform(data.x), data.y, data.w)
        return model.transform(np.asarray(data, dtype=np.float64))
