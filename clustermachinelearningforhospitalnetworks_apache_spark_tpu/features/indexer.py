"""StringIndexer — categorical string column → dense integer codes.

The reference imports ``StringIndexer`` but never uses it
(``mllearnforhospitalnetwork.py:29``; SURVEY.md D5 reads it as intended
categorical handling for ``hospital_id``).  Provided here as a working
stage: frequency-ordered label assignment, matching Spark's default
``frequencyDesc`` ordering, with deterministic lexicographic tie-break.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model


@register_model("StringIndexerModel")
@dataclass(frozen=True)
class StringIndexerModel:
    input_col: str
    output_col: str
    labels: tuple[str, ...]
    handle_invalid: str = "error"  # "error" | "keep" | "skip"

    def _artifacts(self):
        return (
            "StringIndexerModel",
            {
                "input_col": self.input_col,
                "output_col": self.output_col,
                "labels": list(self.labels),
                "handle_invalid": self.handle_invalid,
            },
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            params["input_col"],
            params["output_col"],
            tuple(params["labels"]),
            params.get("handle_invalid", "error"),
        )

    def transform(self, table: Table) -> Table:
        lut = {v: i for i, v in enumerate(self.labels)}
        vals = table.column(self.input_col)
        out = np.empty(len(vals), dtype=np.int64)
        invalid = []
        for i, v in enumerate(vals):
            code = lut.get(v)
            if code is None:
                if self.handle_invalid == "error":
                    raise ValueError(f"unseen label {v!r} in {self.input_col}")
                code = len(self.labels)  # "keep": extra bucket
                invalid.append(i)
            out[i] = code
        t = table.with_column(self.output_col, out, dtype="int")
        if self.handle_invalid == "skip" and invalid:
            keep = np.ones(len(t), dtype=bool)
            keep[invalid] = False
            t = t.mask(keep)
        return t


@dataclass(frozen=True)
class StringIndexer:
    input_col: str
    output_col: str
    handle_invalid: str = "error"

    def fit(self, table: Table) -> StringIndexerModel:
        vals, counts = np.unique(table.column(self.input_col).astype(str), return_counts=True)
        order = np.lexsort((vals, -counts))  # freq desc, then lexicographic
        return StringIndexerModel(
            self.input_col, self.output_col, tuple(vals[order].tolist()), self.handle_invalid
        )
