"""VectorAssembler — column list → dense feature matrix.

Parity with ``pyspark.ml.feature.VectorAssembler`` at reference
``mllearnforhospitalnetwork.py:135-136,:179`` (4 numeric input columns →
``features`` vector).  On TPU "a vector column" is simply a column-stacked
matrix; assembly is a host-side ``np.stack`` (or a device-side
``jnp.stack`` when the columns are already on device), after which the
matrix flows to the mesh in one transfer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model


@register_model("VectorAssembler")
@dataclass(frozen=True)
class VectorAssembler:
    input_cols: Sequence[str]
    output_col: str = "features"

    def _artifacts(self):
        return (
            "VectorAssembler",
            {"input_cols": list(self.input_cols), "output_col": self.output_col},
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(tuple(params["input_cols"]), params.get("output_col", "features"))

    def transform_matrix(self, table: Table, dtype=np.float64) -> np.ndarray:
        """The matrix itself — the form every estimator consumes."""
        return table.numeric_matrix(list(self.input_cols), dtype=dtype)

    def transform(self, table: Table) -> "AssembledTable":
        return AssembledTable(
            table=table,
            feature_cols=tuple(self.input_cols),
            features=self.transform_matrix(table),
            output_col=self.output_col,
        )


@dataclass(frozen=True)
class AssembledTable:
    """A table plus its assembled feature matrix.

    Mirrors the reference's ``final_data = output.select("features",
    "length_of_stay")`` (:137) hand-off, keeping the source table alongside
    so downstream stages (labels, ids, plotting) can still reach raw
    columns.
    """

    table: Table
    feature_cols: tuple[str, ...]
    features: np.ndarray
    output_col: str = "features"

    def __len__(self) -> int:
        return len(self.table)

    def label(self, name: str) -> np.ndarray:
        return self.table.column(name).astype(np.float64)

    def to_device(self, label_col: str | None = None, mesh=None, weight_col: str | None = None):
        from ..core.schema import LABEL_COL
        from ..parallel.sharding import device_dataset

        # The label rides along by default (Spark's transform output keeps
        # the label column next to `prediction`, reference :148,:163): fall
        # back to the canonical LOS label when the table carries it, so
        # `model.transform(assembled)` → evaluator never silently compares
        # against zeros.
        if label_col is None and LABEL_COL in self.table.schema:
            label_col = LABEL_COL
        y = self.label(label_col) if label_col else None
        # weight_col (Spark's weightCol): per-row sample weights folded
        # into the validity column
        w = None
        if weight_col:
            if weight_col not in self.table.schema:
                raise KeyError(
                    f"weight_col {weight_col!r} is not a column of the "
                    f"table; available: {self.table.schema.names}"
                )
            w = self.table.column(weight_col).astype(np.float64)
        return device_dataset(self.features, y, mesh=mesh, weights=w)
