"""VectorAssembler — column list → dense feature matrix.

Parity with ``pyspark.ml.feature.VectorAssembler`` at reference
``mllearnforhospitalnetwork.py:135-136,:179`` (4 numeric input columns →
``features`` vector).  On TPU "a vector column" is simply a column-stacked
matrix; assembly is a host-side ``np.stack`` (or a device-side
``jnp.stack`` when the columns are already on device), after which the
matrix flows to the mesh in one transfer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model


@register_model("VectorAssembler")
@dataclass(frozen=True)
class VectorAssembler:
    input_cols: Sequence[str]
    output_col: str = "features"

    def _artifacts(self):
        return (
            "VectorAssembler",
            {"input_cols": list(self.input_cols), "output_col": self.output_col},
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(tuple(params["input_cols"]), params.get("output_col", "features"))

    def transform_matrix(self, table: Table, dtype=np.float64) -> np.ndarray:
        """The matrix itself — the form every estimator consumes."""
        return table.numeric_matrix(list(self.input_cols), dtype=dtype)

    def transform(self, table: Table) -> "AssembledTable":
        return AssembledTable(
            table=table,
            feature_cols=tuple(self.input_cols),
            features=self.transform_matrix(table),
            output_col=self.output_col,
        )

    def transform_device(
        self,
        view,
        label_col: str | None = None,
        mesh=None,
        na_drop: bool = True,
        compact: bool = False,
    ):
        """Fused assembly (ISSUE 7): a compiled row-level query result
        (:class:`~..core.sql_compile.DeviceView`) → a mesh-ready
        :class:`~..parallel.sharding.DeviceDataset` WITHOUT touching the
        host.  The filter mask becomes the validity weight column and
        ``na_drop`` folds Spark's ``na.drop()`` over the feature/label
        columns into the same kernel (invalid rows stay in place, zeroed,
        weight 0 — the pad-and-weight training contract), so the
        SQL-window → assemble → fit chain never round-trips through
        host numpy.
        """
        import jax

        from ..core.schema import LABEL_COL
        from ..parallel.mesh import DATA_AXIS, default_mesh
        from ..parallel.partitioner import family as _partitioner_family
        from ..parallel.sharding import DeviceDataset

        if label_col is None and LABEL_COL in view.out_names:
            label_col = LABEL_COL
        x, y, w = view.assemble(
            self.input_cols, label_col=label_col, na_drop=na_drop
        )
        if compact:
            # OPT-IN (decision record): one O(1) host sync (the
            # valid-row count) plus an on-device gather moves the valid
            # rows into their own power-of-two bucket, so a highly
            # selective filter's fit stops paying for masked-out rows.
            # Default OFF: on the CPU proxy the gather costs more than
            # it saves (XLA:CPU scatter 74 ms / searchsorted 39 ms for a
            # 524k→262k compaction vs ~20 ms of fit savings at d=4).
            # Adjudication rule, PR 5 style: flip the default if a
            # fenced TPU sweep shows compact=True ≥1.05× end-to-end on
            # `bench.py sql_device` at ≤50% selectivity.
            from ..core.sql_compile import bucket_for_rows, compact_dataset

            n_valid = int(float(jax.device_get((w > 0).sum())))
            out_bucket = bucket_for_rows(max(n_valid, 1))
            if out_bucket < x.shape[0]:
                x, y, w = compact_dataset(x, y, w, out_bucket)
        mesh = mesh or default_mesh()
        if mesh.size > 1 and x.shape[0] % mesh.shape[DATA_AXIS] == 0:
            # power-of-two bucket, power-of-two data axis: the bucket is
            # already divisible, so this is a pure device-to-device
            # resharding (no host round trip)
            _pt = _partitioner_family("rows")
            x = _pt.put("batch/x", x, mesh)
            y = _pt.put("batch/y", y, mesh)
            w = _pt.put("batch/w", w, mesh)
        return DeviceDataset(x=x, y=y, w=w)


@dataclass(frozen=True)
class AssembledTable:
    """A table plus its assembled feature matrix.

    Mirrors the reference's ``final_data = output.select("features",
    "length_of_stay")`` (:137) hand-off, keeping the source table alongside
    so downstream stages (labels, ids, plotting) can still reach raw
    columns.
    """

    table: Table
    feature_cols: tuple[str, ...]
    features: np.ndarray
    output_col: str = "features"

    def __len__(self) -> int:
        return len(self.table)

    def label(self, name: str) -> np.ndarray:
        return self.table.column(name).astype(np.float64)

    def to_device(self, label_col: str | None = None, mesh=None, weight_col: str | None = None):
        from ..core.schema import LABEL_COL
        from ..parallel.sharding import device_dataset

        # The label rides along by default (Spark's transform output keeps
        # the label column next to `prediction`, reference :148,:163): fall
        # back to the canonical LOS label when the table carries it, so
        # `model.transform(assembled)` → evaluator never silently compares
        # against zeros.
        if label_col is None and LABEL_COL in self.table.schema:
            label_col = LABEL_COL
        y = self.label(label_col) if label_col else None
        # weight_col (Spark's weightCol): per-row sample weights folded
        # into the validity column
        w = None
        if weight_col:
            if weight_col not in self.table.schema:
                raise KeyError(
                    f"weight_col {weight_col!r} is not a column of the "
                    f"table; available: {self.table.schema.names}"
                )
            w = self.table.column(weight_col).astype(np.float64)
        return device_dataset(self.features, y, mesh=mesh, weights=w)
