from .assembler import AssembledTable, VectorAssembler
from .scaler import StandardScaler, StandardScalerModel
from .indexer import StringIndexer, StringIndexerModel
from .binarizer import Binarizer
from .bucketizer import Bucketizer
from .discretizer import QuantileDiscretizer
from .imputer import Imputer, ImputerModel
from .minmax import MinMaxScaler, MinMaxScalerModel
from .onehot import OneHotEncoder, OneHotEncoderModel
from .normalizer import IndexToString, Normalizer, PolynomialExpansion
from .pca import PCA, PCAModel
from .robust import (
    MaxAbsScaler,
    MaxAbsScalerModel,
    RobustScaler,
    RobustScalerModel,
)
from .selector import (
    ChiSqSelector,
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
    VectorIndexer,
    VectorIndexerModel,
)
from .lsh import (
    BucketedRandomProjectionLSH,
    BucketedRandomProjectionLSHModel,
    MinHashLSH,
    MinHashLSHModel,
)
from .sql_transformer import SQLTransformer
from .text import (
    CountVectorizer,
    CountVectorizerModel,
    DCT,
    HashingTF,
    IDF,
    IDFModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
)
from .vector_ops import ElementwiseProduct, Interaction, VectorSlicer
from .rformula import RFormula, RFormulaModel, VectorSizeHint
from .word2vec import FeatureHasher, Word2Vec, Word2VecModel

__all__ = [
    "AssembledTable",
    "VectorAssembler",
    "StandardScaler",
    "StandardScalerModel",
    "StringIndexer",
    "StringIndexerModel",
    "Binarizer",
    "Bucketizer",
    "QuantileDiscretizer",
    "Imputer",
    "ImputerModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "IndexToString",
    "Normalizer",
    "PolynomialExpansion",
    "PCA",
    "PCAModel",
    "ChiSqSelector",
    "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel",
    "VectorIndexer",
    "VectorIndexerModel",
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "RobustScaler",
    "RobustScalerModel",
    "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel",
    "BucketedRandomProjectionLSH",
    "BucketedRandomProjectionLSHModel",
    "MinHashLSH",
    "MinHashLSHModel",
    "SQLTransformer",
    "CountVectorizer",
    "CountVectorizerModel",
    "DCT",
    "HashingTF",
    "IDF",
    "IDFModel",
    "NGram",
    "RegexTokenizer",
    "StopWordsRemover",
    "Tokenizer",
    "ElementwiseProduct",
    "Interaction",
    "VectorSlicer",
    "FeatureHasher",
    "RFormula",
    "RFormulaModel",
    "VectorSizeHint",
    "Word2Vec",
    "Word2VecModel",
]
