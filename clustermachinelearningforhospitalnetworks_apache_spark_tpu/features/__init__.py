from .assembler import AssembledTable, VectorAssembler
from .scaler import StandardScaler, StandardScalerModel
from .indexer import StringIndexer, StringIndexerModel
from .binarizer import Binarizer

__all__ = [
    "AssembledTable",
    "VectorAssembler",
    "StandardScaler",
    "StandardScalerModel",
    "StringIndexer",
    "StringIndexerModel",
    "Binarizer",
]
