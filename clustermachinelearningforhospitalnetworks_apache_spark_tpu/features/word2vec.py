"""Word2Vec + FeatureHasher (``pyspark.ml.feature``).

Word2Vec: skip-gram with negative sampling (Mikolov et al.) — Spark
trains skip-gram with hierarchical softmax over RDD partitions; SGNS is
the standard modern equivalent and maps onto the accelerator as pure
batched matmul work.  The host builds the (center, context) pair table
once from the token lists (string work stays on host); training runs as
one jitted ``lax.scan`` over shuffled pair minibatches — each step is an
embedding gather, a dot product against 1 positive + k sampled negatives
(one batched matmul), and a sigmoid loss gradient, all on device.

``transform`` averages word vectors per document (Spark's document
embedding rule: mean of found tokens, zeros when none found);
``find_synonyms`` ranks by cosine similarity.

Deliberate scale limitation (VERDICT r4 weak #5): training is
SINGLE-DEVICE by design — the pair table and the (V, d) embedding
matrices live on one chip, which covers vocabularies to ~10⁶ terms at
d=100 with room to spare (2·V·d f32 ≈ 0.8 GB).  Spark distributes its
Word2Vec because JVM executors are memory-poor, then averages per-
partition models — a scheme known to degrade embedding quality; one
accelerator with the full matrices is both faster and more faithful at
every scale the reference's data could reach.  Sharding the vocabulary
axis would only pay past ~10⁷ terms.

FeatureHasher: Spark's row-dict hasher — numeric values accumulate at
``hash(col) % F`` with their value, string/categorical values accumulate
1.0 at ``hash(col + '=' + value) % F``; CRC32 keeps it process-stable.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from functools import cached_property, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .text import HashingTF, _tokens_column


@partial(jax.jit, static_argnames=("batch", "neg", "steps"))
def _sgns_train(emb_in, emb_out, centers, contexts, negatives, lr, batch: int,
                neg: int, steps: int):
    """Skip-gram negative-sampling SGD over pre-drawn pair minibatches.

    centers/contexts: (steps·batch,) int32; negatives: (steps·batch, neg).
    Per step: gather embeddings, one batched (B, 1+neg) score matmul,
    sigmoid-loss gradients scattered back — the classic SGNS update with
    everything resident on device.
    """

    def step(carry, i):
        ein, eout = carry
        sl = i * batch
        c = lax.dynamic_slice_in_dim(centers, sl, batch)
        pos = lax.dynamic_slice_in_dim(contexts, sl, batch)
        negs = lax.dynamic_slice_in_dim(negatives, sl, batch)
        targets = jnp.concatenate([pos[:, None], negs], axis=1)   # (B, 1+neg)
        labels = jnp.concatenate(
            [jnp.ones((batch, 1)), jnp.zeros((batch, neg))], axis=1
        ).astype(jnp.float32)

        v = ein[c]                                # (B, d)
        u = eout[targets]                         # (B, 1+neg, d)
        scores = jnp.einsum("bd,bkd->bk", v, u)
        g = (jax.nn.sigmoid(scores) - labels) / batch   # mean-loss scaling:
        # scatter-adds SUM duplicate-index grads, so the per-step update
        # must be the batch MEAN or the effective lr multiplies by B and
        # the embeddings blow up along a shared direction
        grad_v = jnp.einsum("bk,bkd->bd", g, u)
        grad_u = g[:, :, None] * v[:, None, :]
        ein = ein.at[c].add(-lr * grad_v)
        eout = eout.at[targets.reshape(-1)].add(
            -lr * grad_u.reshape(-1, v.shape[1])
        )
        return (ein, eout), None

    (emb_in, emb_out), _ = lax.scan(
        step, (emb_in, emb_out), jnp.arange(steps)
    )
    return emb_in, emb_out


@register_model("Word2VecModel")
@dataclass
class Word2VecModel:
    vocabulary: tuple
    vectors: np.ndarray              # (|vocab|, d)

    @cached_property
    def _index(self) -> dict:
        """token → row, built once (transform is called per batch)."""
        return {t: i for i, t in enumerate(self.vocabulary)}

    @property
    def vector_size(self) -> int:
        return self.vectors.shape[1]

    def get_vectors(self) -> dict:
        return {t: self.vectors[i] for i, t in enumerate(self.vocabulary)}

    def transform(self, tokens) -> np.ndarray:
        """(n, d) document embeddings: mean of found token vectors
        (Spark's rule; all-unknown documents embed to zeros)."""
        index = self._index
        rows = _tokens_column(tokens)
        out = np.zeros((len(rows), self.vector_size), np.float32)
        for i, row in enumerate(rows):
            ids = [index[t] for t in row if t in index]
            if ids:
                out[i] = self.vectors[ids].mean(axis=0)
        return out

    def find_synonyms(self, word: str, num: int = 5):
        """[(term, cosine similarity), ...] excluding the query word."""
        index = self._index
        if word not in index:
            raise KeyError(f"{word!r} is not in the fitted vocabulary")
        v = self.vectors[index[word]]
        norms = np.linalg.norm(self.vectors, axis=1) * max(
            np.linalg.norm(v), 1e-12
        )
        sims = self.vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(sims)[::-1]
        out = []
        for j in order:
            if self.vocabulary[j] != word:
                out.append((self.vocabulary[j], float(sims[j])))
            if len(out) == num:
                break
        return out

    def _artifacts(self):
        return (
            "Word2VecModel",
            {"vocabulary": list(self.vocabulary)},
            {"vectors": np.asarray(self.vectors)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(vocabulary=tuple(params["vocabulary"]), vectors=arrays["vectors"])


@dataclass(frozen=True)
class Word2Vec:
    """Spark defaults where they transfer: vectorSize 100, windowSize 5,
    minCount 5, maxIter 1.  ``step_size`` applies to batch-MEAN gradients
    (Spark's 0.025 is a per-pair SGD rate; the equivalent mean-batch rate
    is larger), ``num_negatives`` is the SGNS sample count (Spark's
    hierarchical softmax has no analogue knob)."""

    vector_size: int = 100
    window_size: int = 5
    min_count: int = 5
    max_iter: int = 1
    step_size: float = 0.5
    num_negatives: int = 5
    batch_size: int = 1024
    seed: int = 0

    def fit(self, tokens) -> Word2VecModel:
        if self.vector_size < 1:
            raise ValueError(f"vector_size must be >= 1, got {self.vector_size}")
        if self.max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {self.max_iter}")
        if self.window_size < 1:
            raise ValueError(f"window_size must be >= 1, got {self.window_size}")
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.num_negatives < 1:
            raise ValueError(
                f"num_negatives must be >= 1, got {self.num_negatives}"
            )
        rows = _tokens_column(tokens)
        counts: dict[str, int] = {}
        for row in rows:
            for t in row:
                counts[t] = counts.get(t, 0) + 1
        vocab = sorted(
            (t for t, c in counts.items() if c >= self.min_count),
            key=lambda t: (-counts[t], t),
        )
        if not vocab:
            raise ValueError(
                f"no token reaches min_count={self.min_count}; vocabulary empty"
            )
        index = {t: i for i, t in enumerate(vocab)}
        v = len(vocab)

        # host pass: (center, context) pairs within the window
        centers, contexts = [], []
        for row in rows:
            ids = [index[t] for t in row if t in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window_size)
                hi = min(len(ids), i + self.window_size + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("no skip-gram pairs (documents too short)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        # unigram^0.75 negative-sampling table (Mikolov's distribution)
        freq = np.asarray([counts[t] for t in vocab], np.float64) ** 0.75
        p_neg = freq / freq.sum()

        rng = np.random.default_rng(self.seed)
        d = self.vector_size
        emb_in = jnp.asarray(
            rng.uniform(-0.5 / d, 0.5 / d, size=(v, d)).astype(np.float32)
        )
        emb_out = jnp.zeros((v, d), jnp.float32)

        n_pairs = len(centers)
        batch = min(self.batch_size, n_pairs)
        for _ in range(self.max_iter):
            perm = rng.permutation(n_pairs)
            # ceil-div + wrap-around fill: the shuffled tail trains too
            # (truncating would silently drop up to batch−1 pairs/epoch)
            steps = -(-n_pairs // batch)
            take = np.resize(perm, steps * batch)
            negs = rng.choice(
                v, size=(steps * batch, self.num_negatives), p=p_neg
            ).astype(np.int32)
            emb_in, emb_out = _sgns_train(
                emb_in, emb_out,
                jnp.asarray(centers[take]), jnp.asarray(contexts[take]),
                jnp.asarray(negs), jnp.float32(self.step_size),
                batch, self.num_negatives, steps,
            )
        return Word2VecModel(
            vocabulary=tuple(vocab),
            vectors=np.asarray(jax.device_get(emb_in)),
        )


@register_model("FeatureHasher")
@dataclass(frozen=True)
class FeatureHasher:
    """Hash mixed-type row dicts into a fixed-width vector (Spark's
    semantics: numeric columns land at hash(col) with their value,
    string/bool values at hash(col=value) with 1.0)."""

    num_features: int = 1 << 18
    # ONE budget policy for every dense hasher (shared with HashingTF)
    _MAX_DENSE_ELEMS = HashingTF._MAX_DENSE_ELEMS

    def __post_init__(self):
        if self.num_features < 1:
            raise ValueError(f"num_features must be >= 1, got {self.num_features}")

    def _artifacts(self):
        return ("FeatureHasher", {"num_features": self.num_features}, {})

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(num_features=int(params["num_features"]))

    def transform(self, rows) -> np.ndarray:
        """``rows``: iterable of {column: value} dicts (or a Table, whose
        rows are hashed column-wise)."""
        from ..core.table import Table

        if isinstance(rows, Table):
            cols = {c: rows.column(c) for c in rows.columns}
            rows = [
                {c: cols[c][i] for c in cols} for i in range(len(rows))
            ]
        rows = list(rows)
        if len(rows) * self.num_features > self._MAX_DENSE_ELEMS:
            raise ValueError(
                f"dense FeatureHasher output {len(rows)}×{self.num_features} "
                f"exceeds the element budget; lower num_features"
            )
        out = np.zeros((len(rows), self.num_features), np.float32)
        for i, row in enumerate(rows):
            if not isinstance(row, dict):
                raise TypeError(
                    f"FeatureHasher rows must be dicts; got {type(row).__name__}"
                )
            for col, val in row.items():
                # nulls contribute nothing (Spark ignores missing values)
                if val is None or (
                    isinstance(val, (float, np.floating)) and np.isnan(val)
                ):
                    continue
                if isinstance(val, (bool, np.bool_, str, np.str_)):
                    j = zlib.crc32(f"{col}={val}".encode()) % self.num_features
                    out[i, j] += 1.0
                else:
                    j = zlib.crc32(str(col).encode()) % self.num_features
                    out[i, j] += float(val)
        return out
