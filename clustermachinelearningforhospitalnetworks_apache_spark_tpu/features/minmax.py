"""MinMaxScaler — per-feature rescale to [min, max].

Parity with ``pyspark.ml.feature.MinMaxScaler``: fit finds per-column
(min, max) over the data, transform maps linearly onto
``[min_out, max_out]``; a constant column maps every value to the midpoint
``(min_out + max_out) / 2`` (Spark's rule).  The fit is one fused, jit'd
masked min/max reduction over the sharded rows — pad/zero-weight rows are
excluded via ±inf masking, the same way the mean/std scaler excludes them
by weighting.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.reductions import moment_stats
from ..parallel.sharding import DeviceDataset
from .scaler import _is_assembled


@register_model("MinMaxScalerModel")
@dataclass(frozen=True)
class MinMaxScalerModel:
    data_min: np.ndarray
    data_max: np.ndarray
    min_out: float = 0.0
    max_out: float = 1.0

    def _artifacts(self):
        return (
            "MinMaxScalerModel",
            {"min_out": self.min_out, "max_out": self.max_out},
            {"data_min": np.asarray(self.data_min), "data_max": np.asarray(self.data_max)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            arrays["data_min"], arrays["data_max"],
            float(params.get("min_out", 0.0)), float(params.get("max_out", 1.0)),
        )

    def transform(self, x):
        if _is_assembled(x):
            return replace(x, features=self.transform(x.features))
        if isinstance(x, DeviceDataset):
            scaled = self.transform(x.x) * (x.w[:, None] > 0)
            return DeviceDataset(x=scaled, y=x.y, w=x.w)
        xp = jnp if isinstance(x, jax.Array) else np
        lo = xp.asarray(self.data_min, dtype=x.dtype)
        hi = xp.asarray(self.data_max, dtype=x.dtype)
        span = hi - lo
        out_span = self.max_out - self.min_out
        # constant column → midpoint (Spark rule); guard the 0-div first
        safe = xp.where(span > 0, span, 1.0)
        scaled = (x - lo[None, :]) / safe[None, :] * out_span + self.min_out
        mid = 0.5 * (self.min_out + self.max_out)
        return xp.where((span > 0)[None, :], scaled, mid)


@dataclass(frozen=True)
class MinMaxScaler:
    min_out: float = 0.0   # Spark's min
    max_out: float = 1.0   # Spark's max

    def fit(self, data) -> MinMaxScalerModel:
        if _is_assembled(data):
            data = data.to_device()
        if isinstance(data, DeviceDataset):
            s = moment_stats(data.x, data.w)
            lo, hi = np.asarray(s["min"]), np.asarray(s["max"])
        else:
            x = np.asarray(data, dtype=np.float64)
            lo, hi = x.min(axis=0), x.max(axis=0)
        return MinMaxScalerModel(lo, hi, self.min_out, self.max_out)

    def fit_transform(self, data):
        # transform the ORIGINAL container so the return type matches
        # fit(data).transform(data) (AssembledTable in → AssembledTable out)
        return self.fit(data).transform(data)
