"""OneHotEncoder — integer category column(s) → indicator columns.

Parity with ``pyspark.ml.feature.OneHotEncoder``: fit learns each input
column's category count (max code + 1); transform appends one 0/1 column
per category, named ``<output_col>_<i>``.  ``drop_last=True`` (Spark's
default) omits the final category so the encoding stays full-rank for
linear models.  Appending named scalar columns (rather than a packed
vector type) is the columnar-Table equivalent of Spark's sparse vector —
``VectorAssembler`` then stacks exactly the indicator columns a model
needs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model


@register_model("OneHotEncoderModel")
@dataclass(frozen=True)
class OneHotEncoderModel:
    input_cols: tuple[str, ...]
    output_cols: tuple[str, ...]
    category_sizes: tuple[int, ...]
    drop_last: bool = True
    handle_invalid: str = "error"  # "error" | "keep" (Spark's vocabulary)

    def __post_init__(self):
        if self.handle_invalid not in ("error", "keep"):
            raise ValueError(
                f"handle_invalid must be error|keep, got "
                f"{self.handle_invalid!r} (Spark's OneHotEncoder has no 'skip')"
            )

    def _artifacts(self):
        return (
            "OneHotEncoderModel",
            {
                "input_cols": list(self.input_cols),
                "output_cols": list(self.output_cols),
                "category_sizes": list(self.category_sizes),
                "drop_last": self.drop_last,
                "handle_invalid": self.handle_invalid,
            },
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            tuple(params["input_cols"]),
            tuple(params["output_cols"]),
            tuple(int(s) for s in params["category_sizes"]),
            bool(params.get("drop_last", True)),
            params.get("handle_invalid", "error"),
        )

    def _effective_size(self, col_index: int) -> int:
        # Spark: handleInvalid="keep" ADDS an invalid bucket as the last
        # category, so dropLast then drops the invalid bucket — every valid
        # category keeps its indicator and invalid rows become all-zeros
        # (or, with dropLast=False, get their own indicator column).
        size = self.category_sizes[col_index]
        return size + 1 if self.handle_invalid == "keep" else size

    def output_names(self, col_index: int) -> list[str]:
        eff = self._effective_size(col_index)
        emitted = eff - 1 if self.drop_last else eff
        return [f"{self.output_cols[col_index]}_{i}" for i in range(emitted)]

    def transform(self, table: Table) -> Table:
        out = table
        for ci, (ic, size) in enumerate(zip(self.input_cols, self.category_sizes)):
            codes = out.column(ic).astype(np.int64)
            bad = (codes < 0) | (codes >= size)
            if bad.any():
                if self.handle_invalid == "error":
                    raise ValueError(
                        f"category {int(codes[bad][0])} in {ic!r} is outside "
                        f"[0, {size}) (handle_invalid='error')"
                    )
                codes = np.where(bad, size, codes)  # route to invalid bucket
            for i, name in enumerate(self.output_names(ci)):
                out = out.with_column(
                    name, (codes == i).astype(np.int64), dtype="int"
                )
        return out


@dataclass(frozen=True)
class OneHotEncoder:
    input_cols: Sequence[str]
    output_cols: Sequence[str] | None = None
    drop_last: bool = True     # Spark's dropLast default
    handle_invalid: str = "error"

    def __post_init__(self):
        if self.handle_invalid not in ("error", "keep"):
            raise ValueError(
                f"handle_invalid must be error|keep, got "
                f"{self.handle_invalid!r} (Spark's OneHotEncoder has no 'skip')"
            )

    def fit(self, table: Table) -> OneHotEncoderModel:
        outs = tuple(self.output_cols) if self.output_cols else tuple(
            f"{c}_vec" for c in self.input_cols
        )
        if len(outs) != len(tuple(self.input_cols)):
            raise ValueError("input_cols and output_cols lengths differ")
        sizes = []
        for c in self.input_cols:
            codes = table.column(c).astype(np.int64)
            if codes.size and codes.min() < 0:
                raise ValueError(f"negative category code in {c!r}")
            sizes.append(int(codes.max()) + 1 if codes.size else 0)
        return OneHotEncoderModel(
            tuple(self.input_cols), outs, tuple(sizes),
            self.drop_last, self.handle_invalid,
        )
