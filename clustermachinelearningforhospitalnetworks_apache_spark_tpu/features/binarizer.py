"""Label binarization.

Parity with the reference's classification-label construction at
``mllearnforhospitalnetwork.py:176-177``::

    when(col("length_of_stay") > CONFIG["losThreshold"], 1).otherwise(0)

i.e. strictly-greater-than thresholding at ``losThreshold`` (5.0, :49).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.table import Table
from ..io.model_io import register_model


@register_model("Binarizer")
@dataclass(frozen=True)
class Binarizer:
    input_col: str
    output_col: str
    threshold: float

    def _artifacts(self):
        return (
            "Binarizer",
            {
                "input_col": self.input_col,
                "output_col": self.output_col,
                "threshold": self.threshold,
            },
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(params["input_col"], params["output_col"], float(params["threshold"]))

    def transform(self, table: Table) -> Table:
        v = table.column(self.input_col).astype(np.float64)
        return table.with_column(
            self.output_col, (v > self.threshold).astype(np.int64), dtype="int"
        )
