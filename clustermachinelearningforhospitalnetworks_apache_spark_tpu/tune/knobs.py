"""The knob registry: every hand-set performance constant, declared.

ROADMAP item 2 ("measurement-driven autotuner").  A **knob** is one
tunable performance constant — a micro-batch deadline, a queue bound, a
pipeline depth, a seal chunk size — declared once with its name, its
candidate **domain**, its hand-set **default**, and the obs stage-span /
bench metric that scores it.  Call sites stop owning literals and ask
:func:`knob` instead:

    self.max_wait_s = knob("serve.microbatch.max_wait_ms") / 1e3

With nothing installed, :func:`knob` returns the declared default —
**bit-identical** to the literal it replaced (pinned by
``tests/test_autotune.py::test_migrated_defaults_parity``), so migrating
a call site is behavior-neutral until a selector is installed.  With a
:class:`~.select.Selector` installed (``tune.install`` /
``tune.active``), the lookup routes through the measured-cost model in
``tune/select.py`` — which falls back to the same default when trial
coverage is thin and freezes during fenced A/Bs.

``py_names`` is the contract with the ``untracked-knob`` lint pass
(``tools/lint/passes/knobs.py``): once a constant is registered here,
re-introducing a raw numeric literal under any of those names outside
``tune/`` is a build failure — the same ratchet ``handrolled-sharding``
applies to layout rules.  Keep every registration below a pure literal
call (the lint pass reads this file with ``ast``, never imports it).

Units: knobs named ``*_ms`` are milliseconds; call sites divide by
``1e3``.  Every registered default converts bit-exactly (2.0/1e3 ==
0.002 etc.) so the parity gate stays bit-tight.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable


@dataclass(frozen=True)
class Knob:
    """One tunable: identity, search space, default, and how to score it.

    ``metric`` names the signal that ranks candidate values — either a
    registered obs span (``span:serve.request``) or a bench-reported
    rate (``bench:autotune.seal_scan``).  ``mode`` says which direction
    wins: ``"max"`` for throughput-like metrics, ``"min"`` for
    latencies.  ``py_names`` are the call-site identifiers the
    ``untracked-knob`` lint pass guards (assignment targets and
    parameter names that must no longer carry raw numeric literals).
    """

    name: str
    default: float | int
    domain: tuple = ()
    metric: str = ""
    mode: str = "max"
    py_names: tuple = ()
    description: str = ""

    def __post_init__(self) -> None:
        if self.mode not in ("max", "min"):
            raise ValueError(f"knob {self.name}: mode must be max|min")
        if self.domain and self.default not in self.domain:
            raise ValueError(
                f"knob {self.name}: default {self.default!r} not in domain"
            )


class KnobRegistry:
    """Name → :class:`Knob`.  Registration is declare-once: a second
    ``add`` under the same name must carry an identical declaration
    (idempotent re-import), anything else is a programming error."""

    def __init__(self) -> None:
        self._knobs: dict[str, Knob] = {}
        self._lock = threading.Lock()

    def add(self, knob: Knob) -> Knob:
        with self._lock:
            prev = self._knobs.get(knob.name)
            if prev is not None and prev != knob:
                raise ValueError(
                    f"knob {knob.name!r} re-registered with a different "
                    f"declaration"
                )
            self._knobs[knob.name] = knob
        return knob

    def get(self, name: str) -> Knob:
        try:
            return self._knobs[name]
        except KeyError:
            raise KeyError(f"unregistered knob {name!r}") from None

    def names(self) -> list[str]:
        return sorted(self._knobs)

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def py_name_map(self) -> dict[str, str]:
        """identifier → knob name, for the lint pass and docs table."""
        out: dict[str, str] = {}
        with self._lock:
            knobs = list(self._knobs.values())
        for k in knobs:
            for pn in k.py_names:
                out[pn] = k.name
        return out


#: the process-wide registry every call site resolves through
REGISTRY = KnobRegistry()

#: installed by tune/select.py — ``None`` means "declared defaults"
_RESOLVER: Callable | None = None


def set_resolver(fn: Callable | None) -> None:
    global _RESOLVER
    _RESOLVER = fn


def knob(name: str, shape: int | None = None):
    """Resolve one knob value.

    The no-selector path is two dict lookups and an ``is None`` test —
    cheap enough for ``__init__``-time call sites (hot inner loops
    should resolve once at construction, which is what every migrated
    call site does).  ``shape`` is the workload size hint (rows) the
    selector buckets trials by; without a selector it is ignored.
    """
    k = REGISTRY.get(name)
    r = _RESOLVER
    if r is None:
        return k.default
    return r(k, shape)


def default(name: str):
    """The declared default, bypassing any installed selector — for
    call sites that must never float (compat constants, parity tests)."""
    return REGISTRY.get(name).default


# ---------------------------------------------------------------------------
# The registered knob surface.  Every entry below replaced a hand-set
# literal somewhere in serve/, streaming/, farm/, or core/ — the table in
# docs/ARCHITECTURE.md §Autotuner names each migrated call site.  Keep
# these PURE LITERAL calls: tools/lint/passes/knobs.py reads them by AST.
# ---------------------------------------------------------------------------

REGISTRY.add(Knob(
    name="serve.microbatch.max_wait_ms",
    default=2.0,
    domain=(0.0, 0.5, 1.0, 2.0, 4.0, 8.0),
    metric="span:serve.request",
    mode="max",
    py_names=("max_wait_s", "DEFAULT_MAX_WAIT_S"),
    description="micro-batch linger deadline before a partial batch "
                "dispatches (serve/batcher.py)",
))

REGISTRY.add(Knob(
    name="serve.queue.max_rows",
    default=4096,
    domain=(1024, 2048, 4096, 8192, 16384),
    metric="span:fleet.request",
    mode="max",
    py_names=("max_queue_rows", "max_rows"),
    description="bound on queued rows per server/batcher before "
                "admission sheds (one knob; five diverged copies before)",
))

REGISTRY.add(Knob(
    name="serve.slo.batch.shed_load",
    default=0.45,
    domain=(0.25, 0.35, 0.45, 0.6, 0.8),
    metric="span:fleet.request",
    mode="max",
    py_names=("batch_shed_load",),
    description="queue-load fraction above which the batch SLO class "
                "sheds (serve/fleet/admission.py)",
))

REGISTRY.add(Knob(
    name="serve.slo.best_effort.shed_load",
    default=0.25,
    domain=(0.1, 0.15, 0.25, 0.4, 0.6),
    metric="span:fleet.request",
    mode="max",
    py_names=("best_effort_shed_load",),
    description="queue-load fraction above which best-effort sheds "
                "(serve/fleet/admission.py)",
))

REGISTRY.add(Knob(
    name="stream.pipeline.depth",
    default=2,
    domain=(1, 2, 3, 4, 8),
    metric="span:stream.batch",
    mode="max",
    py_names=("pipeline_depth",),
    description="prefetch pipeline depth: batches in flight ahead of "
                "the driver (streaming/pipeline.py)",
))

REGISTRY.add(Knob(
    name="stream.worker.poll_interval_ms",
    default=50.0,
    domain=(5.0, 10.0, 25.0, 50.0, 100.0),
    metric="span:stream.batch",
    mode="max",
    py_names=("worker_poll_interval_s",),
    description="idle re-list cadence of the prefetch worker "
                "(streaming/pipeline.py)",
))

REGISTRY.add(Knob(
    name="stream.source.max_files_per_batch",
    default=0,
    domain=(0, 2, 4, 8, 16),
    metric="span:stream.batch",
    mode="max",
    py_names=("max_files_per_batch",),
    description="files folded into one micro-batch; 0 = unbounded "
                "(streaming/source.py)",
))

REGISTRY.add(Knob(
    name="sql.stage.min_compiled_rows",
    default=4096,
    domain=(512, 1024, 2048, 4096, 8192, 16384),
    metric="span:sql.query",
    mode="max",
    py_names=("min_compiled_rows",),
    description="batch size below which the SQL feature stage forces "
                "the interpreter (streaming/pipeline.py)",
))

REGISTRY.add(Knob(
    name="sql.rowbucket.min",
    default=256,
    domain=(32, 64, 128, 256, 512, 1024),
    metric="span:sql.query",
    mode="min",
    py_names=("_MIN_BUCKET", "min_bucket"),
    description="floor of the power-of-two row-bucket ladder the "
                "compiled SQL executor pads to (core/sql_compile.py)",
))

REGISTRY.add(Knob(
    name="table.seal.min_batches",
    default=4,
    domain=(2, 4, 8, 16),
    metric="span:table.seal",
    mode="min",
    py_names=("min_seal_batches",),
    description="cold batches worth a segment: fewer seals, larger "
                "segments (core/table_lifecycle.py)",
))

REGISTRY.add(Knob(
    name="table.seal.max_segment_batches",
    default=64,
    domain=(4, 8, 16, 32, 64, 128),
    metric="bench:autotune.seal_scan",
    mode="max",
    py_names=("max_segment_batches",),
    description="batches per sealed segment: smaller segments prune "
                "better on selective scans, larger amortize manifests "
                "(core/table_lifecycle.py)",
))

REGISTRY.add(Knob(
    name="farm.pack.r_floor",
    default=8,
    domain=(2, 4, 8, 16, 32),
    metric="span:farm.fit",
    mode="max",
    py_names=("r_floor",),
    description="floor of the power-of-two tenant-bucket R the farm "
                "pads fleets to (farm/farm.py)",
))
