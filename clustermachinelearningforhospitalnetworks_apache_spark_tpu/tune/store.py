"""Durable trial store: every measurement the selector reasons over.

A **trial** is one scored observation of one knob value:

    {trial_id, knob, value, platform, fingerprint, shape_bucket,
     metric, score, reps, source, meta}

keyed — per the ISSUE contract — by ``(platform, knob,
config-fingerprint, shape-bucket)``.  Trials come from two feeds:
offline sweeps (``tools/autotune.py`` / the ``autotune`` bench config,
which time each candidate under the bench fence discipline) and live
serving stats (``tune/live.py`` records the observed rate at the value
currently deployed).

Durability is the repo's one ladder — tmp → fsync → ``os.replace`` →
dir fsync — under the named fault site ``tune.store.commit``, so the
chaos matrix can kill the commit at the same seam as every other
durable artifact.  ``trial_id`` is a content hash of the trial's
identity fields: re-adding a replayed trial after a killed commit is a
no-op merge, which is what makes the crash story **exactly-once**
(``tests/test_autotune.py`` kills a commit and proves the resumed store
is bit-identical to an uninterrupted one).
"""

from __future__ import annotations

import hashlib
import json
import os

from ..io.fit_checkpoint import fsync_dir
from ..obs.trace import span
from ..utils.faults import fault_point

SCHEMA_VERSION = 1

#: identity fields hashed into ``trial_id`` — two trials that agree on
#: all of these are the same observation and merge to one row
_ID_FIELDS = (
    "knob", "value", "platform", "fingerprint", "shape_bucket",
    "metric", "score", "reps", "source",
)


def shape_bucket(rows: int) -> int:
    """Power-of-two workload-size bucket (min 1) — the shape key trials
    are stored and interpolated under."""
    n, b = max(int(rows), 1), 1
    while b < n:
        b <<= 1
    return b


def trial_id(trial: dict) -> str:
    """Deterministic content hash of the trial's identity fields."""
    key = json.dumps(
        [trial.get(f) for f in _ID_FIELDS], sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def make_trial(
    *,
    knob: str,
    value,
    score: float,
    platform: str = "cpu",
    fingerprint: str = "default",
    shape_rows: int = 1,
    metric: str = "",
    reps: int = 1,
    source: str = "sweep",
    meta: dict | None = None,
) -> dict:
    """Normalize one observation into a keyed, content-addressed trial."""
    t = {
        "knob": str(knob),
        "value": value,
        "platform": str(platform),
        "fingerprint": str(fingerprint),
        "shape_bucket": shape_bucket(shape_rows),
        "metric": str(metric),
        "score": float(score),
        "reps": int(reps),
        "source": str(source),
        "meta": dict(meta or {}),
    }
    t["trial_id"] = trial_id(t)
    return t


class TrialStore:
    """The persisted trial set, merged by ``trial_id``.

    One JSON document (not a log): small — hundreds of trials, not
    millions — and rewritten atomically per commit, so a reader never
    sees a half-merged state and a killed commit leaves either the old
    file or the new one, never a torn mix.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._trials: dict[str, dict] = {}
        self._load()

    # ------------------------------------------------------------- read
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            doc = json.load(f)
        for t in doc.get("trials", []):
            tid = t.get("trial_id")
            if tid:
                self._trials[tid] = t

    def __len__(self) -> int:
        return len(self._trials)

    def trials(
        self,
        *,
        knob: str | None = None,
        platform: str | None = None,
        fingerprint: str | None = None,
    ) -> list[dict]:
        """Trials filtered on the store key, sorted for determinism."""
        out = [
            t for t in self._trials.values()
            if (knob is None or t["knob"] == knob)
            and (platform is None or t["platform"] == platform)
            and (fingerprint is None or t["fingerprint"] == fingerprint)
        ]
        out.sort(key=lambda t: (
            t["knob"], t["shape_bucket"], repr(t["value"]), t["trial_id"],
        ))
        return out

    # ------------------------------------------------------------ write
    def add(self, trials: list[dict]) -> int:
        """Merge trials by content hash and durably commit.

        Returns how many were new.  Replaying the same ``add`` after a
        killed commit merges to the identical document — exactly-once.
        """
        fresh = 0
        for t in trials:
            tid = t.get("trial_id") or trial_id(t)
            t = dict(t, trial_id=tid)
            if tid not in self._trials:
                fresh += 1
            self._trials[tid] = t
        self._commit()
        return fresh

    def _commit(self) -> None:
        doc = {
            "version": SCHEMA_VERSION,
            "trials": [self._trials[k] for k in sorted(self._trials)],
        }
        payload = json.dumps(doc, sort_keys=True, indent=1).encode()
        with span("tune.store", {"trials": len(self._trials)}):
            # the kill lands BEFORE the tmp exists: a crashed commit
            # leaves no litter, only the previous committed document
            fault_point("tune.store.commit", path=self.path)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            fsync_dir(os.path.dirname(os.path.abspath(self.path)))
