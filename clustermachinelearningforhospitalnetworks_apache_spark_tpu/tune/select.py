"""The selector: a cheap interpolating cost model over stored trials.

Flare's discipline (arxiv 1703.08219), applied to knobs: **decide ahead
of the hot path, never during it**.  A :class:`Selector` resolves a
knob to a value once — at construction time of whatever consumes it —
by ranking the store's trials for that ``(platform, knob, fingerprint)``
key at the requested shape bucket.  Scores at absent buckets are
linearly interpolated in log2(bucket) between the nearest measured
buckets (the Spark-ML perf-study shape: model the cost from
measurements, then pick the configuration — arxiv 1612.01437).

Three outcomes, and only three, each named by a PR 6-style reason
constant so every selection is explainable after the fact:

* :data:`REASON_DEFAULT_NO_TRIALS` — coverage is thin (fewer than two
  distinct candidate values measured for the key): the declared default
  wins.  An autotuner with one data point has no gradient; guessing
  would be worse than the hand-set constant.
* ``tuned:<trial-id>`` (:data:`REASON_TUNED_PREFIX`) — the best
  measured value, tagged with the id of the winning trial so the
  decision is auditable back to the measurement that made it.
* :data:`REASON_FROZEN_FENCED` — a fenced A/B is in flight.  The fence
  is **queried, not hoped for**: bench legs run inside
  :func:`ab_fence`, and any resolve during the fence returns the value
  already in effect (last selection, else default) without consulting
  trials — otherwise the autotuner would contaminate the very
  measurement meant to feed it.

``explain()`` returns the last decision per knob: value, reason, trials
considered, shape bucket.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from math import log2
from typing import Iterator

from . import knobs as _knobs
from .knobs import Knob, REGISTRY
from .store import TrialStore, shape_bucket

REASON_DEFAULT_NO_TRIALS = "default:no-trials"
REASON_FROZEN_FENCED = "frozen:fenced-ab"
REASON_TUNED_PREFIX = "tuned:"

# --------------------------------------------------------------- the fence
# One process-global nested counter: bench A/B legs (and anything else
# whose timing must not be perturbed mid-measurement) hold it while a
# leg runs.  Nested fences stack; the selector asks `fence_active()`
# on EVERY resolve.
_FENCE_LOCK = threading.Lock()
_FENCE_DEPTH = 0


@contextmanager
def ab_fence() -> Iterator[None]:
    """Mark a fenced A/B region: no selection happens inside."""
    global _FENCE_DEPTH
    with _FENCE_LOCK:
        _FENCE_DEPTH += 1
    try:
        yield
    finally:
        with _FENCE_LOCK:
            _FENCE_DEPTH -= 1


def fence_active() -> bool:
    return _FENCE_DEPTH > 0


# ------------------------------------------------------------- the selector
class Selector:
    """Trial-backed knob resolution for one (platform, fingerprint)."""

    def __init__(
        self,
        store: TrialStore,
        *,
        platform: str = "cpu",
        fingerprint: str = "default",
        min_distinct_values: int = 2,
    ):
        self.store = store
        self.platform = str(platform)
        self.fingerprint = str(fingerprint)
        self.min_distinct_values = int(min_distinct_values)
        self._lock = threading.Lock()
        self._last: dict[str, dict] = {}   # knob -> last decision record

    # ------------------------------------------------------------ model
    def _score_at(self, by_bucket: dict[int, float], bucket: int) -> float:
        """Score of one candidate value at ``bucket``: exact if
        measured, else linear in log2(bucket) between the nearest
        measured buckets (clamped at the ends)."""
        if bucket in by_bucket:
            return by_bucket[bucket]
        marks = sorted(by_bucket)
        lo = [b for b in marks if b < bucket]
        hi = [b for b in marks if b > bucket]
        if not lo:
            return by_bucket[hi[0]]
        if not hi:
            return by_bucket[lo[-1]]
        b0, b1 = lo[-1], hi[0]
        w = (log2(bucket) - log2(b0)) / (log2(b1) - log2(b0))
        return by_bucket[b0] * (1.0 - w) + by_bucket[b1] * w

    def _rank(self, knob: Knob, bucket: int):
        """Best (value, winning-trial-id, n-trials) for the key, or
        ``None`` when coverage is thin."""
        trials = self.store.trials(
            knob=knob.name, platform=self.platform,
            fingerprint=self.fingerprint,
        )
        per_value: dict = {}
        for t in trials:
            per_value.setdefault(repr(t["value"]), []).append(t)
        if len(per_value) < self.min_distinct_values:
            return None, None, len(trials)
        sign = 1.0 if knob.mode == "max" else -1.0
        best = None
        for group in per_value.values():
            by_bucket: dict[int, float] = {}
            for t in group:
                b = int(t["shape_bucket"])
                # several reps at one bucket: keep the best leg, the
                # same best-of-N discipline the bench applies
                s = float(t["score"])
                if b not in by_bucket or sign * s > sign * by_bucket[b]:
                    by_bucket[b] = s
            score = sign * self._score_at(by_bucket, bucket)
            nearest = min(group, key=lambda t: (
                abs(log2(max(int(t["shape_bucket"]), 1)) - log2(bucket)),
                t["trial_id"],
            ))
            cand = (score, repr(group[0]["value"]))
            if best is None or cand > best[0]:
                best = (cand, group[0]["value"], nearest["trial_id"])
        _, value, tid = best
        return value, tid, len(trials)

    # ---------------------------------------------------------- resolve
    def resolve(self, knob: Knob, shape: int | None = None):
        """The :func:`tune.knob` hook — fence first, trials second,
        default last; every path records an explainable decision."""
        bucket = shape_bucket(shape if shape is not None else 1)
        with self._lock:
            if fence_active():
                prev = self._last.get(knob.name)
                value = prev["value"] if prev else knob.default
                self._note(knob.name, value, REASON_FROZEN_FENCED, 0, bucket)
                return value
            value, tid, n = self._rank(knob, bucket)
            if tid is None:
                self._note(
                    knob.name, knob.default, REASON_DEFAULT_NO_TRIALS,
                    n, bucket,
                )
                return knob.default
            self._note(
                knob.name, value, REASON_TUNED_PREFIX + tid, n, bucket,
            )
            return value

    def _note(self, name, value, reason, n_trials, bucket) -> None:
        self._last[name] = {
            "value": value, "reason": reason,
            "trials_considered": int(n_trials), "shape_bucket": int(bucket),
        }

    def explain(self, name: str | None = None) -> dict:
        """Last decision per knob (or one knob): ``{value, reason,
        trials_considered, shape_bucket}``."""
        with self._lock:
            if name is not None:
                return dict(self._last.get(name, {}))
            return {k: dict(v) for k, v in self._last.items()}


# ------------------------------------------------------------ installation
_SELECTOR: Selector | None = None


def install(selector: Selector) -> Selector:
    """Route every :func:`tune.knob` lookup through ``selector``."""
    global _SELECTOR
    _SELECTOR = selector
    _knobs.set_resolver(selector.resolve)
    return selector


def clear() -> None:
    global _SELECTOR
    _SELECTOR = None
    _knobs.set_resolver(None)


def installed() -> Selector | None:
    return _SELECTOR


@contextmanager
def active(selector: Selector) -> Iterator[Selector]:
    """``with tune.active(Selector(store)): ...`` — installed for the
    block, uninstalled (back to declared defaults) on exit."""
    install(selector)
    try:
        yield selector
    finally:
        clear()
