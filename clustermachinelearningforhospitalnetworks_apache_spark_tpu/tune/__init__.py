"""``tune/`` — the measurement-driven autotuner (ROADMAP item 2).

One subsystem owns every hand-set performance knob:

* :mod:`.knobs` — the registry: every tunable declares name, domain,
  default, and the metric that scores it; call sites resolve through
  :func:`knob` instead of carrying literals (the ``untracked-knob``
  lint pass keeps it that way).
* :mod:`.store` — durable trials keyed by (platform, knob,
  config-fingerprint, shape-bucket), committed under the
  ``tune.store.commit`` fault site with content-hash exactly-once merge.
* :mod:`.select` — the interpolating cost model: defaults when coverage
  is thin, never selects inside a fenced A/B, every decision explained
  by a reason constant.
* :mod:`.live` — one serving knob retuned from observed load through a
  journaled intent/commit protocol (``tune.select.apply`` kill seam).
"""

from .knobs import REGISTRY, Knob, KnobRegistry, default, knob
from .live import LiveRetuner
from .select import (
    REASON_DEFAULT_NO_TRIALS, REASON_FROZEN_FENCED, REASON_TUNED_PREFIX,
    Selector, ab_fence, active, clear, fence_active, install, installed,
)
from .store import TrialStore, make_trial, shape_bucket, trial_id

__all__ = [
    "REGISTRY",
    "Knob",
    "KnobRegistry",
    "knob",
    "default",
    "TrialStore",
    "make_trial",
    "shape_bucket",
    "trial_id",
    "Selector",
    "ab_fence",
    "fence_active",
    "install",
    "installed",
    "active",
    "clear",
    "LiveRetuner",
    "REASON_DEFAULT_NO_TRIALS",
    "REASON_FROZEN_FENCED",
    "REASON_TUNED_PREFIX",
]
