"""Live retuning: one serving knob re-decided from observed fleet load.

The offline sweeps in ``tools/autotune.py`` measure candidates under a
synthetic workload; the fleet's *actual* load is the ground truth.  A
:class:`LiveRetuner` closes that loop for one knob (the ISSUE names the
micro-batch deadline and the SLO queue threshold as the targets):

1. **observe** — the caller reports the rate the fleet is seeing at the
   currently-deployed value (pulled from the obs registry's stage
   timings or the soak probe); the observation lands in the trial store
   as a ``source="live"`` trial.
2. **select** — the fence-aware :class:`~.select.Selector` re-ranks.
   Inside a fenced A/B nothing moves (``frozen:fenced-ab``).
3. **apply** — if the winner differs from the deployed value, the
   change goes through a write-ahead protocol on the retune journal::

       append {kind: "intent", ...}      # durable: what we are about to do
       fault_point("tune.select.apply")  # the chaos kill seam
       apply_fn(value)                   # the existing atomic swap path
       append {kind: "commit", ...}      # durable: it is now in effect

   ``apply_fn`` is the *existing* atomic application path of the knob —
   a single attribute store on the micro-batcher (its worker reads
   ``max_wait_s`` fresh each iteration) or an admission-class dict-entry
   swap — never a new mutation protocol.

Crash story (proved by the chaos tests): a kill at
``tune.select.apply`` leaves an intent with no commit — :meth:`resume`
ignores it, so the previous value keeps serving; a kill after apply but
before commit dies with the process, and the restart resumes the last
*committed* value — again the previous one.  A committed retune is
re-applied by :meth:`resume` on restart, so the tuned value survives.
"""

from __future__ import annotations

from typing import Callable

from ..obs.trace import span
from ..utils.faults import fault_point
from .knobs import REGISTRY, Knob
from .select import Selector
from .store import make_trial


class LiveRetuner:
    """Observe → select → journal → apply, for one registered knob."""

    def __init__(
        self,
        knob_name: str,
        *,
        journal_path: str,
        apply_fn: Callable,
        selector: Selector,
        convert: Callable | None = None,
    ):
        self.knob: Knob = REGISTRY.get(knob_name)
        self.journal_path = str(journal_path)
        self.apply_fn = apply_fn
        self.selector = selector
        #: knob-units → call-site units (e.g. ms → s); identity when None
        self.convert = convert or (lambda v: v)
        self.current = self.knob.default
        self.events = 0

    # ------------------------------------------------------------ resume
    def resume(self):
        """Replay the journal: re-apply the last **committed** value.

        Uncommitted intents are ignored — a kill between intent and
        apply must leave the previous value serving, and the journal
        reader (``streaming/wal.read_lines``) already skips torn lines.
        Returns the resumed value, or ``None`` when nothing committed.
        """
        from ..streaming.wal import read_lines  # lazy: avoids import cycle

        committed = None
        for entry in read_lines(self.journal_path):
            if entry.get("kind") == "commit" and entry.get("knob") == \
                    self.knob.name:
                committed = entry
        if committed is None:
            return None
        value = committed["value"]
        self.apply_fn(self.convert(value))
        self.current = value
        return value

    # ------------------------------------------------------------ retune
    def observe(self, score: float, *, shape_rows: int = 1,
                reps: int = 1, meta: dict | None = None) -> dict:
        """Record what the deployed value is actually delivering."""
        trial = make_trial(
            knob=self.knob.name, value=self.current, score=score,
            platform=self.selector.platform,
            fingerprint=self.selector.fingerprint,
            shape_rows=shape_rows, metric=self.knob.metric,
            reps=reps, source="live", meta=meta,
        )
        self.selector.store.add([trial])
        return trial

    def retune(self, *, shape_rows: int = 1) -> dict:
        """One selection pass; applies (journaled) only on a change.

        Returns ``{knob, old, new, applied, reason}`` — the record the
        soak report banks for its retune-boundary invariant.
        """
        with span("tune.select", {"knob": self.knob.name}):
            new = self.selector.resolve(self.knob, shape_rows)
            reason = self.selector.explain(self.knob.name).get("reason", "")
            old = self.current
            out = {
                "knob": self.knob.name, "old": old, "new": new,
                "applied": False, "reason": reason,
            }
            if new == old:
                return out
            self.events += 1
            entry = {
                "knob": self.knob.name, "old": old, "value": new,
                "reason": reason, "seq": self.events,
            }
            # the span-log exemption does NOT apply here: this journal
            # IS the durability story, so appends keep the wal.append
            # torn-tail discipline under their own site name
            append_line_kind(self.journal_path, entry, "intent")
            fault_point("tune.select.apply", knob=self.knob.name)
            self.apply_fn(self.convert(new))
            append_line_kind(self.journal_path, entry, "commit")
            self.current = new
            out["applied"] = True
            return out


def append_line_kind(path: str, entry: dict, kind: str) -> None:
    from ..streaming.wal import append_line  # lazy: avoids import cycle

    append_line(path, dict(entry, kind=kind))
