"""Model selection — ``pyspark.ml.tuning`` parity.

``ParamGridBuilder`` / ``CrossValidator`` / ``TrainValidationSplit``: the
MLlib hyper-parameter search surface a Spark user would reach for around
the reference's estimators (the reference hand-picks parameters at
``mllearnforhospitalnetwork.py:146-158``; tuning is the Spark-machinery
capability on top, SURVEY.md §2B E4).

TPU-shaped re-design, not a scheduler port: Spark parallelizes fold fits
across the cluster; here every fit already saturates the mesh, so the
search is a **sequential loop of device-resident fits** — fold membership
is decided once on host (seeded permutation) and the train/validation row
subsets are built host-side per fold; each fit stages its subset to the
mesh, and every (fold × param) fit reuses the same jitted estimator
executables (shapes are identical across params, so XLA compiles each
estimator once per fold shape).

Estimators are frozen/plain dataclasses, so a "param map" is a plain dict
applied via ``dataclasses.replace``:

- bare keys (``"reg_param"``) set fields on the estimator itself; for a
  ``Pipeline`` they target the **last stage that has the field** (the
  conventional estimator slot),
- dotted keys (``"1.reg_param"``) target an explicit Pipeline stage index.
"""

from __future__ import annotations

import copy
import dataclasses
import itertools
import json
import os
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from ..features.assembler import AssembledTable
from ..io.model_io import (
    METADATA_FILE,
    load_model,
    finalize_artifact_dir,
    prepare_artifact_dir,
    register_composite,
    validate_persistable,
    write_metadata,
)
from ..pipeline.ml_pipeline import Pipeline, _call_stage
from ..version import __version__


class ParamGridBuilder:
    """``ParamGridBuilder().add_grid("reg_param", [0.0, 0.1]).build()`` →
    cartesian-product list of param dicts (Spark's ``addGrid``/``build``)."""

    def __init__(self) -> None:
        self._grid: dict[str, Sequence[Any]] = {}

    def add_grid(self, param: str, values: Sequence[Any]) -> "ParamGridBuilder":
        if not values:
            raise ValueError(f"empty value list for param {param!r}")
        self._grid[param] = list(values)
        return self

    def base_on(self, params: Mapping[str, Any]) -> "ParamGridBuilder":
        """Fixed (non-swept) params merged into every map (Spark ``baseOn``)."""
        for k, v in params.items():
            self._grid[k] = [v]
        return self

    def build(self) -> list[dict[str, Any]]:
        keys = list(self._grid)
        return [
            dict(zip(keys, combo))
            for combo in itertools.product(*(self._grid[k] for k in keys))
        ]


def _replace_field(obj: Any, name: str, value: Any) -> Any:
    if dataclasses.is_dataclass(obj):
        if name not in {f.name for f in dataclasses.fields(obj)}:
            raise ValueError(
                f"{type(obj).__name__} has no param {name!r}; fields: "
                f"{sorted(f.name for f in dataclasses.fields(obj))}"
            )
        return dataclasses.replace(obj, **{name: value})
    if not hasattr(obj, name):
        raise ValueError(f"{type(obj).__name__} has no param {name!r}")
    clone = copy.copy(obj)
    setattr(clone, name, value)
    return clone


def apply_params(estimator: Any, params: Mapping[str, Any]) -> Any:
    """A copy of ``estimator`` with the param map applied (see module doc
    for bare-vs-dotted key semantics on Pipelines)."""
    if not params:
        return estimator
    if isinstance(estimator, Pipeline):
        stages = list(estimator.stages)
        for key, value in params.items():
            if "." in key:
                idx_s, name = key.split(".", 1)
                idx = int(idx_s)
                if not 0 <= idx < len(stages):
                    raise ValueError(
                        f"param {key!r}: stage index {idx} out of range "
                        f"({len(stages)} stages)"
                    )
                stages[idx] = _replace_field(stages[idx], name, value)
            else:
                for idx in range(len(stages) - 1, -1, -1):
                    target = stages[idx]
                    names = (
                        {f.name for f in dataclasses.fields(target)}
                        if dataclasses.is_dataclass(target)
                        else set(vars(target))
                    )
                    if key in names:
                        stages[idx] = _replace_field(target, key, value)
                        break
                else:
                    raise ValueError(
                        f"no pipeline stage has param {key!r}; use a dotted "
                        "'<stage>.<param>' key to target one explicitly"
                    )
        return Pipeline(tuple(stages))
    out = estimator
    for key, value in params.items():
        out = _replace_field(out, key, value)
    return out


def _num_rows(data: Any) -> int:
    if isinstance(data, AssembledTable):
        return len(data)
    if isinstance(data, tuple) and len(data) in (2, 3):
        return int(np.asarray(data[0]).shape[0])
    if hasattr(data, "num_rows"):
        return int(data.num_rows)
    return int(np.asarray(data).shape[0])


def _row_subset(data: Any, keep: np.ndarray) -> Any:
    """Host-side row filter for the supported fit inputs (Table,
    AssembledTable, (x, y[, w]), bare array) — fold subsets are staged to
    the mesh by the estimator's own ``fit``."""
    if isinstance(data, AssembledTable):
        return dataclasses.replace(
            data, table=data.table.mask(keep), features=data.features[keep]
        )
    if isinstance(data, tuple) and len(data) in (2, 3):
        return tuple(np.asarray(a)[keep] for a in data)
    if hasattr(data, "mask"):
        return data.mask(keep)
    return np.asarray(data)[keep]


def _val_features(val) -> np.ndarray:
    if isinstance(val, AssembledTable):
        return np.asarray(val.features, dtype=np.float32)
    if isinstance(val, tuple):
        return np.asarray(val[0], dtype=np.float32)
    return np.asarray(val, dtype=np.float32)


def _score(model, val, evaluator, label_col, mesh) -> float:
    from ..evaluation.clustering import ClusteringEvaluator

    if isinstance(evaluator, ClusteringEvaluator):
        # clustering models are scored (features, assignments)-style —
        # silhouette needs the features, not a PredictionResult; the
        # assignment pass runs on the caller's mesh, not the process default
        from ..models.base import as_device_dataset
        from ..parallel.sharding import unpad

        x = _val_features(val)
        ds = as_device_dataset(x, mesh=mesh)
        assign = np.asarray(unpad(model.predict(ds.x), x.shape[0]))
        k = getattr(model, "k", None) or getattr(
            model, "cluster_centers", np.zeros((0,))
        ).shape[0] or None
        return float(evaluator.evaluate(x, assign, k=k, mesh=mesh))
    pred = _call_stage(model.transform, val, label_col, mesh)
    return float(evaluator.evaluate(pred))


def _fit_and_score(estimator, params, train, val, evaluator, label_col, mesh):
    est = apply_params(estimator, params)
    model = _call_stage(est.fit, train, label_col, mesh)
    return model, _score(model, val, evaluator, label_col, mesh)


def _best_index(avg: np.ndarray, larger_better: bool) -> int:
    """NaN-safe winner selection: np.argmax/argmin treat NaN as the
    extremum, so one NaN-scoring (fold, param) cell — a degenerate
    silhouette, an r2 on a pathological fold — would silently win."""
    if np.all(np.isnan(avg)):
        raise ValueError(
            "every parameter map scored NaN; the metric is undefined on "
            "this data/estimator combination"
        )
    return int(np.nanargmax(avg) if larger_better else np.nanargmin(avg))


@dataclass(frozen=True)
class CrossValidator:
    """K-fold model selection (Spark ``CrossValidator``): every param map is
    fit on each fold's train split and scored on its validation split; the
    best average wins and is refit on the full data."""

    estimator: Any
    param_maps: Sequence[Mapping[str, Any]]
    evaluator: Any
    num_folds: int = 3
    seed: int = 0
    collect_sub_models: bool = False

    def fit(self, data: Any, label_col: str | None = None, mesh=None) -> "CrossValidatorModel":
        if self.num_folds < 2:
            raise ValueError(f"num_folds must be ≥2, got {self.num_folds}")
        if not self.param_maps:
            raise ValueError("param_maps is empty; build one with ParamGridBuilder")
        n = _num_rows(data)
        fold_of = np.random.default_rng(self.seed).permutation(n) % self.num_folds
        metrics = np.zeros((len(self.param_maps), self.num_folds))
        sub_models: list[list[Any]] = [[] for _ in self.param_maps]
        for fold in range(self.num_folds):
            val_mask = fold_of == fold
            train = _row_subset(data, ~val_mask)
            val = _row_subset(data, val_mask)
            for pi, params in enumerate(self.param_maps):
                model, score = _fit_and_score(
                    self.estimator, params, train, val, self.evaluator,
                    label_col, mesh,
                )
                metrics[pi, fold] = score
                if self.collect_sub_models:
                    sub_models[pi].append(model)
        avg = metrics.mean(axis=1)
        larger = getattr(self.evaluator, "is_larger_better", True)
        best = _best_index(avg, larger)
        best_est = apply_params(self.estimator, self.param_maps[best])
        best_model = _call_stage(best_est.fit, data, label_col, mesh)
        return CrossValidatorModel(
            best_model=best_model,
            avg_metrics=avg,
            best_index=best,
            param_maps=tuple(dict(p) for p in self.param_maps),
            fold_metrics=metrics,
            sub_models=tuple(map(tuple, sub_models)) if self.collect_sub_models else None,
        )


@dataclass(frozen=True)
class TrainValidationSplit:
    """Single-split model selection (Spark ``TrainValidationSplit``)."""

    estimator: Any
    param_maps: Sequence[Mapping[str, Any]]
    evaluator: Any
    train_ratio: float = 0.75
    seed: int = 0

    def fit(self, data: Any, label_col: str | None = None, mesh=None) -> "TrainValidationSplitModel":
        if not 0.0 < self.train_ratio < 1.0:
            raise ValueError(f"train_ratio must be in (0, 1), got {self.train_ratio}")
        if not self.param_maps:
            raise ValueError("param_maps is empty; build one with ParamGridBuilder")
        n = _num_rows(data)
        perm = np.random.default_rng(self.seed).permutation(n)
        n_train = int(round(n * self.train_ratio))
        train_mask = np.zeros(n, dtype=bool)
        train_mask[perm[:n_train]] = True
        train = _row_subset(data, train_mask)
        val = _row_subset(data, ~train_mask)
        metrics = np.zeros(len(self.param_maps))
        for pi, params in enumerate(self.param_maps):
            _, metrics[pi] = _fit_and_score(
                self.estimator, params, train, val, self.evaluator, label_col, mesh
            )
        larger = getattr(self.evaluator, "is_larger_better", True)
        best = _best_index(metrics, larger)
        best_est = apply_params(self.estimator, self.param_maps[best])
        best_model = _call_stage(best_est.fit, data, label_col, mesh)
        return TrainValidationSplitModel(
            best_model=best_model,
            validation_metrics=metrics,
            best_index=best,
            param_maps=tuple(dict(p) for p in self.param_maps),
        )


class _SelectedModel:
    """Shared transform/persistence shell around ``best_model``."""

    _ARTIFACT: str = ""

    def transform(self, data: Any, label_col: str | None = None, mesh=None):
        return _call_stage(self.best_model.transform, data, label_col, mesh)

    def _validate_persistable(self, prefix: str = "") -> None:
        validate_persistable(self.best_model, label=f"{prefix}bestModel")
        for pi, fold_models in enumerate(self._extra_models() or ()):
            for fi, m in enumerate(fold_models):
                validate_persistable(m, label=f"{prefix}subModel {pi}/{fi}")

    def _extra_models(self):
        return getattr(self, "sub_models", None)

    def save(self, path: str, overwrite: bool = True) -> None:
        # pre-validate so a failed save never destroys an existing artifact
        self._validate_persistable()
        prepare_artifact_dir(path, overwrite)
        self.best_model.save(os.path.join(path, "bestModel"))
        subs = self._extra_models()
        if subs:
            for pi, fold_models in enumerate(subs):
                for fi, m in enumerate(fold_models):
                    m.save(os.path.join(path, "subModels", f"p{pi}", f"f{fi}"))
        write_metadata(path, {
            "model_class": self._ARTIFACT,
            "framework_version": __version__,
            **self._selection_meta(),
        })
        finalize_artifact_dir(path)  # commit: drop sentinel, discard .old

    def write(self):
        from ..models.base import _Writer

        return _Writer(self)

    def _selection_meta(self) -> dict:
        raise NotImplementedError

    @classmethod
    def load(cls, path: str, _meta: dict | None = None):
        if _meta is None:
            with open(os.path.join(path, METADATA_FILE)) as f:
                _meta = json.load(f)
        best = load_model(os.path.join(path, "bestModel"))
        return cls._from_meta(best, _meta, path)

    @classmethod
    def _from_meta(cls, best, meta, path):
        raise NotImplementedError

    @staticmethod
    def _load_sub_models(meta: dict, path: str):
        shape = meta.get("sub_models_shape")
        if not shape:
            return None
        return tuple(
            tuple(
                load_model(os.path.join(path, "subModels", f"p{pi}", f"f{fi}"))
                for fi in range(shape[1])
            )
            for pi in range(shape[0])
        )


@dataclass(frozen=True)
class CrossValidatorModel(_SelectedModel):
    best_model: Any
    avg_metrics: np.ndarray
    best_index: int
    param_maps: tuple[dict, ...]
    fold_metrics: np.ndarray | None = None
    sub_models: tuple | None = None

    _ARTIFACT = "CrossValidatorModel"

    def _selection_meta(self) -> dict:
        return {
            "avg_metrics": np.asarray(self.avg_metrics).tolist(),
            "best_index": int(self.best_index),
            "param_maps": [dict(p) for p in self.param_maps],
            "fold_metrics": (
                np.asarray(self.fold_metrics).tolist()
                if self.fold_metrics is not None
                else None
            ),
            "sub_models_shape": (
                [len(self.sub_models), len(self.sub_models[0])]
                if self.sub_models
                else None
            ),
        }

    @classmethod
    def _from_meta(cls, best, meta, path):
        return cls(
            best_model=best,
            avg_metrics=np.asarray(meta["avg_metrics"]),
            best_index=int(meta["best_index"]),
            param_maps=tuple(meta["param_maps"]),
            fold_metrics=(
                np.asarray(meta["fold_metrics"])
                if meta.get("fold_metrics") is not None
                else None
            ),
            sub_models=cls._load_sub_models(meta, path),
        )


@dataclass(frozen=True)
class TrainValidationSplitModel(_SelectedModel):
    best_model: Any
    validation_metrics: np.ndarray
    best_index: int
    param_maps: tuple[dict, ...]

    _ARTIFACT = "TrainValidationSplitModel"

    def _selection_meta(self) -> dict:
        return {
            "validation_metrics": np.asarray(self.validation_metrics).tolist(),
            "best_index": int(self.best_index),
            "param_maps": [dict(p) for p in self.param_maps],
        }

    @classmethod
    def _from_meta(cls, best, meta, path):
        return cls(
            best_model=best,
            validation_metrics=np.asarray(meta["validation_metrics"]),
            best_index=int(meta["best_index"]),
            param_maps=tuple(meta["param_maps"]),
        )


register_composite(
    "CrossValidatorModel",
    "clustermachinelearningforhospitalnetworks_apache_spark_tpu.tuning.tuning:CrossValidatorModel",
)
register_composite(
    "TrainValidationSplitModel",
    "clustermachinelearningforhospitalnetworks_apache_spark_tpu.tuning.tuning:TrainValidationSplitModel",
)
