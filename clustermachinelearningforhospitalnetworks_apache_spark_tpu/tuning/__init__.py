from .tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)

__all__ = [
    "CrossValidator",
    "CrossValidatorModel",
    "ParamGridBuilder",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
]
