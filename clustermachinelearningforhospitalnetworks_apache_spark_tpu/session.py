"""Session — the ``SparkSession`` analogue.

The reference bootstraps ``SparkSession.builder.appName(...).master(
"spark://…").getOrCreate()`` (``mllearnforhospitalnetwork.py:55-58``) and
then uses it for streaming reads (:75), SQL (:128) and implicit cluster
scheduling.  Here a Session is an in-process object (SURVEY.md L2: the
Py4J/JVM hop is *eliminated*): it owns the device mesh, a named-table
registry, and the fluent streaming read/write surface, including the
builder chain so reference code ports line-for-line::

    spark = Session.builder.app_name("x").mesh(cfg).get_or_create()
    sdf = (spark.read_stream.schema(schema).csv(path)
                 .with_watermark("event_time", "10 minutes"))
    q = (sdf.write_stream.foreach_batch(fn)
            .option("checkpointLocation", ckpt).table("events"))
    q.process_available()          # or q.await_termination(timeout)
    train = spark.sql("SELECT * FROM events WHERE event_time BETWEEN "
                      "'2025-03-31 22:00:00' AND '2025-03-31 23:00:00'")
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable

from .config import MeshConfig, PipelineConfig
from .core.schema import Schema
from .core.table import Table
from .parallel.mesh import build_mesh, set_default_mesh
from .streaming.checkpoint import StreamCheckpoint
from .streaming.microbatch import BatchInfo, StreamExecution
from .streaming.source import FileStreamSource
from .streaming.unbounded_table import UnboundedTable
from .streaming.watermark import WatermarkTracker
from .utils.logging import get_logger
from .utils.metrics import MetricsRegistry

log = get_logger("session")

_DURATION = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(second|minute|hour|day)s?\s*$")


def parse_duration_minutes(text: str) -> float:
    """'10 minutes' → 10.0 (Spark interval-string parity)."""
    m = _DURATION.match(text)
    if not m:
        raise ValueError(f"cannot parse duration {text!r}")
    value, unit = float(m.group(1)), m.group(2)
    return value * {"second": 1 / 60, "minute": 1, "hour": 60, "day": 1440}[unit]


# ------------------------------------------------------------------ session
_ACTIVE_SESSION: "Session | None" = None


class Session:
    def __init__(self, config: PipelineConfig | None = None, mesh=None):
        global _ACTIVE_SESSION
        from .parallel import mesh as _mesh_mod

        self.config = config or PipelineConfig()
        self.mesh = mesh if mesh is not None else build_mesh(self.config.mesh)
        # Remember what we displaced so stop() can restore it rather than
        # nulling the process-wide state out from under another session.
        self._prev_default_mesh = _mesh_mod._DEFAULT_MESH
        self._prev_active_session = _ACTIVE_SESSION
        set_default_mesh(self.mesh)
        self.metrics = MetricsRegistry()
        self._tables: dict[str, Any] = {}
        self._streams: list[StreamExecution] = []
        # materialized views (ISSUE 14): one registry per session; the
        # streaming commit path maintains them, Session.sql serves from
        # them when a plan fingerprint matches a fresh view
        from .core.sql_views import ViewRegistry

        self.views = ViewRegistry()
        _ACTIVE_SESSION = self

    # builder ----------------------------------------------------------
    class _Builder:
        def __init__(self) -> None:
            self._config = PipelineConfig()

        def app_name(self, name: str) -> "Session._Builder":
            self._config = self._config.replace(app_name=name)
            return self

        appName = app_name  # Spark spelling

        def config_obj(self, cfg: PipelineConfig) -> "Session._Builder":
            self._config = cfg
            return self

        def mesh(self, mesh_cfg: MeshConfig) -> "Session._Builder":
            self._config = self._config.replace(mesh=mesh_cfg)
            return self

        def get_or_create(self) -> "Session":
            """Spark semantics: reuse the active session if one exists
            (builder config is then ignored, as in Spark)."""
            if _ACTIVE_SESSION is not None:
                return _ACTIVE_SESSION
            return Session(self._config)

        getOrCreate = get_or_create

    class _BuilderAccessor:
        """Fresh builder per access (so chained configs don't leak between
        sessions the way a shared mutable builder would)."""

        def __get__(self, obj, objtype=None) -> "Session._Builder":
            return Session._Builder()

    # tables ------------------------------------------------------------
    def register_table(self, name: str, table: Table | UnboundedTable) -> None:
        self._tables[name] = table

    def table(self, name: str) -> Table:
        t = self._tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r}; registered: {sorted(self._tables)}")
        return t.read() if isinstance(t, UnboundedTable) else t

    def sql(self, query: str) -> Table:
        """SQL over registered tables (``core/sql.py``) — a real parsed
        subset, not just the reference's windowed SELECT (:123-128):
        projections, aggregates (COUNT/SUM/AVG/MIN/MAX), WHERE with
        AND/OR/BETWEEN/comparisons, GROUP BY, ORDER BY, LIMIT.

        Dispatch (ISSUE 7): fully-supported single-table plans run as
        jitted columnar XLA kernels over device-held columns; the long
        tail runs the numpy interpreter (``sql_explain`` shows which,
        and why, per plan node).  ISSUE 14: when a registered
        materialized view matches the plan's fingerprint and is fresh,
        the answer comes from the view's delta-maintained state instead
        of re-executing over the table's full history."""
        from .core.sql import execute

        return execute(query, self.table, views=self.views)

    def sql_explain(self, query: str) -> dict:
        """Planner view of ``query`` without running it: the route
        (compiled | interpreter), the plan fingerprint, every plan
        node's supported/fallback decision, and each node's incremental
        decision (``incremental`` vs ``full-recompute:<reason>`` —
        whether a materialized view would maintain it per batch)."""
        from .core.sql import explain

        return explain(query, self.table)

    def create_view(
        self, name: str, query: str, watermark=None
    ) -> Any:
        """Register a materialized view (ISSUE 14) over a registered
        :class:`~.streaming.unbounded_table.UnboundedTable`: the view is
        maintained incrementally per committed batch (mergeable
        aggregate partials / per-batch row deltas) and ``Session.sql``
        transparently answers matching queries from it.  ``watermark``
        (a ``WatermarkTracker``, typically the stream's) enables sealing
        + compaction of aggregate partials below the event-time
        watermark.  Non-incrementalizable queries still register but
        serve loud full recomputes (``sql_explain`` shows why per
        node)."""
        from .core.sql_parse import _Query, parse

        node = parse(query)
        if (
            not isinstance(node, _Query)
            or not isinstance(node.table[0], str)
            or node.joins
        ):
            raise ValueError(
                "a materialized view needs a single-table SELECT over a "
                "registered unbounded table"
            )
        source = self._tables.get(node.table[0])
        if not isinstance(source, UnboundedTable):
            raise ValueError(
                f"view {name!r}: {node.table[0]!r} is not a registered "
                "UnboundedTable (views materialize over the streaming "
                "sink; plain tables are already in memory)"
            )
        return self.views.register(name, query, source, watermark=watermark)

    def sql_to_device(
        self,
        query: str,
        feature_cols=None,
        label_col: str | None = None,
        mesh=None,
        na_drop: bool = True,
        clock=None,
        mode: str = "auto",
    ):
        """The fused training path (ISSUE 7): SQL window extract →
        feature assembly → a mesh-ready ``DeviceDataset``, entirely on
        device when the plan compiles — ingest (PR 4) → SQL → assemble →
        fit (PR 5) then never round-trips through the host.

        Falls back to interpreter + host assembly when the plan has
        fallback nodes (``mode="compile"`` raises instead;
        ``core.sql.last_dispatch()`` records the route).  ``na_drop``
        mirrors the reference's ``na.drop()`` over the feature/label
        columns.  ``clock`` (a ``StageClock``) brackets the
        transfer/sql/assemble stages for the host-detour evidence.
        """
        from contextlib import nullcontext

        from .core.schema import FEATURE_COLS, LABEL_COL
        from .core.sql import execute
        from .core.sql_compile import compile_rowlevel
        from .features.assembler import VectorAssembler

        feature_cols = tuple(feature_cols or FEATURE_COLS)
        assembler = VectorAssembler(feature_cols)
        view = compile_rowlevel(query, self.table, mode=mode, clock=clock)
        stage = clock.stage if clock is not None else (lambda _: nullcontext())
        if view is not None:
            with stage("assemble"):
                return assembler.transform_device(
                    view, label_col=label_col, mesh=mesh or self.mesh,
                    na_drop=na_drop,
                )
        # host fallback: interpreter (or compiled materialization) +
        # host-side assembly — one transfer at the to_device boundary.
        # A fresh fingerprint-matched materialized view answers here too
        # (ISSUE 14): the fused device path above stays view-free (it
        # never materializes), but the host path's table may as well
        # come from folded view state instead of a history re-scan.
        with stage("sql"):
            t = execute(query, self.table, views=self.views)
        if label_col is None and LABEL_COL in t.schema:
            label_col = LABEL_COL
        if na_drop:
            t = t.na_drop(
                subset=list(feature_cols)
                + ([label_col] if label_col else [])
            )
        with stage("assemble"):
            assembled = assembler.transform(t)
            return assembled.to_device(
                label_col=label_col, mesh=mesh or self.mesh
            )

    # streaming read ----------------------------------------------------
    @property
    def read_stream(self) -> "StreamingReader":
        return StreamingReader(self)

    readStream = read_stream

    def stop(self) -> None:
        global _ACTIVE_SESSION
        from .parallel import mesh as _mesh_mod

        # Restore displaced process-wide state, but only if it is still
        # ours — a non-LIFO stop must not clobber another live session's.
        if _mesh_mod._DEFAULT_MESH is self.mesh:
            set_default_mesh(self._prev_default_mesh)
        if _ACTIVE_SESSION is self:
            _ACTIVE_SESSION = self._prev_active_session
        log.info("session stopped", app=self.config.app_name)


Session.builder = Session._BuilderAccessor()


# --------------------------------------------------- fluent streaming layer
@dataclass
class StreamingReader:
    session: Session
    _schema: Schema | None = None
    _header: bool = True

    def schema(self, s: Schema) -> "StreamingReader":
        self._schema = s
        return self

    def option(self, key: str, value: Any) -> "StreamingReader":
        if key.lower() == "header":
            self._header = str(value).lower() in ("1", "true", "yes")
        return self

    def csv(self, path: str) -> "StreamingFrame":
        if self._schema is None:
            raise ValueError("streaming CSV requires an explicit schema (as in the reference :64-80)")
        return StreamingFrame(
            session=self.session,
            source=FileStreamSource(path, self._schema, header=self._header),
        )


@dataclass
class StreamingFrame:
    session: Session
    source: FileStreamSource
    watermark: WatermarkTracker | None = None

    def with_watermark(self, column: str, delay: str) -> "StreamingFrame":
        self.watermark = WatermarkTracker(column, parse_duration_minutes(delay))
        return self

    withWatermark = with_watermark

    @property
    def write_stream(self) -> "StreamWriter":
        return StreamWriter(frame=self)

    writeStream = write_stream


@dataclass
class StreamWriter:
    frame: StreamingFrame
    _foreach: Callable[[Table, int], None] | None = None
    _options: dict[str, str] = field(default_factory=dict)

    def foreach_batch(self, fn: Callable[[Table, int], None]) -> "StreamWriter":
        self._foreach = fn
        return self

    foreachBatch = foreach_batch

    def output_mode(self, mode: str) -> "StreamWriter":
        if mode != "append":
            raise ValueError("only append mode is supported (the reference uses append, :113)")
        return self

    outputMode = output_mode

    def format(self, fmt: str) -> "StreamWriter":
        # delta/parquet both map onto the parquet-backed unbounded table
        return self

    def option(self, key: str, value: str) -> "StreamWriter":
        self._options[key] = value
        return self

    def table(self, name: str) -> "StreamingQuery":
        ckpt_path = self._options.get(
            "checkpointLocation", self.frame.session.config.checkpoint_location
        )
        sink_dir = self._options.get("path", ckpt_path + "_table_" + name)
        sink = UnboundedTable(sink_dir, self.frame.source.schema, name=name)
        execution = StreamExecution(
            source=self.frame.source,
            sink=sink,
            checkpoint=StreamCheckpoint(ckpt_path),
            watermark=self.frame.watermark,
            foreach_batch=self._foreach,
            # the session's materialized views fold each committed
            # batch's delta in on the commit path (ISSUE 14)
            views=self.frame.session.views,
        )
        self.frame.session.register_table(name, sink)
        self.frame.session._streams.append(execution)
        return StreamingQuery(execution=execution, name=name)

    def start(self, name: str | None = None) -> "StreamingQuery":
        """Spark-style no-argument start(): the query/table name comes from
        the ``queryName`` option, falling back to a generated name."""
        return self.table(
            name
            or self._options.get("queryName")
            or f"stream_query_{len(self.frame.session._streams)}"
        )


@dataclass
class StreamingQuery:
    execution: StreamExecution
    name: str

    def process_available(self) -> list[BatchInfo]:
        """Drain everything currently in the source (Spark's
        processAllAvailable) — StreamExecution.run's drain-once mode."""
        return self.execution.run()

    processAllAvailable = process_available

    def await_termination(self, timeout_s: float | None = None) -> list[BatchInfo]:
        """Poll-process until the timeout (:117-118's awaitTermination with
        a bound — an unbounded wait would hang a library caller)."""
        if timeout_s is None:
            raise ValueError("await_termination requires a timeout in library use")
        return self.execution.run(timeout_s=timeout_s)

    awaitTermination = await_termination

    @property
    def last_progress(self) -> BatchInfo | None:
        return self.execution.history[-1] if self.execution.history else None
