"""Pairwise-distance primitives.

The Lloyd-assignment step of every clustering estimator (the hottest loop
in the framework, per the BASELINE north star: "pairwise-distance matmul,
argmin assignment") reduces to one MXU matmul: using

    ||x − c||² = ||x||² − 2·x·cᵀ + ||c||²

the (n, k) distance matrix is a single ``x @ cᵀ`` plus rank-1 row/column
corrections, which XLA fuses with the following argmin.  MLlib's
``fastSquaredDistance`` does the same trick scalar-by-scalar on the JVM;
here it is batched onto the systolic array.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax


def sq_norms(x: jax.Array) -> jax.Array:
    return jnp.sum(x * x, axis=-1)


#: matmul precision modes for the assignment cross-term.  On TPU,
#: ``Precision.HIGHEST`` emulates an f32 matmul with ~6 bf16 MXU passes
#: and ``HIGH`` with 3; ``"bf16"`` truncates the operands to bfloat16 and
#: accumulates in f32 — ONE pass, the native MXU rate.  The ||x||²/||c||²
#: correction terms always stay f32, so bf16 mode only perturbs the
#: cross-term's low mantissa bits (assignment ties aside, the argmin is
#: stable for well-separated centroids; the bench A/Bs silhouette parity).
MATMUL_PRECISIONS = ("highest", "high", "default", "bf16")


def validate_matmul_precision(value: str) -> None:
    """Raise the shared friendly error for an unknown precision mode —
    one copy of the membership check KMeans and GaussianMixture both
    apply at fit time."""
    if value not in MATMUL_PRECISIONS:
        raise ValueError(
            f"matmul_precision must be one of {MATMUL_PRECISIONS}, got "
            f"{value!r}"
        )


def matmul_p(a: jax.Array, b: jax.Array, precision) -> jax.Array:
    """``a @ b`` under a :data:`MATMUL_PRECISIONS` mode — the one copy of
    the bf16-truncate/f32-accumulate vs ``lax.Precision`` dispatch shared
    by the assignment matmul here and the GMM E-step contractions."""
    if precision == "bf16":
        return jnp.dot(
            a.astype(jnp.bfloat16),
            b.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    if isinstance(precision, str):
        precision = lax.Precision(precision.lower())
    return jnp.dot(a, b, precision=precision)


def pairwise_sqdist(
    x: jax.Array,
    centers: jax.Array,
    x_sq: jax.Array | None = None,
    c_sq: jax.Array | None = None,
    precision=lax.Precision.HIGHEST,
) -> jax.Array:
    """(n, d), (k, d) → (n, k) squared Euclidean distances (clamped ≥ 0).

    ``precision`` is a ``lax.Precision`` or the string ``"bf16"`` (operands
    truncated to bfloat16, f32 accumulation — the native single-pass MXU
    rate; see :data:`MATMUL_PRECISIONS`)."""
    if x_sq is None:
        x_sq = sq_norms(x)
    if c_sq is None:
        c_sq = sq_norms(centers)
    cross = matmul_p(x, centers.T, precision)
    d2 = x_sq[:, None] - 2.0 * cross + c_sq[None, :]
    return jnp.maximum(d2, 0.0)


def normalize_rows(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Unit-normalize rows — cosine distance reduces to Euclidean on the
    sphere (Spark's ``distanceMeasure="cosine"`` path)."""
    n = jnp.sqrt(jnp.maximum(sq_norms(x), eps))
    return x / n[:, None]


def assign_clusters(
    x: jax.Array, centers: jax.Array, c_sq: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """→ (argmin index (n,), min squared distance (n,))."""
    d2 = pairwise_sqdist(x, centers, c_sq=c_sq)
    return jnp.argmin(d2, axis=1).astype(jnp.int32), jnp.min(d2, axis=1)


#: rows per tile of the chunked assignment — bounds the (chunk, k) distance
#: tile so no (n, k) matrix lands in HBM at BASELINE scale
ASSIGN_CHUNK = 65536


def _assign_chunked_local(x: jax.Array, centers: jax.Array, chunk: int):
    """Chunked (lax.map) assignment over a *local* array — (n, chunk·k)
    tiles instead of one (n, k) matrix."""
    n, d = x.shape
    c = min(chunk, max(n, 1))
    pad = (-n) % c
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    a = lax.map(lambda xc: assign_clusters(xc, centers)[0], x.reshape(-1, c, d))
    return a.reshape(-1)[:n]


def assign_clusters_chunked(
    x: jax.Array, centers: jax.Array, chunk: int = ASSIGN_CHUNK
) -> jax.Array:
    """Assignment without an (n, k) HBM intermediate — at 10M rows × k=256
    the full distance matrix is ~10 GB, which the Lloyd training step
    already avoids via its row-chunked scan; this is the matching predict
    path.  A mesh-sharded ``x`` is processed shard-locally under
    ``shard_map`` (assignment is embarrassingly row-parallel); anything
    else goes through one jitted chunked scan."""
    from jax.sharding import Mesh

    from ..parallel.partitioner import family as _partitioner_family

    mesh = getattr(getattr(x, "sharding", None), "mesh", None)
    if isinstance(mesh, Mesh):
        return _assign_chunked_sharded(mesh, chunk)(
            x, _partitioner_family("distance").put("const/centers", centers, mesh)
        )
    return _assign_chunked_jit(chunk)(x, centers)


@lru_cache(maxsize=64)
def _assign_chunked_jit(chunk: int):
    """Cached jit wrapper: building ``jax.jit`` per call retraced every
    scoring job (ISSUE 13 jit-in-function finding — the PR 5 class)."""
    return jax.jit(lambda x, centers: _assign_chunked_local(x, centers, chunk))


@lru_cache(maxsize=64)
def _assign_chunked_sharded(mesh, chunk: int):
    from ..parallel.partitioner import family as _partitioner_family

    _pt = _partitioner_family("distance")
    return jax.jit(
        jax.shard_map(
            lambda xs, cen: _assign_chunked_local(xs, cen, chunk),
            mesh=mesh,
            in_specs=(_pt.spec("rows/x", 2), _pt.spec("const/centers")),
            out_specs=_pt.spec("rows/assign", 1),
        )
    )
