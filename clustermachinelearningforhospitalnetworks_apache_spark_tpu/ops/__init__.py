from .distance import assign_clusters, normalize_rows, pairwise_sqdist, sq_norms

__all__ = ["assign_clusters", "normalize_rows", "pairwise_sqdist", "sq_norms"]
