"""Pallas TPU kernels for the clustering hot loop.

SURVEY.md §3.3 names the Lloyd assignment/accumulation step as "the kernel
to own (Pallas)": Spark MLlib runs it row-by-row on the JVM inside
``treeAggregate`` (reference ``mllearnforhospitalnetwork.py`` delegates
every ``KMeans.fit``-style call to that machinery).  The XLA fallback in
``models/kmeans.py`` already batches it onto the MXU, but materializes the
``(rows, k)`` distance matrix **and** a same-shaped one-hot in HBM between
the matmul and the ``segment_sum``.  The fused kernel here keeps one row
block resident in VMEM and produces the per-block sufficient statistics
directly:

    HBM traffic per block:  x  in   (B·d floats)
                            sums/counts out  (k·d + k, once per pass)

instead of ``B·d + 2·B·k`` — for the BASELINE north star (k=256, d≈8)
that is a ~65× cut in bytes moved, turning an HBM-bound loop compute-bound.

Two entry points:

``fused_lloyd_stats``  — one pass over a row shard: weighted center sums,
                         counts, total cost.  Drives the KMeans fit when
                         ``KMeans(use_pallas=True)`` (model axis must be 1).
``fused_assign``       — distance+argmin only (opt-in predict path).

Both run in interpreter mode off-TPU so the CPU test mesh exercises the
exact kernel code path (tests/test_pallas.py).

**Status (measured, v5e single chip, k=256 d=8 n=10M, 2026-07-29):** the
XLA ``lax.scan`` path in models/kmeans.py sustains ~270M records/s/chip;
this kernel ~112M (block 2048; ≥4096 exceeds VMEM), and the gap is
VPU-chain/overhead-bound, not matmul-precision-bound (DEFAULT-precision
matmuls measure *slower*, 83M).  At d=8 the workload is too skinny for a
hand-scheduled win — XLA's fusion already keeps the (rows, k)
intermediates out of HBM inside the scan body.  The kernels therefore stay
**opt-in** (``use_pallas=True``): correct, TPU-compiled, parity-tested.

**Win-or-retire decision record (SURVEY §3.3):** the d=8 verdict above is
the measured decision for the BASELINE shape — XLA owns the skinny-d
loop.  The remaining open shape was wide-d (d≥64), where the fused VMEM
accumulation cuts the (rows, k)+(rows, d) HBM traffic most; the
``pallas_ab`` config in ``bench.py`` A/Bs exactly that (k=64, d=64) on
every driver sweep (``vs_baseline`` > 1 = kernel wins).

**Round-5 verdict (measured, TPU v5e single chip, k=64 d=64 n=2M,
2026-07-31, ≥2 s fenced windows, spread 0.9%):** fused 169.5M vs XLA scan
180.1M records/s/chip — the kernel loses by 6% at the shape chosen to
favor it.  RETIRED to a documented opt-in experiment: XLA's scan fusion
already keeps the block intermediates in VMEM at every shape this
framework's workloads hit, and the hand-scheduled kernel adds grid
overhead without cutting any traffic XLA hadn't.  ``use_pallas=True``
remains supported (correct, parity-tested) for future shapes/hardware
where the balance may differ.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_BIG = 1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # backend not initialized yet
        return False


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose ``vma`` (varying-across-mesh-axes set, checked
    by shard_map in JAX ≥0.9) is the union of the operands' — so the kernels
    compose with shard_map without the caller threading axis names in."""
    vma = None
    for op in operands:
        v = getattr(jax.typeof(op), "vma", None)
        if v:
            vma = v if vma is None else vma | v
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pick_block_rows(n: int, k: int, d: int, requested: int | None) -> int:
    """Auto block size: largest power-of-two whose VMEM-resident buffers
    (padded x block + the (B, k) distance/one-hot intermediates) stay
    within budget.  An explicit ``requested`` is honored as-is (clamped to
    ≥8) — callers tuning for a specific chip own the VMEM math."""
    if requested is not None:
        return max(requested, 8)
    # ~4 live (B, k) f32 intermediates (cross, d2, one-hot, compare) plus
    # the padded x block; 10 MB budget picks 2048 at k=256/d=8, which is
    # the largest block that compiles on v5e (4096 exceeds scoped VMEM).
    budget = 10 * 1024 * 1024
    b = 8192
    while b > 8 and 4 * b * (max(d, 128) + 4 * max(k, 128)) > budget:
        b //= 2
    return max(b, 8)


def _lloyd_kernel(x_ref, w_ref, c_ref, cvalid_ref, sums_ref, counts_ref, cost_ref):
    """Grid dim 0 walks row blocks; outputs revisit block (0, 0) every step
    (TPU grid is sequential per core), so they act as VMEM accumulators."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[:] = jnp.zeros_like(cost_ref)

    x = x_ref[:]                      # (B, d)
    w = w_ref[:]                      # (B, 1)
    c = c_ref[:]                      # (k, d)
    cvalid = cvalid_ref[:]            # (1, k)

    x_sq = jnp.sum(x * x, axis=1, keepdims=True)          # (B, 1)
    c_sq = jnp.sum(c * c, axis=1, keepdims=True)          # (k, 1)
    # precision=HIGHEST matches ops/distance.py — without it the MXU runs
    # bf16-truncated passes on TPU and near-tied argmins flip vs XLA.
    cross = jnp.dot(
        x, c.T, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )                                                     # MXU (B, k)
    d2 = x_sq - 2.0 * cross + c_sq.T
    d2 = jnp.maximum(d2, 0.0)
    d2 = jnp.where(cvalid > 0.0, d2, _BIG)

    min_d2 = jnp.min(d2, axis=1, keepdims=True)           # (B, 1)
    assign = jnp.argmin(d2, axis=1)                       # (B,)

    k = c.shape[0]
    onehot = (
        lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1) == assign[:, None]
    ).astype(jnp.float32) * w                             # (B, k), weighted
    sums_ref[:] += jnp.dot(
        onehot.T, x, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True).T     # (k, 1)
    # (1, 1)-shaped store — Mosaic cannot store scalars to VMEM
    cost_ref[:] += jnp.sum(min_d2 * w, axis=(0, 1), keepdims=True)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _lloyd_call(x, w, centers, c_valid, *, block_rows: int, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // block_rows,)
    sums, counts, cost = pl.pallas_call(
        _lloyd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((k, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            _out_struct((k, d), jnp.float32, x, w, centers, c_valid),
            _out_struct((k, 1), jnp.float32, x, w, centers, c_valid),
            _out_struct((1, 1), jnp.float32, x, w, centers, c_valid),
        ],
        interpret=interpret,
    )(x, w, centers, c_valid)
    return sums, counts[:, 0], cost[0, 0]


def fused_lloyd_stats(
    x: jax.Array,
    w: jax.Array,
    centers: jax.Array,
    c_valid: jax.Array,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """One fused pass: → (sums (k, d), counts (k,), cost ()).

    ``x`` (n, d) rows with validity/frequency weights ``w`` (n,);
    ``centers`` (k, d); ``c_valid`` (k,) 1.0 for live centroids (padding
    slots score +inf and never attract rows).  Rows are processed in
    VMEM-resident blocks; n is padded internally to a block multiple with
    w=0 so any n is accepted.
    """
    if interpret is None:
        interpret = not _on_tpu()
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    n, d = x.shape
    k = centers.shape[0]
    if n == 0:
        # empty grid would skip the kernel's i==0 init and return
        # uninitialized output buffers
        return (
            jnp.zeros((k, d), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
    b = _pick_block_rows(n, k, d, block_rows)
    pad = (-n) % b
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        w = jnp.pad(w, (0, pad))
    return _lloyd_call(
        x, w[:, None], centers, c_valid.astype(jnp.float32)[None, :],
        block_rows=b, interpret=bool(interpret),
    )


def _assign_kernel(x_ref, c_ref, cvalid_ref, out_ref, d2_ref):
    x = x_ref[:]
    c = c_ref[:]
    cvalid = cvalid_ref[:]
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(c * c, axis=1, keepdims=True)
    cross = jnp.dot(
        x, c.T, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    d2 = jnp.maximum(x_sq - 2.0 * cross + c_sq.T, 0.0)
    d2 = jnp.where(cvalid > 0.0, d2, _BIG)
    out_ref[:] = jnp.argmin(d2, axis=1, keepdims=True).astype(jnp.int32)
    d2_ref[:] = jnp.min(d2, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _assign_call(x, centers, c_valid, *, block_rows: int, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    assign, d2 = pl.pallas_call(
        _assign_kernel,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _out_struct((n, 1), jnp.int32, x, centers, c_valid),
            _out_struct((n, 1), jnp.float32, x, centers, c_valid),
        ],
        interpret=interpret,
    )(x, centers, c_valid)
    return assign[:, 0], d2[:, 0]


def fused_assign(
    x: jax.Array,
    centers: jax.Array,
    c_valid: jax.Array | None = None,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """Fused distance+argmin: → (assignment (n,) int32, min-sq-dist (n,))."""
    if interpret is None:
        interpret = not _on_tpu()
    x = x.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    n, d = x.shape
    k = centers.shape[0]
    if c_valid is None:
        c_valid = jnp.ones((k,), jnp.float32)
    if n == 0:
        return jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.float32)
    b = _pick_block_rows(n, k, d, block_rows)
    pad = (-n) % b
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    a, d2 = _assign_call(
        x, centers, c_valid.astype(jnp.float32)[None, :],
        block_rows=b, interpret=bool(interpret),
    )
    return a[:n], d2[:n]


# ------------------------------------------------------------- tree hist
def _hist_kernel(binned_ref, base_ref, w_ref, pos_ref, out_ref, *, LN, S, B, d):
    """Fused bin-and-accumulate for one (tree, row-block) grid step.

    Grid is (T, row blocks); the output block is indexed by tree only, so
    the row-block axis (innermost, sequential on TPU) accumulates into the
    same VMEM-resident (LN·S, d·B) tile.  Per step: build the masked stats
    (LN·S, C) and the per-feature bin one-hots in VMEM, then d small MXU
    matmuls — the stats transient never touches HBM, which is the entire
    point vs. the XLA scan formulation (SURVEY.md §7 hard-part 1).
    """
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[0, :]                                   # (C,) int32
    w = w_ref[0, :]                                       # (C,)
    base = base_ref[:]                                    # (S, C)
    c = pos.shape[0]

    node_iota = lax.broadcasted_iota(jnp.int32, (LN, c), 0)
    node_oh = (pos[None, :] == node_iota).astype(base.dtype) * w[None, :]
    stats = (node_oh[:, None, :] * base[None, :, :]).reshape(LN * S, c)

    binned = binned_ref[:]                                # (d, C) int32
    bin_iota = lax.broadcasted_iota(jnp.int32, (c, B), 1)
    for f in range(d):                                    # static unroll
        binoh = (binned[f][:, None] == bin_iota).astype(base.dtype)
        out_ref[0, :, f * B : (f + 1) * B] += jnp.dot(
            stats, binoh,
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )


@functools.partial(
    jax.jit, static_argnames=("level_nodes", "S", "B", "block_rows", "interpret")
)
def _hist_call(binned_t, base_t, w_tree, pos, *, level_nodes, S, B, block_rows, interpret):
    d, n = binned_t.shape
    T = w_tree.shape[0]
    kernel = functools.partial(_hist_kernel, LN=level_nodes, S=S, B=B, d=d)
    out = pl.pallas_call(
        kernel,
        grid=(T, n // block_rows),
        in_specs=[
            pl.BlockSpec((d, block_rows), lambda t, i: (0, i)),
            pl.BlockSpec((S, block_rows), lambda t, i: (0, i)),
            pl.BlockSpec((1, block_rows), lambda t, i: (t, i)),
            pl.BlockSpec((1, block_rows), lambda t, i: (t, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, level_nodes * S, d * B), lambda t, i: (t, 0, 0)
        ),
        out_shape=_out_struct(
            (T, level_nodes * S, d * B), jnp.float32,
            binned_t, base_t, w_tree, pos,
        ),
        interpret=interpret,
    )(binned_t, base_t, w_tree, pos)
    # (T, LN·S, d·B) → (T, LN, S, d, B) → (T, LN, d, B, S)
    return jnp.transpose(
        out.reshape(T, level_nodes, S, d, B), (0, 1, 3, 4, 2)
    )


def fused_level_hist(
    binned_t: jax.Array,
    base_t: jax.Array,
    w_tree: jax.Array,
    pos: jax.Array,
    level_nodes: int,
    B: int,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """Per-(tree, frontier-node, feature, bin) stat histograms, fused.

    Same contract as the XLA scan inside
    ``models.tree.engine._make_level_hist`` (shard-local part): inputs are
    row-transposed shards, padding rows carry ``pos=-1``/``w=0``.
    → (T, level_nodes, d, B, S) float32.

    Opt-in via ``grow_forest(use_pallas=True)`` /
    ``GBTRegressor(use_pallas=True)``; interpreter mode on CPU so the
    test mesh runs the exact kernel code path.

    **Win-or-retire decision record (PR 5, same discipline as the
    retired Lloyd kernel above):** RETIRED to a documented opt-in
    experiment at the tree shapes this framework hits.  Structural
    verdict, pending contrary on-chip evidence: at the BASELINE tree
    shape (d=8, B=32) the kernel's per-feature (LN·S, C)×(C, B) matmuls
    run with N=B=32 of 128 MXU lanes utilized and a ``d``-step unrolled
    store chain per grid step, while the XLA formulation contracts ONE
    (T·LN·S, C)×(d·C·B one-hot) einsum per chunk with a deeper effective
    M — and XLA's scan fusion already keeps the masked-stats transient
    out of HBM (the exact mechanism that retired ``fused_lloyd_stats``
    at k=256/d=8: 112M vs 270M rec/s/chip).  The kernel adds grid
    overhead without cutting traffic XLA hadn't.  ADJUDICATION IS NOW
    AUTOMATIC: every ``rf20``/``gbt20`` bench row on a TPU sweep records
    ``tree_pallas_vs_xla`` (this kernel vs the XLA scan, >1 = kernel
    wins) — adopt by flipping the default only after it clears 1.05 on
    two consecutive fenced on-chip sweeps; until then the A/B rides
    every sweep for free.  ``BENCH_TREE_PALLAS=1`` still forces the
    HEADLINE measurement through the kernel for manual runs.
    """
    if interpret is None:
        interpret = not _on_tpu()
    d, n = binned_t.shape
    S = base_t.shape[0]
    if n == 0:
        T = w_tree.shape[0]
        return jnp.zeros((T, level_nodes, d, B, S), jnp.float32)
    if block_rows is None:
        # stats (LN·S, C) is the big VMEM tenant; keep it ≲2 MB
        block_rows = 2048
        while block_rows > 128 and 4 * level_nodes * S * block_rows > (2 << 20):
            block_rows //= 2
    pad = (-n) % block_rows
    if pad:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, pad)))
        base_t = jnp.pad(base_t, ((0, 0), (0, pad)))
        w_tree = jnp.pad(w_tree, ((0, 0), (0, pad)))
        pos = jnp.pad(pos, ((0, 0), (0, pad)), constant_values=-1)
    return _hist_call(
        binned_t, base_t, w_tree, pos,
        level_nodes=level_nodes, S=S, B=B,
        block_rows=block_rows, interpret=bool(interpret),
    )
