"""Pallas TPU kernels for the clustering hot loop.

SURVEY.md §3.3 names the Lloyd assignment/accumulation step as "the kernel
to own (Pallas)": Spark MLlib runs it row-by-row on the JVM inside
``treeAggregate`` (reference ``mllearnforhospitalnetwork.py`` delegates
every ``KMeans.fit``-style call to that machinery).  The XLA fallback in
``models/kmeans.py`` already batches it onto the MXU, but materializes the
``(rows, k)`` distance matrix **and** a same-shaped one-hot in HBM between
the matmul and the ``segment_sum``.  The fused kernel here keeps one row
block resident in VMEM and produces the per-block sufficient statistics
directly:

    HBM traffic per block:  x  in   (B·d floats)
                            sums/counts out  (k·d + k, once per pass)

instead of ``B·d + 2·B·k`` — for the BASELINE north star (k=256, d≈8)
that is a ~65× cut in bytes moved, turning an HBM-bound loop compute-bound.

Two entry points:

``fused_lloyd_stats``  — one pass over a row shard: weighted center sums,
                         counts, total cost.  Drives the KMeans fit when
                         ``KMeans(use_pallas=True)`` (model axis must be 1).
``fused_assign``       — distance+argmin only (opt-in predict path).

Both run in interpreter mode off-TPU so the CPU test mesh exercises the
exact kernel code path (tests/test_pallas.py).

**Status (measured, v5e single chip, k=256 d=8 n=10M, 2026-07-29):** the
XLA ``lax.scan`` path in models/kmeans.py sustains ~270M records/s/chip;
this kernel ~112M (block 2048; ≥4096 exceeds VMEM), and the gap is
VPU-chain/overhead-bound, not matmul-precision-bound (DEFAULT-precision
matmuls measure *slower*, 83M).  At d=8 the workload is too skinny for a
hand-scheduled win — XLA's fusion already keeps the (rows, k)
intermediates out of HBM inside the scan body.  The kernels therefore stay
**opt-in** (``use_pallas=True``): correct, TPU-compiled, parity-tested,
and the starting point for wide-d workloads where the fused accumulation
should pay off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_BIG = 1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # backend not initialized yet
        return False


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose ``vma`` (varying-across-mesh-axes set, checked
    by shard_map in JAX ≥0.9) is the union of the operands' — so the kernels
    compose with shard_map without the caller threading axis names in."""
    vma = None
    for op in operands:
        v = getattr(jax.typeof(op), "vma", None)
        if v:
            vma = v if vma is None else vma | v
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pick_block_rows(n: int, k: int, d: int, requested: int | None) -> int:
    """Auto block size: largest power-of-two whose VMEM-resident buffers
    (padded x block + the (B, k) distance/one-hot intermediates) stay
    within budget.  An explicit ``requested`` is honored as-is (clamped to
    ≥8) — callers tuning for a specific chip own the VMEM math."""
    if requested is not None:
        return max(requested, 8)
    # ~4 live (B, k) f32 intermediates (cross, d2, one-hot, compare) plus
    # the padded x block; 10 MB budget picks 2048 at k=256/d=8, which is
    # the largest block that compiles on v5e (4096 exceeds scoped VMEM).
    budget = 10 * 1024 * 1024
    b = 8192
    while b > 8 and 4 * b * (max(d, 128) + 4 * max(k, 128)) > budget:
        b //= 2
    return max(b, 8)


def _lloyd_kernel(x_ref, w_ref, c_ref, cvalid_ref, sums_ref, counts_ref, cost_ref):
    """Grid dim 0 walks row blocks; outputs revisit block (0, 0) every step
    (TPU grid is sequential per core), so they act as VMEM accumulators."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[:] = jnp.zeros_like(cost_ref)

    x = x_ref[:]                      # (B, d)
    w = w_ref[:]                      # (B, 1)
    c = c_ref[:]                      # (k, d)
    cvalid = cvalid_ref[:]            # (1, k)

    x_sq = jnp.sum(x * x, axis=1, keepdims=True)          # (B, 1)
    c_sq = jnp.sum(c * c, axis=1, keepdims=True)          # (k, 1)
    # precision=HIGHEST matches ops/distance.py — without it the MXU runs
    # bf16-truncated passes on TPU and near-tied argmins flip vs XLA.
    cross = jnp.dot(
        x, c.T, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )                                                     # MXU (B, k)
    d2 = x_sq - 2.0 * cross + c_sq.T
    d2 = jnp.maximum(d2, 0.0)
    d2 = jnp.where(cvalid > 0.0, d2, _BIG)

    min_d2 = jnp.min(d2, axis=1, keepdims=True)           # (B, 1)
    assign = jnp.argmin(d2, axis=1)                       # (B,)

    k = c.shape[0]
    onehot = (
        lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1) == assign[:, None]
    ).astype(jnp.float32) * w                             # (B, k), weighted
    sums_ref[:] += jnp.dot(
        onehot.T, x, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True).T     # (k, 1)
    # (1, 1)-shaped store — Mosaic cannot store scalars to VMEM
    cost_ref[:] += jnp.sum(min_d2 * w, axis=(0, 1), keepdims=True)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _lloyd_call(x, w, centers, c_valid, *, block_rows: int, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // block_rows,)
    sums, counts, cost = pl.pallas_call(
        _lloyd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((k, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            _out_struct((k, d), jnp.float32, x, w, centers, c_valid),
            _out_struct((k, 1), jnp.float32, x, w, centers, c_valid),
            _out_struct((1, 1), jnp.float32, x, w, centers, c_valid),
        ],
        interpret=interpret,
    )(x, w, centers, c_valid)
    return sums, counts[:, 0], cost[0, 0]


def fused_lloyd_stats(
    x: jax.Array,
    w: jax.Array,
    centers: jax.Array,
    c_valid: jax.Array,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """One fused pass: → (sums (k, d), counts (k,), cost ()).

    ``x`` (n, d) rows with validity/frequency weights ``w`` (n,);
    ``centers`` (k, d); ``c_valid`` (k,) 1.0 for live centroids (padding
    slots score +inf and never attract rows).  Rows are processed in
    VMEM-resident blocks; n is padded internally to a block multiple with
    w=0 so any n is accepted.
    """
    if interpret is None:
        interpret = not _on_tpu()
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    n, d = x.shape
    k = centers.shape[0]
    if n == 0:
        # empty grid would skip the kernel's i==0 init and return
        # uninitialized output buffers
        return (
            jnp.zeros((k, d), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
    b = _pick_block_rows(n, k, d, block_rows)
    pad = (-n) % b
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        w = jnp.pad(w, (0, pad))
    return _lloyd_call(
        x, w[:, None], centers, c_valid.astype(jnp.float32)[None, :],
        block_rows=b, interpret=bool(interpret),
    )


def _assign_kernel(x_ref, c_ref, cvalid_ref, out_ref, d2_ref):
    x = x_ref[:]
    c = c_ref[:]
    cvalid = cvalid_ref[:]
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(c * c, axis=1, keepdims=True)
    cross = jnp.dot(
        x, c.T, precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    d2 = jnp.maximum(x_sq - 2.0 * cross + c_sq.T, 0.0)
    d2 = jnp.where(cvalid > 0.0, d2, _BIG)
    out_ref[:] = jnp.argmin(d2, axis=1, keepdims=True).astype(jnp.int32)
    d2_ref[:] = jnp.min(d2, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _assign_call(x, centers, c_valid, *, block_rows: int, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    assign, d2 = pl.pallas_call(
        _assign_kernel,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _out_struct((n, 1), jnp.int32, x, centers, c_valid),
            _out_struct((n, 1), jnp.float32, x, centers, c_valid),
        ],
        interpret=interpret,
    )(x, centers, c_valid)
    return assign[:, 0], d2[:, 0]


def fused_assign(
    x: jax.Array,
    centers: jax.Array,
    c_valid: jax.Array | None = None,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """Fused distance+argmin: → (assignment (n,) int32, min-sq-dist (n,))."""
    if interpret is None:
        interpret = not _on_tpu()
    x = x.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    n, d = x.shape
    k = centers.shape[0]
    if c_valid is None:
        c_valid = jnp.ones((k,), jnp.float32)
    if n == 0:
        return jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.float32)
    b = _pick_block_rows(n, k, d, block_rows)
    pad = (-n) % b
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    a, d2 = _assign_call(
        x, centers, c_valid.astype(jnp.float32)[None, :],
        block_rows=b, interpret=bool(interpret),
    )
    return a[:n], d2[:n]
