"""Shared weighted column reductions.

One fused, jit'd pass producing every per-column sufficient statistic the
feature stages and ``ml.stat`` consume (Σw, Σw·x, Σw·x², Σw·x xᵀ, masked
min/max, L1, non-zero count).  Centralized so the masked-±sentinel idiom
and any future numeric fixes live in exactly one place; stages that need a
subset still pay only one pass (the extra O(n·d) column stats are
negligible next to the O(n·d²) Gram the heavy users already need).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: finite sentinel for masked min/max — ±inf would poison a sum-based
#: fusion and NaN-propagate through where() on some backends
_MASK_BIG = np.float32(3.4e38)


@jax.jit
def moment_stats(x: jax.Array, w: jax.Array) -> dict[str, jax.Array]:
    """Fused single pass over a weighted, padded row shard (pad rows w=0)."""
    wcol = w[:, None]
    valid = wcol > 0
    big = jnp.asarray(_MASK_BIG, x.dtype)
    return {
        "n": jnp.sum(w),
        "count": jnp.sum((w > 0).astype(x.dtype)),
        "s1": jnp.sum(x * wcol, axis=0),
        "s2": jnp.sum(x * x * wcol, axis=0),
        "xtx": (x * wcol).T @ x,
        "min": jnp.min(jnp.where(valid, x, big), axis=0),
        "max": jnp.max(jnp.where(valid, x, -big), axis=0),
        "l1": jnp.sum(jnp.abs(x) * wcol, axis=0),
        "nnz": jnp.sum(((x != 0) & valid).astype(x.dtype) * wcol, axis=0),
    }


def host_moments(x: jax.Array, w: jax.Array) -> dict[str, np.ndarray]:
    """moment_stats fetched to host as float64."""
    return {k: np.asarray(v, dtype=np.float64) for k, v in moment_stats(x, w).items()}
