"""Span-based tracing threaded through the real unit-of-work chain.

A **span** is one timed, named piece of work: ``{trace_id, span_id,
parent_id, name, t0, dur_s, thread, attrs}``.  Spans nest through a
``contextvars.ContextVar``, so a streaming batch's span automatically
parents the SQL query it dispatches, the fit stages the update runs,
and the lifecycle transition it triggers — and one ``trace_id`` queried
from the span log reconstructs the whole ingest→SQL→fit→serve→promotion
timeline (:func:`timeline`; ``examples/observability_demo.py`` walks
one end to end).

Cost discipline (the ``utils/faults.py`` uninstalled-site rule): with
no :class:`Tracer` installed, :func:`span` returns a shared no-op
singleton — no allocation, two attribute loads and an ``is None`` test
— so the serve hot path pays nothing for instrumentation it isn't
using (pinned allocation-free by ``tests/test_obs.py`` and the
``obs_overhead`` bench gate).

Durability: spans are buffered and appended to a JSONL log through the
same append/torn-tail discipline as the streaming WAL and the lifecycle
journal (``streaming/wal.py``) — a crash mid-flush costs at most the
batch being written, and readers skip torn lines.  The
:class:`~.flight_recorder.FlightRecorder` ring is fed on every span end
while a tracer is installed, so a postmortem dump carries the spans
leading up to the failure.

Instrumentation registry: :data:`REGISTERED_SPANS` is the literal set
of span names the codebase emits and :data:`SITE_COVERAGE` maps every
named fault site to the span under which it fires in the instrumented
end-to-end chain.  ``tools/check_obs.py`` (run in tier-1) statically
cross-checks both against the source, so a new fault site or journal
state cannot silently ship without observability.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Iterator

from . import flight_recorder as _flight

#: every span name the instrumentation emits.  ``stage.*`` covers the
#: dynamic StageClock sink (``stage.<clock-stage-name>``).  Checked
#: against the source by tools/check_obs.py — keep it a pure literal.
REGISTERED_SPANS = (
    "stream.batch",
    "stream.quarantine",
    "stage.*",
    "sql.query",
    "sql.view.maintain",  # per-commit delta fold into a materialized view
    "sql.view.serve",     # answering a query/read from view state
    "serve.request",
    "lifecycle.transition",
    "lifecycle.retrain",
    "lifecycle.promote",
    "lifecycle.rollback",
    "lifecycle.feedback",
    "farm.fit",          # model-farm fleet fit (one dispatch, T tenants)
    "farm.refit",        # drifted-subset masked refit
    "farm.predict",      # tenant-routed predict (host convenience path)
    "fleet.request",     # serving-fleet front door: admission→route→answer
    "fleet.promote",     # atomic fleet-wide swap (every replica or none)
    "fleet.proc",        # replica worker-process spawn/kill (proc fleet)
    "router.route",      # the routing decision (policy, chosen replica)
    "obs.demo",          # example/bench root spans
    "fed.round",         # one federated fit round: collect→merge→fit→broadcast
    "soak.run",          # one compressed-day soak run (root of the E2E trace)
    "table.seal",        # cold batches → sealed CRC-manifested segment
    "table.retire",      # superseded part files deleted under retention
    "table.scrub",       # segment CRC audit: quarantine + rebuild rot
    "tune.store",        # autotuner trial-store durable commit
    "tune.select",       # one live-retune decision: select→journal→apply
)

#: fault site (fnmatch glob) → the registered span that encloses or
#: records it in the instrumented pipelines.  tools/check_obs.py fails
#: when a ``fault_point``/``torn_point``/``mangle_bytes``/
#: ``corrupt_data`` site in the source has no entry here, or an entry
#: points at an unregistered span.
SITE_COVERAGE = {
    "stream.after_*": "stream.batch",
    "source.read_file": "stream.batch",
    "sink.write_part": "stream.batch",
    "wal.append": "stream.batch",
    "ingest.csv_text": "stream.batch",
    "serve.predict": "serve.request",
    "fit_ckpt.*": "lifecycle.retrain",
    "model_io.save.*": "lifecycle.retrain",
    "lifecycle.journal.append": "lifecycle.transition",
    "lifecycle.retrain.commit": "lifecycle.retrain",
    "lifecycle.shadow.start": "lifecycle.retrain",
    "lifecycle.registry.flip": "lifecycle.promote",
    "lifecycle.registry.swap": "lifecycle.promote",
    "lifecycle.rollback": "lifecycle.rollback",
    "lifecycle.feedback.*": "lifecycle.feedback",
    "fleet.swap.*": "fleet.promote",
    "fleet.proc.*": "fleet.proc",   # worker spawn / rpc mangle / SIGKILL
    "sql.view.maintain": "sql.view.maintain",
    "fed.round.*": "fed.round",
    "soak.schedule.tick": "soak.run",      # chaos-event dispatch point
    "soak.phase.transition": "soak.run",   # diurnal phase boundary
    "soak.report.commit": "soak.run",      # SoakReport atomic-write commit
    "soak.replica.kill": "soak.run",       # replica-kill postmortem notify
    "table.seal.*": "table.seal",          # stage (segment+manifest) / commit
    "table.retire.commit": "table.retire",  # log-first part retirement
    "table.scrub.repair": "table.scrub",   # quarantine-and-rebuild point
    "tune.store.commit": "tune.store",     # trial merge atomic-write commit
    "tune.select.apply": "tune.select",    # between retune intent and apply
}

_CTX: contextvars.ContextVar = contextvars.ContextVar("obs_trace", default=None)


# span/trace ids: a per-process random base + a monotone counter — the
# uniqueness of urandom at ~10x less hot-path cost (ids are minted twice
# per root span; ``next()`` on a count is atomic under the GIL)
_ID_BASE = os.urandom(4).hex()
_ID_COUNT = itertools.count(1)


def _new_id() -> str:
    return f"{_ID_BASE}{next(_ID_COUNT) & 0xFFFFFFFF:08x}"


class Tracer:
    """Span sink: buffers finished spans, flushes them as JSONL.

    ``path=None`` keeps every span in memory (tests, short demos);
    with a path, spans land in batches of ``flush_every`` through ONE
    torn-tail-repaired append + fsync (``streaming/wal.append_lines``),
    so per-span cost stays amortized.  ``close()``/``flush()`` drain
    the buffer; :func:`active` does it on scope exit.

    ``flush_every`` trades postmortem completeness for hot-path cost:
    each flush is an fsync, and on a 1-core host an fsync every 256
    request spans measurably taxes the serve path it is observing
    (obs_overhead leg: 0.974 → 0.997 of uninstrumented at 2048+).
    Spans are *telemetry* — the crash story is the flight recorder's
    CRC-dumped ring, so losing an unflushed tail to a crash costs
    visibility, never correctness.
    """

    def __init__(self, path: str | None = None, flush_every: int = 2048):
        self.path = path
        self.flush_every = max(int(flush_every), 1)
        self.spans: list[dict] = []      # in-memory (path=None) transcript
        self.emitted = 0
        self.dropped = 0
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)

    def emit(self, span: dict) -> None:
        flush = False
        with self._lock:
            self.emitted += 1
            if self.path is None:
                if len(self.spans) < 1_000_000:
                    self.spans.append(span)
                else:
                    self.dropped += 1
            else:
                self._buf.append(span)
                flush = len(self._buf) >= self.flush_every
        try:  # the ring is bounded and lock-light; never let it raise
            _flight._RECORDER.note_span(span)
        except Exception:  # noqa: BLE001 — observability must not break work
            pass
        if flush:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf or self.path is None:
            return
        from ..streaming.wal import append_lines  # lazy: avoids import cycle

        append_lines(self.path, buf, site=None)

    def close(self) -> None:
        self.flush()


class _NoopSpan:
    """The uninstalled-tracer singleton: every operation a real span
    supports, at the cost of a method call — and zero allocation."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def note(self, key: str, value) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = (
        "name", "attrs", "trace_id", "span_id", "parent_id",
        "_tracer", "_token", "_t0", "_t0_epoch",
    )

    def __init__(self, tracer: Tracer, name: str, attrs: dict | None):
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self._tracer = tracer
        parent = _CTX.get()
        if parent is None:
            self.trace_id = _new_id()
            self.parent_id = None
        else:
            self.trace_id, self.parent_id = parent
        self.span_id = _new_id()
        self._token = None
        self._t0 = 0.0
        self._t0_epoch = 0.0

    def __enter__(self) -> "_Span":
        self._token = _CTX.set((self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        self._t0_epoch = time.time()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        if self._token is not None:
            _CTX.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        self._tracer.emit({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t0": self._t0_epoch,
            "dur_s": dur,
            "thread": threading.current_thread().name,
            "attrs": self.attrs,
        })
        return False

    def note(self, key: str, value) -> None:
        """Attach one attribute (positional on purpose: the hot path
        must not build kwargs dicts when tracing is off)."""
        self.attrs[key] = value


# ---------------------------------------------------------------- install
_TRACER: Tracer | None = None


def install(tracer: Tracer) -> Tracer:
    global _TRACER
    _TRACER = tracer
    return tracer


def clear() -> None:
    global _TRACER
    t, _TRACER = _TRACER, None
    if t is not None:
        t.close()


@contextmanager
def active(tracer: Tracer) -> Iterator[Tracer]:
    """``with trace.active(Tracer(path)): ...`` — installed for the
    block, flushed and uninstalled on exit."""
    install(tracer)
    try:
        yield tracer
    finally:
        clear()


def enabled() -> bool:
    return _TRACER is not None


def span(name: str, attrs: dict | None = None):
    """Open a span (use as a context manager).  With no tracer installed
    this returns the shared no-op singleton: no allocation, ever."""
    t = _TRACER
    if t is None:
        return _NOOP
    return _Span(t, name, attrs)


def record_span(name: str, dur_s: float, attrs: dict | None = None) -> None:
    """Emit an already-timed span (no context push) as a child of the
    current context — the StageClock sink: a clock stage that just
    finished becomes span ``stage.<name>`` under whatever unit of work
    is in flight on this thread.  No-op (one load + None test) when no
    tracer is installed."""
    t = _TRACER
    if t is None:
        return
    parent = _CTX.get()
    trace_id, parent_id = (parent if parent is not None else (_new_id(), None))
    t.emit({
        "trace_id": trace_id,
        "span_id": _new_id(),
        "parent_id": parent_id,
        "name": name,
        "t0": time.time() - dur_s,
        "dur_s": dur_s,
        "thread": threading.current_thread().name,
        "attrs": dict(attrs) if attrs else {},
    })


def current_trace_id() -> str | None:
    ctx = _CTX.get()
    return None if ctx is None else ctx[0]


# ---------------------------------------------------------------- reading
_SPAN_KEYS = ("trace_id", "span_id", "name", "t0", "dur_s")


def read_spans(path: str) -> list[dict]:
    """All intact spans from a span log — the WAL reader (torn/corrupt
    lines skipped; a crash mid-flush never hides earlier spans) plus a
    span-shape filter."""
    from ..streaming.wal import read_lines  # lazy: avoids import cycle

    return [
        o for o in read_lines(path)
        if isinstance(o, dict) and all(k in o for k in _SPAN_KEYS)
    ]


def timeline(spans: list[dict], trace_id: str) -> list[dict]:
    """One trace's spans in start order — the reconstructed end-to-end
    story of a unit of work (ingest → SQL → fit → serve → promotion)."""
    return sorted(
        (s for s in spans if s.get("trace_id") == trace_id),
        key=lambda s: (s["t0"], s["dur_s"]),
    )


def format_timeline(spans: list[dict]) -> str:
    """Human-readable rendering of :func:`timeline` output."""
    if not spans:
        return "(no spans)"
    t_base = min(s["t0"] for s in spans)
    lines = []
    by_id = {s["span_id"]: s for s in spans}

    def depth(s: dict) -> int:
        d, p = 0, s.get("parent_id")
        while p in by_id and d < 32:
            d, p = d + 1, by_id[p].get("parent_id")
        return d

    for s in spans:
        attrs = ", ".join(
            f"{k}={v}" for k, v in sorted((s.get("attrs") or {}).items())
        )
        lines.append(
            f"+{s['t0'] - t_base:8.3f}s {'  ' * depth(s)}{s['name']}"
            f" [{s['dur_s'] * 1e3:.1f} ms]{('  ' + attrs) if attrs else ''}"
        )
    return "\n".join(lines)


def by_name(spans: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for s in spans:
        out[s["name"]] = out.get(s["name"], 0) + 1
    return out
