"""Unified observability fabric: metrics, tracing, postmortem capture.

One place to ask "where did this request/batch/retrain spend its time,
and what was the system doing when it broke":

* :mod:`registry` — process-global :class:`~.registry.MetricsRegistry`
  of counters / gauges / fixed-bucket mergeable histograms; every
  subsystem's metric source lands here (``serve.*``, ``stream.*``,
  ``sql.*``, drift PSI, breaker states, lifecycle phase) either by
  writing directly or through a registered pull-collector.
* :mod:`trace`    — span-based tracing threaded through the real
  unit-of-work chain (streaming batch → SQL fingerprint → fit stages →
  serve request → lifecycle transition), emitted as JSONL spans with
  the WAL append/torn-tail discipline; near-zero cost uninstalled
  (the ``utils/faults.py`` uninstalled-site discipline).
* :mod:`export`   — Prometheus-text and JSON snapshot exporters over
  the registry (the schema downstream scrapers pin on).
* :mod:`flight_recorder` — bounded ring of recent spans/metric marks,
  dumped atomically (CRC32C) on breaker trip, quarantine, lifecycle
  rollback, or :class:`~..utils.faults.InjectedCrash` — every chaos
  kill leaves a postmortem artifact.

This ``__init__`` stays import-light on purpose: ``utils/metrics.py``
(imported by nearly everything) shims onto :mod:`registry`, so pulling
the sibling submodules in eagerly here would cycle back through
``streaming``/``serve``.  They load lazily on first attribute access.
"""

from __future__ import annotations

from . import registry
from .registry import FixedHistogram, MetricsRegistry, global_registry

_LAZY = ("trace", "export", "flight_recorder")

__all__ = [
    "FixedHistogram",
    "MetricsRegistry",
    "global_registry",
    "registry",
    *_LAZY,
]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
