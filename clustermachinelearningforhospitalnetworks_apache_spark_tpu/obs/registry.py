"""Process-global metrics registry: counters, gauges, histograms.

The one metrics surface (ISSUE 10).  Before this module the repo held
three overlapping metric holders — ``utils/metrics.py`` (training-side
counters/gauges/stage timings), ``serve/metrics.py::ServingMetrics``
(latency reservoir + its own registry), and the streaming driver's
ad-hoc ``stream.*`` counter dict — none of which could answer a fleet
question ("what is this process's p99, PSI, and breaker state *right
now*") from one snapshot.  This registry is that place:

* **counters** monotonically accumulate (``inc``), **gauges** hold the
  last value (``set``) — both plain dicts updated under the GIL, the
  same cost profile the old ``utils.metrics`` had;
* **histograms** are fixed-bucket and **mergeable** — the
  ``quality/sketches.py`` discipline (explicit under/overflow bins,
  counts addable across shards/processes) applied to latency and fill
  distributions, so p50/p99 come from bounded state instead of an
  unbounded (or sampled) reservoir;
* **collectors** are pull-sources registered by subsystems that hold
  their own state (breaker snapshots, drift monitors, the lifecycle
  phase, SQL dispatch routes): they contribute at *export* time only,
  so the hot path never pays for observability it isn't using — the
  ``utils/faults.py`` uninstalled-site discipline.  Collectors are held
  by weakref: a test's server dying unregisters it automatically.

``global_registry()`` is the process-wide instance every exporter
reads; subsystem-owned registries (a server's, a stream's) stay
isolated for tests and fold upward through collectors.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
import weakref
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Sequence

import numpy as np

#: default latency histogram edges (seconds): log-spaced 100µs → 10s,
#: 4 buckets/decade — coarse enough to stay tiny, fine enough that p99
#: interpolation lands within ~30% of the true tail
LATENCY_EDGES_S = tuple(
    round(10.0 ** (e / 4.0), 6) for e in range(-16, 5)
)

#: default ratio histogram edges (batch fill, shares): uniform on [0, 1]
RATIO_EDGES = tuple(i / 16.0 for i in range(17))


class FixedHistogram:
    """Fixed-edge, mergeable histogram with explicit under/overflow bins.

    ``counts`` has ``len(edges) + 1`` entries — ``counts[0]`` is the
    underflow bin (< edges[0]), ``counts[-1]`` the overflow bin
    (≥ edges[-1]) — exactly the ``quality/sketches.py::FeatureSketch``
    layout, so two histograms over the same edges merge by addition.
    ``sum``/``count`` ride along so the exact mean survives bucketing
    (Prometheus ``_sum``/``_count`` semantics).
    """

    __slots__ = ("edges", "counts", "count", "sum", "_lock")

    def __init__(self, edges: Sequence[float]):
        self.edges = np.asarray(edges, dtype=np.float64)
        if self.edges.size < 2:
            raise ValueError("FixedHistogram needs at least 2 bin edges")
        if np.any(np.diff(self.edges) <= 0):
            raise ValueError("histogram edges must be strictly increasing")
        self.counts = np.zeros(self.edges.size + 1, dtype=np.float64)
        self.count = 0.0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, values) -> None:
        v = np.atleast_1d(np.asarray(values, dtype=np.float64)).ravel()
        if v.size == 0:
            return
        idx = np.searchsorted(self.edges, v, side="right")
        # a value exactly on the top edge belongs to the last interior
        # bin, not overflow (sketches.py discipline; keeps Prometheus
        # le-buckets inclusive: fill ratio 1.0 lands in le="1")
        idx[v == self.edges[-1]] = self.edges.size - 1
        with self._lock:
            self.counts += np.bincount(
                idx, minlength=self.counts.size
            ).astype(np.float64)
            self.count += float(v.size)
            self.sum += float(v.sum())

    def merge(self, other: "FixedHistogram") -> "FixedHistogram":
        if self.edges.size != other.edges.size or not np.allclose(
            self.edges, other.edges
        ):
            raise ValueError("cannot merge histograms with different edges")
        with self._lock:
            self.counts = self.counts + other.counts
            self.count += other.count
            self.sum += other.sum
        return self

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count > 0 else float("nan")

    def quantile(self, q: float) -> float:
        """Interpolated quantile over ALL mass.  The open-ended bins get
        synthetic extents (underflow: down to 0 or a mirrored width;
        overflow: one bin width past the top) so a distribution that
        lands mostly below/above the edges still yields a finite,
        monotone estimate instead of NaN."""
        with self._lock:
            counts = self.counts.copy()
        total = counts.sum()
        if total <= 0:
            return float("nan")
        e = self.edges
        lo0 = min(0.0, float(e[0]) - float(e[1] - e[0]))
        hi_end = float(e[-1]) + float(e[-1] - e[-2])
        lows = np.concatenate([[lo0], e])
        highs = np.concatenate([e, [hi_end]])
        cum = np.cumsum(counts)
        target = min(max(q, 0.0), 1.0) * total
        i = int(np.searchsorted(cum, target))
        i = min(i, counts.size - 1)
        prev = cum[i - 1] if i > 0 else 0.0
        frac = 0.0 if counts[i] == 0 else (target - prev) / counts[i]
        return float(lows[i] + frac * (highs[i] - lows[i]))

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "edges": [float(x) for x in self.edges],
                "counts": [float(c) for c in self.counts],
                "count": float(self.count),
                "sum": float(self.sum),
            }


@dataclass
class StageTiming:
    """One timed pipeline stage (the pre-ISSUE-10 ``utils.metrics``
    surface, kept verbatim — bench and examples consume it)."""

    name: str
    seconds: float
    rows: int | None = None

    @property
    def rows_per_sec(self) -> float | None:
        if self.rows is None or self.seconds <= 0:
            return None
        return self.rows / self.seconds


class MetricsRegistry:
    """Counters + gauges + histograms + stage timings, one object.

    Drop-in superset of the old ``utils.metrics.MetricsRegistry``: the
    ``counters``/``gauges`` dict attributes, ``inc``/``set``/``stage``/
    ``time_stage``/``snapshot`` all behave identically, so every
    existing call site (streaming drivers, serve metrics, health
    endpoints, tests) keeps working unchanged.
    """

    def __init__(self) -> None:
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, FixedHistogram] = {}
        self.timings: list[StageTiming] = []
        self._hist_lock = threading.Lock()
        #: key -> weakref-wrapped zero-arg callable returning a metrics
        #: fragment ``{"counters": {...}, "gauges": {...}}``
        self._collectors: dict[str, Callable[[], dict | None]] = {}
        self._collector_lock = threading.Lock()

    # ------------------------------------------------------------ write
    def inc(self, name: str, value: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + value

    def set(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def hist(
        self, name: str, edges: Sequence[float] = LATENCY_EDGES_S
    ) -> FixedHistogram:
        """Get-or-create the named histogram (edges bind on first use)."""
        h = self.histograms.get(name)
        if h is None:
            with self._hist_lock:
                h = self.histograms.get(name)
                if h is None:
                    h = FixedHistogram(edges)
                    self.histograms[name] = h
        return h

    def observe(
        self, name: str, value, edges: Sequence[float] = LATENCY_EDGES_S
    ) -> None:
        self.hist(name, edges).observe(value)

    @contextmanager
    def stage(self, name: str, rows: int | None = None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings.append(
                StageTiming(name=name, seconds=time.perf_counter() - t0, rows=rows)
            )

    def time_stage(self, name: str, fn, *args, rows: int | None = None, **kw):
        with self.stage(name, rows=rows):
            return fn(*args, **kw)

    # ------------------------------------------------------- collectors
    def register_collector(self, key: str, owner: Any, fn: Callable[[Any], dict]) -> None:
        """Register a pull-source: at export time ``fn(owner)`` runs and
        its ``{"counters": ..., "gauges": ...}`` fragment merges into the
        collected snapshot.  ``owner`` is held by WEAKREF — when it dies
        the collector silently unregisters, so a long-lived global
        registry never pins a test's server alive or reports its ghost.
        """
        ref = weakref.ref(owner)

        def pull() -> dict | None:
            o = ref()
            return None if o is None else fn(o)

        with self._collector_lock:
            self._collectors[key] = pull

    def unregister_collector(self, key: str) -> None:
        with self._collector_lock:
            self._collectors.pop(key, None)

    def collector_keys(self) -> list[str]:
        with self._collector_lock:
            return sorted(self._collectors)

    # ------------------------------------------------------------- read
    def snapshot(self) -> dict[str, Any]:
        """The pre-ISSUE-10 shape plus ``histograms`` — own state only
        (no collectors); :meth:`collect` is the full pull."""
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                k: h.to_dict() for k, h in sorted(self.histograms.items())
            },
            "stages": [
                {
                    "name": t.name,
                    "seconds": round(t.seconds, 6),
                    "rows": t.rows,
                    "rows_per_sec": None
                    if t.rows_per_sec is None
                    else round(t.rows_per_sec, 1),
                }
                for t in self.timings
            ],
        }

    def collect(self) -> dict[str, Any]:
        """Own state + every live collector's fragment — what the
        exporters serialize.  A collector that raises contributes an
        ``error`` note instead of taking the export down; dead weakrefs
        are pruned as a side effect."""
        out = self.snapshot()
        dead: list[str] = []
        with self._collector_lock:
            items = list(self._collectors.items())
        for key, pull in items:
            try:
                frag = pull()
            except Exception as e:  # noqa: BLE001 — observability must
                # never be the thing that breaks
                out["counters"][f"obs.collector_errors.{key}"] = (
                    out["counters"].get(f"obs.collector_errors.{key}", 0.0) + 1
                )
                out["gauges"][f"obs.collector_broken.{key}"] = 1.0
                continue
            if frag is None:
                dead.append(key)
                continue
            # counters SUM across sources (two servers' request counts
            # are one process total); gauges are point-in-time — last
            # writer wins, per-entity gauges disambiguate via labels
            for name, value in (frag.get("counters") or {}).items():
                out["counters"][name] = out["counters"].get(name, 0.0) + value
            for name, value in (frag.get("gauges") or {}).items():
                out["gauges"][name] = value
            # histogram fragments arrive pre-serialized (to_dict shape);
            # same-name fragments overwrite — per-source names/labels
            # disambiguate where that matters
            for name, value in (frag.get("histograms") or {}).items():
                out["histograms"][name] = value
        if dead:
            with self._collector_lock:
                for key in dead:
                    self._collectors.pop(key, None)
        # export-side cardinality backstop: a family that grew past the
        # series budget (per-tenant labels that skipped cohort_label)
        # leaves collect() cohort-bucketed, never 10k series wide
        return cap_label_cardinality(out)

    def merge_registry(self, other: "MetricsRegistry") -> None:
        """Fold another registry's state in (counters add, gauges take
        the other's value, histograms merge) — the cross-shard reduce."""
        for k, v in other.counters.items():
            self.inc(k, v)
        for k, v in other.gauges.items():
            self.set(k, v)
        for k, h in other.histograms.items():
            self.hist(k, h.edges).merge(h)


_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """The process-wide registry every exporter reads."""
    return _GLOBAL


# --------------------------------------------------------------------------
# Label-cardinality guard.
#
# A per-TENANT label on a farm metric is a 10,000-series Prometheus export
# waiting to happen (every scrape carries every series ever written).  Two
# defenses, both here so every writer and every exporter share them:
#
# * :func:`cohort_label` is the WRITE-side discipline — the farm labels
#   its metrics by a bounded tenant *cohort* (stable hash of the tenant id
#   into :data:`N_COHORTS` buckets), never by raw tenant id;
# * :func:`cap_label_cardinality` is the EXPORT-side backstop applied by
#   :meth:`MetricsRegistry.collect` — any labeled family that still grows
#   past :data:`MAX_SERIES_PER_FAMILY` distinct label combinations gets
#   its label VALUES cohort-bucketed at collect time (counters sum into
#   the bucket, gauges keep the max — the conservative alarm view — and
#   same-edge histograms merge), with an ``obs.cardinality_capped{metric=}``
#   counter recording that the cap fired.
# --------------------------------------------------------------------------

#: distinct label-combination budget per metric family at export; override
#: with the CMLHN_OBS_MAX_SERIES env var
MAX_SERIES_PER_FAMILY = int(os.environ.get("CMLHN_OBS_MAX_SERIES", "256"))

#: cohort bucket count for high-cardinality label values (tenant ids)
N_COHORTS = 32

_LABELED_RE = re.compile(r"^(?P<name>[^{]+)\{(?P<labels>.*)\}$")
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z0-9_.]+)="(?P<v>[^"]*)"')


def split_labels(name: str) -> tuple[str, dict[str, str]]:
    """``'x.y{model="los",state="open"}'`` → ``("x.y", {...})`` — the one
    parser for the brace-label convention (exporters re-use it)."""
    m = _LABELED_RE.match(name)
    if m is None:
        return name, {}
    labels = {
        lm.group("k"): lm.group("v")
        for lm in _LABEL_RE.finditer(m.group("labels"))
    }
    return m.group("name"), labels


def join_labels(base: str, labels: dict[str, str]) -> str:
    if not labels:
        return base
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{base}{{{inner}}}"


def cohort_label(value: str, n_cohorts: int = N_COHORTS) -> str:
    """Stable bounded bucket for a high-cardinality label value: the
    tenant-cohort name the farm labels its metrics with (``"c07"``)."""
    return f"c{zlib.crc32(str(value).encode()) % n_cohorts:02d}"


#: replica-count ceiling for per-replica metric labels — a fleet is a
#: few to a few dozen replicas, never tenant-shaped cardinality
MAX_REPLICAS_LABELED = 256


def replica_label(index: int) -> str:
    """Bounded, format-pinned label value for a serving-fleet replica
    (``"r03"``).  The ONLY sanctioned way to put a replica label on a
    metric — ``tools/check_obs.py`` fails the build on a brace-label
    built any other way, the same discipline that keeps tenant labels
    behind :func:`cohort_label`."""
    i = int(index)
    if not 0 <= i < MAX_REPLICAS_LABELED:
        raise ValueError(
            f"replica index {index} outside the labeled range "
            f"[0, {MAX_REPLICAS_LABELED})"
        )
    return f"r{i:02d}"


def _merge_hist_dicts(a: dict, b: dict) -> dict:
    """Bin-addition merge of two ``FixedHistogram.to_dict`` fragments when
    the edges agree; otherwise keep ``b`` (last wins, as collect does for
    same-name fragments)."""
    if a.get("edges") == b.get("edges"):
        return {
            "edges": a["edges"],
            "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
            "count": a.get("count", 0.0) + b.get("count", 0.0),
            "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        }
    return b


def cap_label_cardinality(
    snap: dict[str, Any], max_series: int | None = None
) -> dict[str, Any]:
    """Enforce the per-family series budget on a collected snapshot
    (in place; returns it).  Families within budget pass through
    untouched — per-model breaker gauges etc. keep their exact labels."""
    budget = MAX_SERIES_PER_FAMILY if max_series is None else max_series
    if budget <= 0:
        return snap
    for kind in ("counters", "gauges", "histograms"):
        table = snap.get(kind)
        if not table:
            continue
        fams: dict[str, list[str]] = {}
        for raw in table:
            base, labels = split_labels(raw)
            if labels:
                fams.setdefault(base, []).append(raw)
        for base, raws in fams.items():
            if len(raws) <= budget:
                continue
            # bucket ONLY the label keys whose distinct-value count blew
            # the budget — a low-cardinality companion label (model=,
            # state=) keeps attributing series exactly
            values_by_key: dict[str, set] = {}
            for raw in raws:
                for k, v in split_labels(raw)[1].items():
                    values_by_key.setdefault(k, set()).add(v)
            hot_keys = {
                k for k, vals in values_by_key.items() if len(vals) > budget
            } or set(values_by_key)  # combinatorial blowup with no single
            # hot key: bucket everything rather than export 10k series
            capped: dict[str, Any] = {}
            for raw in raws:
                _, labels = split_labels(raw)
                new_raw = join_labels(
                    base,
                    {
                        k: cohort_label(v) if k in hot_keys else v
                        for k, v in labels.items()
                    },
                )
                old = capped.get(new_raw)
                v = table.pop(raw)
                if old is None:
                    capped[new_raw] = v
                elif kind == "counters":
                    capped[new_raw] = old + v
                elif kind == "gauges":
                    capped[new_raw] = max(old, v)
                else:
                    capped[new_raw] = _merge_hist_dicts(old, v)
            table.update(capped)
            c = snap.setdefault("counters", {})
            key = f'obs.cardinality_capped{{metric="{base}"}}'
            c[key] = c.get(key, 0.0) + float(len(raws))
    return snap


def is_finite_number(v: Any) -> bool:
    """Shared exporter guard: JSON/Prometheus emit numbers only."""
    return isinstance(v, (int, float)) and math.isfinite(v)
