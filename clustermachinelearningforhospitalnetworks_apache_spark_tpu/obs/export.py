"""Registry exporters: Prometheus exposition text + JSON snapshots.

Both read the SAME :meth:`~.registry.MetricsRegistry.collect` pull (own
counters/gauges/histograms plus every live collector fragment), so the
text a scraper sees and the JSONL a dashboard tails can never disagree.

Naming convention (pinned by ``tests/test_obs.py`` — downstream
scrapers rely on it):

* internal dotted names (``stream.batches``, ``serve.latency_seconds``)
  become ``cmlhn_``-prefixed snake names (``cmlhn_stream_batches``);
  counters additionally get the Prometheus ``_total`` suffix;
* per-entity breakdowns ride as labels, written into the internal name
  with Prometheus brace syntax (``serve.breaker_open{model="los"}``) —
  :func:`split_labels` parses them back out;
* histograms export cumulative ``_bucket{le=...}`` + ``_sum`` +
  ``_count`` (the under/overflow bins fold into the first bucket and
  ``+Inf`` respectively).

The JSON snapshot keeps the internal dotted names verbatim — it is the
programmatic surface (``InferenceServer.health``/``bench.py`` consume
it), while the text form is the scrape surface.
"""

from __future__ import annotations

import re
import time
from typing import Any

from .registry import (
    MetricsRegistry,
    global_registry,
    is_finite_number,
    split_labels,  # noqa: F401 — the label parser lives with the label
    # writers in registry.py (cohort_label / cap_label_cardinality need
    # it too); re-exported here because exporters are its public home.
    # Both exporters read collect(), which has already applied the
    # label-cardinality backstop — a runaway tenant-labeled family
    # reaches the scrape page cohort-bucketed, never 10k series wide.
)

PREFIX = "cmlhn"

_BAD = re.compile(r"[^a-zA-Z0-9_]")


def prom_name(name: str) -> str:
    """Internal dotted name → Prometheus metric name."""
    return f"{PREFIX}_{_BAD.sub('_', name.strip())}"


def label_str(labels: dict[str, str], extra: str = "") -> str:
    parts = [
        f'{_BAD.sub("_", k)}="{v}"' for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    """The ``/metrics`` page: one TYPE line per family, values grouped
    under it, keys emitted in sorted order so the output is diffable."""
    reg = registry if registry is not None else global_registry()
    snap = reg.collect()
    families: dict[str, tuple[str, list[str]]] = {}

    def add(kind: str, raw: str, value: float, suffix: str = "") -> None:
        base, labels = split_labels(raw)
        fam = prom_name(base) + suffix
        families.setdefault(fam, (kind, []))[1].append(
            f"{fam}{label_str(labels)} {value:g}"
        )

    for raw, v in sorted(snap["counters"].items()):
        if is_finite_number(v):
            add("counter", raw, float(v), "_total")
    for raw, v in sorted(snap["gauges"].items()):
        if is_finite_number(v):
            add("gauge", raw, float(v))
    for raw, h in sorted(snap["histograms"].items()):
        base, labels = split_labels(raw)
        fam = prom_name(base)
        lines = families.setdefault(fam, ("histogram", []))[1]
        cum = 0.0
        # counts[0] is the underflow bin: cumulative ≤ edges[0] includes it
        for edge, c in zip(h["edges"], h["counts"][:-1]):
            cum += c
            le = 'le="%g"' % edge
            lines.append(f"{fam}_bucket{label_str(labels, le)} {cum:g}")
        inf = 'le="+Inf"'
        lines.append(
            f"{fam}_bucket{label_str(labels, inf)} {cum + h['counts'][-1]:g}"
        )
        lines.append(f"{fam}_sum{label_str(labels)} {h['sum']:g}")
        lines.append(f"{fam}_count{label_str(labels)} {h['count']:g}")
    out = []
    for fam in sorted(families):
        typ, lines = families[fam]
        out.append(f"# TYPE {fam} {typ}")
        out.extend(lines)
    return "\n".join(out) + "\n"


def json_snapshot(registry: MetricsRegistry | None = None) -> dict[str, Any]:
    """Schema-stable JSON view of :meth:`collect` (internal names kept):
    ``{time, counters, gauges, histograms}`` — the programmatic twin of
    the Prometheus page."""
    reg = registry if registry is not None else global_registry()
    snap = reg.collect()
    return {
        "time": round(time.time(), 3),
        "counters": {
            k: v for k, v in sorted(snap["counters"].items())
            if is_finite_number(v)
        },
        "gauges": {
            k: v for k, v in sorted(snap["gauges"].items())
            if is_finite_number(v)
        },
        "histograms": snap["histograms"],
    }


def write_snapshot(path: str, registry: MetricsRegistry | None = None) -> dict:
    """Append one JSON snapshot line to ``path`` (WAL append/torn-tail
    discipline — a scrape log survives crashes the same way every other
    log here does) and return the snapshot."""
    snap = json_snapshot(registry)
    from ..streaming.wal import append_lines  # lazy: avoids import cycle

    append_lines(path, [snap], site=None)
    return snap


def read_snapshots(path: str) -> list[dict]:
    """All intact snapshot lines (the WAL reader skips torn lines)."""
    from ..streaming.wal import read_lines  # lazy: avoids import cycle

    return [
        o for o in read_lines(path) if isinstance(o, dict) and "counters" in o
    ]


def schema(registry: MetricsRegistry | None = None) -> list[tuple]:
    """The scrape contract as data: sorted ``(prom_name, type,
    label_keys)`` triples — what the pinned-schema test freezes."""
    reg = registry if registry is not None else global_registry()
    snap = reg.collect()
    rows: set[tuple] = set()
    for kind, key in (
        ("counter", "counters"), ("gauge", "gauges"),
        ("histogram", "histograms"),
    ):
        for raw in snap[key]:
            base, labels = split_labels(raw)
            name = prom_name(base) + ("_total" if kind == "counter" else "")
            rows.add((name, kind, tuple(sorted(labels))))
    return sorted(rows)
