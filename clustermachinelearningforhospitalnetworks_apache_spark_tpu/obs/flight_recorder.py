"""Crash flight recorder: a bounded ring of recent events, dumped on
failure.

The postmortem half of ISSUE 10: while the system runs, cheap
``note()`` calls append recent activity — span ends (fed by the
tracer), fault-rule fires, quarantines, breaker transitions, journal
hops — into a fixed-size in-memory ring.  When something *breaks*, the
ring plus a metrics snapshot is dumped atomically to disk, so every
failure leaves an artifact answering "what was the system doing in the
seconds before this?".

Dump triggers (wired at the source, not polled):

* **breaker trip/open**   — ``serve/breaker.py`` on any transition to OPEN
* **poison-batch quarantine** — ``streaming/microbatch.py::_quarantine``
* **lifecycle rollback**  — ``lifecycle/controller.py::_rollback``
* **InjectedCrash**       — ``utils/faults.py``: the exception's
  constructor itself dumps, so every chaos-matrix kill (fault-rule
  crashes, torn WAL writes, test-raised crashes) leaves a postmortem
  no matter which code path raised it.  ``tools/run_chaos.sh`` asserts
  the dumps exist and round-trip for its whole kill matrix.

Dump integrity: the payload is serialized canonically (key-sorted,
separator-pinned — the ``lifecycle/journal.py`` convention) and wrapped
with its CRC32C (``io/integrity.py``); :func:`read_dump` verifies
before trusting, so a torn or bit-rotted postmortem reads as corrupt
instead of as evidence.  The write is tmp-file + atomic rename.

Always on: the ring is a few hundred small dicts (bounded deque), and
``note()`` is a lock + append — cheap enough to leave armed in
production, the whole point of a flight recorder.  ``CMLHN_FLIGHT_DIR``
overrides the dump directory (default: a per-process tempdir path).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from collections import deque

#: ring capacity: enough context to read a story, small enough to dump
#: in one write
DEFAULT_CAPACITY = 256

_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")


def default_dump_dir() -> str:
    env = os.environ.get("CMLHN_FLIGHT_DIR")
    if env:
        return env
    return os.path.join(
        tempfile.gettempdir(), f"cmlhn_flight-{os.getpid()}"
    )


#: dump-directory bound: a breaker that re-opens every recovery cycle
#: under sustained drift (an EXPECTED state, PR 7) must not fill the
#: disk with postmortems — oldest dumps evict past this count
DEFAULT_MAX_DUMPS = 256


class FlightRecorder:
    """Bounded event ring + atomic CRC32C postmortem dumps."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        dump_dir: str | None = None,
        max_dumps: int = DEFAULT_MAX_DUMPS,
    ):
        self.capacity = max(int(capacity), 8)
        self.dump_dir = dump_dir
        self.max_dumps = max(int(max_dumps), 1)
        self.events: deque = deque(maxlen=self.capacity)
        self.dumps = 0
        self.dump_failures = 0
        self.last_dump_path: str | None = None
        self._lock = threading.Lock()
        self._seq = 0
        self._in_dump = threading.local()

    # ------------------------------------------------------------ record
    def note(self, kind: str, name: str, **attrs) -> None:
        """Append one event to the ring (cheap; never raises)."""
        evt = {
            "t": round(time.time(), 6),
            "kind": kind,
            "name": name,
            "thread": threading.current_thread().name,
        }
        if attrs:
            evt["attrs"] = attrs
        with self._lock:
            self.events.append(evt)

    def note_span(self, span: dict) -> None:
        """Tracer fast path: fold a finished span into the ring without
        rebuilding it (the span dict is already immutable-by-convention
        once emitted) — kwargs-free on purpose, it runs per span."""
        with self._lock:
            self.events.append({
                "t": span["t0"] + span["dur_s"],
                "kind": "span",
                "name": span["name"],
                "attrs": {
                    "trace_id": span["trace_id"], "dur_s": span["dur_s"],
                },
            })

    # ------------------------------------------------------------- dump
    def dump(
        self, reason: str, site: str | None = None,
        attrs: dict | None = None,
    ) -> str | None:
        """Write the postmortem artifact; returns its path (None when the
        dump itself failed — counted, never raised: the recorder must
        not turn a failure into a worse one).  ``attrs`` is a dict, not
        ``**kwargs``, so trigger attributes can never collide with the
        ``reason``/``site`` parameters.  Reentrancy-guarded: a crash
        raised *while dumping* does not recurse."""
        if getattr(self._in_dump, "active", False):
            return None
        self._in_dump.active = True
        try:
            return self._dump(reason, site, attrs or {})
        except Exception:  # noqa: BLE001 — postmortem capture is best-effort
            self.dump_failures += 1
            return None
        finally:
            self._in_dump.active = False

    def _dump(self, reason: str, site: str | None, attrs: dict) -> str:
        from ..io.integrity import crc32c_hex  # lazy: keeps import light

        with self._lock:
            events = list(self.events)
            self._seq += 1
            seq = self._seq
        try:
            from .export import json_snapshot

            metrics = json_snapshot()
        except Exception:  # noqa: BLE001 — a broken collector must not
            # cost the postmortem its event ring
            metrics = {"error": "metrics snapshot failed"}
        try:
            from .trace import current_trace_id

            trace_id = current_trace_id()
        except Exception:  # noqa: BLE001
            trace_id = None
        payload = {
            "reason": str(reason),
            "site": site,
            "trigger": {k: v for k, v in attrs.items()},
            "time": round(time.time(), 6),
            "pid": os.getpid(),
            "seq": seq,
            "trace_id": trace_id,
            "events": events,
            "metrics": metrics,
        }
        body = json.dumps(
            payload, sort_keys=True, separators=(",", ":"), default=str
        )
        record = {"crc32c": crc32c_hex(body.encode()), "payload": payload}
        d = self.dump_dir or default_dump_dir()
        os.makedirs(d, exist_ok=True)
        tag = _SAFE.sub("_", (site or reason))[:48]
        path = os.path.join(d, f"flight-{os.getpid()}-{seq:04d}-{tag}.json")
        tmp = path + ".tmp"
        # The flight recorder is a best-effort OBSERVER of the
        # durability story, not a member of it (PR 8): a postmortem
        # lost to power loss costs evidence, never state, and routing
        # it through the sanctioned helpers would put an fsync_dir on
        # the breaker-trip path this module exists to keep cheap.
        # cmlhn: disable=raw-durable-write — best-effort postmortem observer, loss costs evidence never state
        with open(tmp, "w") as f:
            json.dump(
                record, f, sort_keys=True, separators=(",", ":"), default=str
            )
            f.flush()
            os.fsync(f.fileno())
        # cmlhn: disable=raw-durable-rename — best-effort postmortem observer, loss costs evidence never state
        os.replace(tmp, path)
        self.dumps += 1
        self.last_dump_path = path
        # bound the directory: evict oldest dumps past max_dumps (names
        # sort by pid+seq, so lexicographic order is write order per
        # process; eviction is best-effort — a raced unlink is fine)
        existing = sorted(
            f for f in os.listdir(d)
            if f.startswith("flight-") and f.endswith(".json")
        )
        for stale in existing[: max(0, len(existing) - self.max_dumps)]:
            try:
                os.unlink(os.path.join(d, stale))
            except OSError:
                pass
        return path

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "events_buffered": len(self.events),
                "capacity": self.capacity,
                "dumps": self.dumps,
                "dump_failures": self.dump_failures,
                "last_dump_path": self.last_dump_path,
            }


def read_dump(path: str) -> dict:
    """Load + CRC-verify one postmortem; raises ``ValueError`` on a
    mismatched or malformed artifact (corruption must be loud here — a
    silently-wrong postmortem is worse than none)."""
    from ..io.integrity import crc32c_hex

    with open(path) as f:
        record = json.load(f)
    if not isinstance(record, dict) or "payload" not in record:
        raise ValueError(f"{path}: not a flight-recorder dump")
    body = json.dumps(
        record["payload"], sort_keys=True, separators=(",", ":"), default=str
    )
    got = crc32c_hex(body.encode())
    want = record.get("crc32c")
    if got != want:
        raise ValueError(
            f"{path}: crc32c mismatch ({got} computed, {want} recorded)"
        )
    return record["payload"]


# ---------------------------------------------------------------- install
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def install(rec: FlightRecorder) -> FlightRecorder:
    global _RECORDER
    _RECORDER = rec
    return rec


def note(kind: str, name: str, **attrs) -> None:
    _RECORDER.note(kind, name, **attrs)


def notify(kind: str, site: str, **attrs) -> str | None:
    """A dump trigger fired: record it in the ring AND write the
    postmortem.  Never raises."""
    try:
        _RECORDER.note(kind, site, **attrs)
        return _RECORDER.dump(kind, site=site, attrs=attrs)
    except Exception:  # noqa: BLE001 — see dump()
        return None


def crash_dump(exc: BaseException) -> None:
    """Called from ``InjectedCrash.__init__``: every simulated process
    death dumps the ring at the moment of death, tagged with the site
    that killed it.  Never raises (a recorder bug must not change what
    the chaos test observes)."""
    try:
        site = getattr(exc, "site", None) or "injected_crash"
        _RECORDER.note("injected_crash", site, message=str(exc))
        _RECORDER.dump(
            "injected_crash", site=site, attrs={"message": str(exc)}
        )
    except Exception:  # noqa: BLE001
        pass
