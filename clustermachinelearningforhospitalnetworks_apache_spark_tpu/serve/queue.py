"""Bounded request queue: backpressure, deadlines, graceful degradation.

The serving layer's stability contract, in order of preference when load
exceeds capacity:

1. **Backpressure** — the queue is bounded in ROWS (not requests: one
   1024-row bulk request is 1024 singles' worth of work).  Admission
   beyond the bound never blocks the caller indefinitely.
2. **Shed** — an over-bound request is immediately answered with a
   503-style :class:`ServeResult` (status ``rejected``), optionally
   carrying a cheap fallback model's prediction instead of nothing.
3. **Deadline drop** — a request whose per-request deadline expires while
   queued is answered ``deadline_exceeded`` (again with the fallback if
   one is configured) rather than served late; the batcher never spends
   device time on an answer nobody is waiting for.

Nothing in this module touches jax — it is pure host-side bookkeeping,
unit-testable without a device.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..tune import knob

#: result statuses, 503-analogue semantics
STATUS_OK = "ok"
STATUS_REJECTED = "rejected"             # queue saturated at admission
STATUS_DEADLINE_EXCEEDED = "deadline_exceeded"
#: legacy (PR 1): no longer emitted — primary failures now answer
#: ``unavailable`` through the fallback/breaker path; kept exported so
#: clients that branched on it keep importing
STATUS_ERROR = "error"
STATUS_UNAVAILABLE = "unavailable"       # primary failed / circuit open
STATUS_SHUTDOWN = "shutdown"
#: input guard refusal (PR 3): non-finite / wildly out-of-range features
#: under the ``reject`` policy — a 400, not a 503, so NOT a degraded
#: status (a made-up answer to a garbage question helps nobody)
STATUS_INVALID_INPUT = "invalid_input"
#: lifecycle canary (ISSUE 9): a full-quality answer scored by the
#: CANDIDATE model during its canary traffic split — ``ok`` is True, the
#: tag exists so clients/audits can attribute the answer to the candidate
STATUS_CANARY = "canary"

#: statuses answered by the fallback path (degraded but not failed)
DEGRADED_STATUSES = (
    STATUS_REJECTED, STATUS_DEADLINE_EXCEEDED, STATUS_UNAVAILABLE,
)


@dataclass
class ServeResult:
    """What a client gets back — always, and promptly: every admission
    path ends in exactly one ``ServeResult``, never a hang."""

    value: Optional[np.ndarray]
    status: str = STATUS_OK
    latency_s: float = 0.0
    degraded: bool = False
    detail: str = ""

    @property
    def ok(self) -> bool:
        # canary answers are full-quality predictions (just attributed to
        # the candidate model), not a degradation
        return self.status in (STATUS_OK, STATUS_CANARY)


@dataclass
class Request:
    """One admitted unit of work (1..top-bucket rows) plus its rendezvous."""

    x: np.ndarray
    enqueued_at: float
    deadline: float | None  # absolute monotonic seconds, None = patient
    _event: threading.Event = field(default_factory=threading.Event)
    _result: ServeResult | None = None

    @property
    def rows(self) -> int:
        return self.x.shape[0]

    def expired(self, now: float | None = None) -> bool:
        return self.deadline is not None and (now or time.monotonic()) >= self.deadline

    # rendezvous ---------------------------------------------------------
    def complete(self, result: ServeResult) -> None:
        result.latency_s = time.monotonic() - self.enqueued_at
        self._result = result
        self._event.set()

    def wait(self, timeout: float | None = None) -> ServeResult:
        if not self._event.wait(timeout):
            return ServeResult(
                None, STATUS_DEADLINE_EXCEEDED,
                latency_s=time.monotonic() - self.enqueued_at,
                detail="client wait timed out",
            )
        assert self._result is not None
        return self._result


class RequestQueue:
    """Row-bounded FIFO with shed-at-admission semantics."""

    def __init__(self, max_rows: int | None = None):
        # None → the registry's serve.queue.max_rows (the ONE copy of a
        # bound that previously lived as five diverged 4096 literals)
        if max_rows is None:
            max_rows = int(knob("serve.queue.max_rows"))
        if max_rows < 1:
            raise ValueError("max_rows must be positive")
        self.max_rows = max_rows
        self._q: deque[Request] = deque()
        self._rows = 0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)

    # ------------------------------------------------------------ admit
    def offer(self, req: Request) -> bool:
        """Admit or refuse immediately — never blocks.  False means the
        queue is saturated (caller sheds/falls back)."""
        with self._not_empty:
            if self._rows + req.rows > self.max_rows:
                return False
            self._q.append(req)
            self._rows += req.rows
            self._not_empty.notify()
            return True

    # ------------------------------------------------------------ drain
    def take(
        self, max_rows: int, wait_s: float | None, more_wait_s: float = 0.0
    ) -> list[Request]:
        """Pop a coalesced run of requests totalling ≤ ``max_rows`` rows.

        Blocks up to ``wait_s`` for the FIRST request (None = forever);
        after one arrives, lingers up to ``more_wait_s`` for followers
        while capacity remains — the micro-batching window.  Expired
        requests are popped too (the batcher answers them degraded);
        a request that would overflow ``max_rows`` stays queued for the
        next batch."""
        batch: list[Request] = []
        got = 0
        deadline = None if wait_s is None else time.monotonic() + wait_s
        with self._not_empty:
            while not self._q:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return batch
                self._not_empty.wait(remaining)
            linger_until = time.monotonic() + more_wait_s
            while True:
                while self._q and got + self._q[0].rows <= max_rows:
                    r = self._q.popleft()
                    self._rows -= r.rows
                    got += r.rows
                    batch.append(r)
                if got >= max_rows or more_wait_s <= 0:
                    break
                remaining = linger_until - time.monotonic()
                if remaining <= 0 or (self._q and got + self._q[0].rows > max_rows):
                    break
                self._not_empty.wait(remaining)
        return batch

    # ------------------------------------------------------------ stats
    @property
    def depth_rows(self) -> int:
        with self._lock:
            return self._rows

    @property
    def depth_requests(self) -> int:
        with self._lock:
            return len(self._q)

    def drain_all(self) -> list[Request]:
        """Pop everything (shutdown path)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            self._rows = 0
            return out

    def wake_all(self) -> None:
        with self._not_empty:
            self._not_empty.notify_all()
