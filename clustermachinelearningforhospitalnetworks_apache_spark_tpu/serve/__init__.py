"""TPU-native online inference: the trained half's path back to traffic.

``serve/`` turns a saved artifact into low-latency predictions without
ever recompiling in steady state:

* :mod:`registry`  — saved model → jitted, shape-bucketed executables
* :mod:`bucketing` — the power-of-two batch-shape ladder (zero-recompile
  contract)
* :mod:`batcher`   — adaptive micro-batching of single-row requests
* :mod:`queue`     — bounded admission, deadlines, graceful degradation
* :mod:`breaker`   — per-model circuit breaker (closed→open→half-open)
* :mod:`scoring`   — sharded bulk scoring over the training data mesh
* :mod:`metrics`   — p50/p99 latency, queue depth, fill ratio, recompiles
* :mod:`server`    — the composed front door (:class:`InferenceServer`)
* :mod:`fleet`     — N replicas behind a tenant-aware router with
  per-tenant SLO admission (:class:`fleet.ReplicaSet`)

See docs/ARCHITECTURE.md §Serving layer and §Serving fleet for the
design rationale.
"""

from .batcher import DEFAULT_MAX_WAIT_S, MicroBatcher
from .breaker import (
    CircuitBreaker,
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
)
from .bucketing import DEFAULT_BUCKETS, bucket_for, fill_ratio, pad_to_bucket
from .metrics import ServingMetrics
from .queue import (
    DEGRADED_STATUSES,
    Request,
    RequestQueue,
    ServeResult,
    STATUS_CANARY,
    STATUS_DEADLINE_EXCEEDED,
    STATUS_ERROR,
    STATUS_INVALID_INPUT,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTDOWN,
    STATUS_UNAVAILABLE,
)
from .registry import ModelRegistry, ServingModel
from .scoring import ShardedScorer, bulk_score
from .server import InferenceServer, NotRoutableError
from . import fleet

__all__ = [
    "CircuitBreaker",
    "DEFAULT_BUCKETS",
    "DEFAULT_MAX_WAIT_S",
    "DEGRADED_STATUSES",
    "InferenceServer",
    "STATE_CLOSED",
    "STATE_HALF_OPEN",
    "STATE_OPEN",
    "STATUS_UNAVAILABLE",
    "MicroBatcher",
    "ModelRegistry",
    "NotRoutableError",
    "Request",
    "fleet",
    "RequestQueue",
    "ServeResult",
    "ServingMetrics",
    "ServingModel",
    "ShardedScorer",
    "STATUS_CANARY",
    "STATUS_DEADLINE_EXCEEDED",
    "STATUS_ERROR",
    "STATUS_INVALID_INPUT",
    "STATUS_OK",
    "STATUS_REJECTED",
    "STATUS_SHUTDOWN",
    "bucket_for",
    "bulk_score",
    "fill_ratio",
    "pad_to_bucket",
]
