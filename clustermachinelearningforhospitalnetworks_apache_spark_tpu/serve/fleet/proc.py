"""Multi-process fleet: each replica is a real OS process (ISSUE 19b).

The in-process :class:`~.replica_set.ReplicaSet` multiplies servers
inside ONE Python interpreter — replicas share the GIL, the jax runtime,
and every compilation stall.  This module runs each replica as its own
process with its own jax runtime, so fleet goodput can actually scale
with N on a multi-core host: ``ProcReplicaSet`` overrides exactly the
two seams the base class exposes (``_build_server`` / ``_make_replica``)
and everything else — router, admission, atomic swap, kill/revive,
metrics pull — is the PR 10 code path, unchanged.

Transport
---------
One ``socketpair`` per replica, passed to the spawned worker by fd.
Frames are ``magic + u32 big-endian length + pickle``: a torn header,
bad magic, oversize length, or undecodable payload each raise
:class:`FrameError` — the stream has no resync point, so a framing
error is transport death, answered by the same ladder as a process
death.  Parent→child requests carry a monotone ``id``; the parent's
receive thread resolves replies against a pending map, so any number of
requests overlap on one socket.  Model objects cross the wire pickled
(params are committed numpy arrays — ``validate_persistable`` is the
same contract :mod:`...io.model_io` relies on); fallbacks must be
picklable or ``None``.

Failure ladder (reused, not reinvented)
---------------------------------------
* spawn: :func:`...utils.retry.call_with_retry` around the whole
  spawn+handshake (the ``fleet.proc.spawn`` fault site fires inside it,
  so an injected transient spawn failure is retried like any IO fault);
* data plane: a parent-side :class:`~..breaker.CircuitBreaker` guards
  the transport — timeouts and framing errors count as failures, and an
  open breaker makes ``submit``/``predict`` raise ``KeyError``, which is
  precisely the signal the fleet's bounded reroute loop already treats
  as "replica lost mid-dispatch";
* death: EOF on the socket completes EVERY in-flight request with a
  ``ServeResult(status=unavailable)`` — answered, never stranded — and
  flips the client dead so ``ProcReplica.healthy()`` excludes it from
  routing.

Swap atomicity
--------------
``prepare_swap`` builds + warms the successor INSIDE the worker and
parks it behind an integer handle; ``commit_swap`` flips it.  The
fleet's ``swap_model`` therefore keeps its every-replica-or-none shape:
phase 1 RPCs can fail with zero replicas flipped; phase 2 commits are
in-memory flips in each worker.

Worker environment
------------------
The child inherits the parent's env with two fixes: any
``--xla_force_host_platform_device_count`` token is scrubbed from
``XLA_FLAGS`` (a replica worker serves on ONE device; forcing the
test topology's 8 virtual devices into every child multiplies startup
cost for nothing), and a persistent jax compilation cache dir is
defaulted so N workers compiling identical serving executables hit the
cache instead of compiling N times.
"""

from __future__ import annotations

import itertools
import os
import pathlib
import pickle
import queue as _queue
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ...obs import flight_recorder as _flight
from ...obs import trace as _trace
from ...obs.registry import replica_label
from ...tune import knob
from ...utils.faults import fault_point, mangle_bytes
from ...utils.logging import get_logger
from ...utils.retry import RetryPolicy, call_with_retry
from ..breaker import STATE_OPEN, CircuitBreaker
from ..bucketing import DEFAULT_BUCKETS
from ..queue import Request, ServeResult, STATUS_UNAVAILABLE
from .replica_set import (
    _BREAKER_CODE,
    _STATE_CODE,
    REPLICA_DEAD,
    REPLICA_LIVE,
    Replica,
    ReplicaSet,
)

log = get_logger("serve")

#: fully-qualified module the worker is spawned as (``python -m ...``);
#: a dedicated entry module, so runpy never re-executes a module the
#: package ``__init__`` already imported
_WORKER_MODULE = (
    "clustermachinelearningforhospitalnetworks_apache_spark_tpu"
    ".serve.fleet._proc_worker"
)
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[3]

# ----------------------------------------------------------------- framing

_MAGIC = b"CMP1"

#: the one chaos-injectable wire site: every parent→worker frame passes
#: through :func:`mangle_bytes` under this name (a once-assigned literal
#: so ``tools/check_obs.py`` can tie it to ``SITE_COVERAGE``).
RPC_SITE = "fleet.proc.rpc"
_HEADER = struct.Struct(">4sI")
#: 64 MiB — generous for a pickled model + profile, small enough that a
#: corrupted length field can't ask the receiver to buffer gigabytes
MAX_FRAME_BYTES = 64 << 20


class RPCError(RuntimeError):
    """Control-plane RPC failure (timeout, transport death, remote
    error) — loud, because control calls (add/swap/start) have no
    reroute fallback."""


class FrameError(RPCError):
    """Unrecoverable wire-format violation: torn header/payload, bad
    magic, oversize length, undecodable pickle.  The stream has no
    resync point, so the connection is dead."""


def send_frame(
    sock: socket.socket,
    obj: Any,
    *,
    lock: threading.Lock | None = None,
    mangle: bool = False,
    max_bytes: int = MAX_FRAME_BYTES,
    **ctx,
) -> None:
    """Pickle ``obj`` and write one length-prefixed frame.  ``mangle``
    routes the encoded payload through :func:`mangle_bytes` at
    :data:`RPC_SITE` so chaos tests can corrupt RPC bytes in flight."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if mangle:
        payload = mangle_bytes(RPC_SITE, payload, **ctx)
    if len(payload) > max_bytes:
        raise FrameError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{max_bytes}-byte cap"
        )
    buf = _HEADER.pack(_MAGIC, len(payload)) + payload
    if lock is not None:
        with lock:
            sock.sendall(buf)
    else:
        sock.sendall(buf)


def _recv_exact(
    sock: socket.socket, n: int, *, eof_ok: bool = False
) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if eof_ok and not buf:
                return None
            raise FrameError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return bytes(buf)


def recv_frame(
    sock: socket.socket, *, max_bytes: int = MAX_FRAME_BYTES
) -> Any | None:
    """Read one frame; ``None`` on clean EOF at a frame boundary,
    :class:`FrameError` on any wire-format violation."""
    head = _recv_exact(sock, _HEADER.size, eof_ok=True)
    if head is None:
        return None
    magic, length = _HEADER.unpack(head)
    if magic != _MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if length > max_bytes:
        raise FrameError(f"oversize frame: {length} > {max_bytes} bytes")
    payload = _recv_exact(sock, length)
    try:
        return pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — any decode failure is torn wire
        raise FrameError(f"undecodable frame payload: {e!r}") from None


# ----------------------------------------------------------------- client

#: spawn + handshake retry: a transient spawn failure (including one
#: injected at ``fleet.proc.spawn``) rides the standard IO ladder
_SPAWN_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
    retryable=(OSError, FrameError),
)


class _RegistryEntry:
    """Parent-side registry row: just enough surface for
    ``_FleetModelView.get`` / ``predict_tenant``'s affinity lookup."""

    __slots__ = ("model",)

    def __init__(self, model):
        self.model = model


class _ClientRegistry:
    def __init__(self):
        self._entries: dict[str, _RegistryEntry] = {}

    def names(self) -> list[str]:
        return sorted(self._entries)

    def get(self, name: str) -> _RegistryEntry:
        return self._entries[name]


@dataclass
class ProcPreparedSwap:
    """Parent handle to a successor prepared INSIDE a worker."""

    name: str
    handle: int
    model: Any


class ProcServerClient:
    """The parent-side facade over one replica worker process — the same
    surface :class:`~..server.InferenceServer` exposes to the fleet
    (``add_model``/``prepare_swap``/``commit_swap``/``start``/``stop``/
    ``submit``/``predict``/``predict_tenant``/``registry``), answered
    over the frame RPC."""

    def __init__(
        self,
        replica_id: int,
        server_kw: dict,
        *,
        worker_threads: int = 2,
        spawn_timeout_s: float = 180.0,
        rpc_timeout_s: float = 30.0,
        max_frame_bytes: int = MAX_FRAME_BYTES,
        env: dict | None = None,
    ):
        self.replica_id = replica_id
        self._server_kw = dict(server_kw)
        # the registry owns the bound: this fallback used to be a fifth
        # hand-copied 4096 that could (and did) diverge from the other
        # four — now every path resolves serve.queue.max_rows
        mq = self._server_kw.get("max_queue_rows")
        self.max_queue_rows = int(
            knob("serve.queue.max_rows") if mq is None else mq
        )
        self.breaker = CircuitBreaker(
            failure_threshold=int(
                self._server_kw.get("breaker_failure_threshold", 5)
            ),
            recovery_timeout_s=float(
                self._server_kw.get("breaker_recovery_s", 5.0)
            ),
        )
        self._worker_threads = max(int(worker_threads), 1)
        self._spawn_timeout_s = spawn_timeout_s
        self._rpc_timeout_s = rpc_timeout_s
        self._max_frame = max_frame_bytes
        self._env_extra = dict(env or {})
        self.registry = _ClientRegistry()
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: dict[int, dict] = {}
        self._ids = itertools.count(1)
        self._inflight_rows = 0
        self._dead = threading.Event()
        self._closing = False
        self._sock: socket.socket | None = None
        self._proc: subprocess.Popen | None = None
        self.pid: int | None = None
        self.counters: dict[str, float] = {
            "serve.requests": 0.0,
            "fleet.proc.rpc_sent": 0.0,
            "fleet.proc.short_circuited": 0.0,
            "fleet.proc.transport_down": 0.0,
            "fleet.proc.killed": 0.0,
        }
        #: flight-recorder artifact path from the last ``kill()``
        self.last_postmortem: str | None = None
        call_with_retry(self._spawn, policy=_SPAWN_RETRY)

    # ------------------------------------------------------------ spawn
    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # one device per worker: the parent test topology's forced
        # 8-virtual-device flag would multiply every child's startup
        flags = [
            t for t in env.get("XLA_FLAGS", "").split()
            if not t.startswith("--xla_force_host_platform_device_count")
        ]
        if flags:
            env["XLA_FLAGS"] = " ".join(flags)
        else:
            env.pop("XLA_FLAGS", None)
        # N workers compile identical serving executables — share one
        # persistent compilation cache so only the first pays
        env.setdefault(
            "JAX_COMPILATION_CACHE_DIR",
            env.get("JAX_TEST_CACHE_DIR")
            or os.path.join(tempfile.gettempdir(), "cmlhn_proc_jax_cache"),
        )
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
        pp = env.get("PYTHONPATH")
        root = str(_REPO_ROOT)
        env["PYTHONPATH"] = root + (os.pathsep + pp if pp else "")
        env.update(self._env_extra)
        return env

    def _spawn(self) -> None:
        fault_point("fleet.proc.spawn", replica=self.replica_id)
        self._teardown_transport()
        with _trace.span(
            "fleet.proc",
            {"event": "spawn", "replica": replica_label(self.replica_id)},
        ):
            parent, child = socket.socketpair()
            try:
                proc = subprocess.Popen(
                    [
                        sys.executable, "-m", _WORKER_MODULE,
                        "--fd", str(child.fileno()),
                    ],
                    pass_fds=(child.fileno(),),
                    env=self._worker_env(),
                    close_fds=True,
                )
            except Exception:
                parent.close()
                raise
            finally:
                child.close()
            self._sock, self._proc = parent, proc
            try:
                rid = next(self._ids)
                send_frame(parent, {
                    "op": "init", "id": rid,
                    "server_kw": self._server_kw,
                    "worker_threads": self._worker_threads,
                    "replica": self.replica_id,
                }, max_bytes=self._max_frame)
                parent.settimeout(self._spawn_timeout_s)
                reply = recv_frame(parent, max_bytes=self._max_frame)
                parent.settimeout(None)
            except (OSError, FrameError):
                self._teardown_transport()
                raise
            if reply is None or not reply.get("ok"):
                self._teardown_transport()
                raise OSError(
                    f"replica {self.replica_id} worker failed to "
                    f"initialize: {reply and reply.get('error')}"
                )
        self.pid = proc.pid
        self._dead = threading.Event()
        self._closing = False
        t = threading.Thread(
            target=self._recv_loop,
            name=f"proc-replica-{self.replica_id}-recv", daemon=True,
        )
        t.start()
        log.info(
            "replica worker spawned",
            replica=self.replica_id, pid=proc.pid,
        )

    def _teardown_transport(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        proc, self._proc = self._proc, None
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    # ------------------------------------------------------------ receive
    def _recv_loop(self) -> None:
        sock = self._sock
        while True:
            try:
                msg = recv_frame(sock, max_bytes=self._max_frame)
            except (FrameError, OSError) as e:
                self._on_transport_down(str(e))
                return
            if msg is None:
                self._on_transport_down("connection closed by worker")
                return
            self._dispatch(msg)

    def _dispatch(self, msg: dict) -> None:
        with self._state_lock:
            entry = self._pending.pop(msg.get("id"), None)
            if entry is not None and entry["kind"] == "request":
                self._inflight_rows -= entry["rows"]
        if entry is None:
            return  # late reply for a request nobody waits on anymore
        if entry["kind"] == "request":
            if msg.get("ok"):
                r = msg["result"]
                res = ServeResult(
                    r["value"], r["status"],
                    degraded=r["degraded"], detail=r["detail"],
                )
                self.counters["serve.requests"] += 1
            else:
                res = ServeResult(
                    None, STATUS_UNAVAILABLE,
                    detail=f"worker error: {msg.get('error', '')}",
                )
            # a reply arrived at all: the TRANSPORT is healthy, whatever
            # the model answered
            self.breaker.record_success()
            entry["req"].complete(res)
        else:
            entry["reply"] = msg
            entry["event"].set()

    def _on_transport_down(self, detail: str) -> None:
        with self._state_lock:
            if self._dead.is_set():
                return
            self._dead.set()
            closing = self._closing
            pending = list(self._pending.values())
            self._pending.clear()
            self._inflight_rows = 0
        if not closing:
            # an EXPECTED close (our own stop()) is not a failure signal
            self.breaker.record_failure()
            self.counters["fleet.proc.transport_down"] += 1
        for entry in pending:
            if entry["kind"] == "request":
                entry["req"].complete(ServeResult(
                    None, STATUS_UNAVAILABLE,
                    detail=f"replica process died: {detail}",
                ))
            else:
                entry["error"] = RPCError(
                    f"replica {self.replica_id} transport down: {detail}"
                )
                entry["event"].set()
        if not closing:
            log.warning(
                "replica transport down",
                replica=self.replica_id, detail=detail,
                answered_inflight=len(pending),
            )

    # ------------------------------------------------------------ send
    def _send(self, msg: dict) -> None:
        fault_point(
            RPC_SITE, replica=self.replica_id, op=msg.get("op")
        )
        sock = self._sock
        if sock is None or self._dead.is_set():
            raise OSError(f"replica {self.replica_id} transport is down")
        send_frame(
            sock, msg, lock=self._send_lock, mangle=True,
            max_bytes=self._max_frame,
            replica=self.replica_id, op=msg.get("op"),
        )

    # ------------------------------------------------------------ control
    def alive(self) -> bool:
        return (
            not self._dead.is_set()
            and self._proc is not None
            and self._proc.poll() is None
        )

    def inflight_rows(self) -> int:
        with self._state_lock:
            return self._inflight_rows

    def _call(self, op: str, *, timeout: float | None = None, **fields):
        if not self.alive():
            raise RPCError(f"replica {self.replica_id} process is dead")
        rid = next(self._ids)
        entry = {
            "kind": "call", "event": threading.Event(),
            "reply": None, "error": None,
        }
        with self._state_lock:
            self._pending[rid] = entry
        try:
            self._send({"op": op, "id": rid, **fields})
        except (OSError, FrameError) as e:
            with self._state_lock:
                self._pending.pop(rid, None)
            self.breaker.record_failure()
            raise RPCError(
                f"{op} rpc to replica {self.replica_id} failed: {e}"
            ) from e
        if not entry["event"].wait(timeout or self._rpc_timeout_s):
            with self._state_lock:
                self._pending.pop(rid, None)
            self.breaker.record_failure()
            raise RPCError(
                f"{op} rpc to replica {self.replica_id} timed out after "
                f"{timeout or self._rpc_timeout_s:g}s"
            )
        if entry["error"] is not None:
            raise entry["error"]
        reply = entry["reply"]
        if not reply.get("ok"):
            if reply.get("error_type") == "KeyError":
                raise KeyError(reply.get("error"))
            raise RPCError(
                f"{op} failed on replica {self.replica_id}: "
                f"{reply.get('error')}"
            )
        return reply.get("value")

    def ping(self) -> dict:
        return call_with_retry(
            lambda: self._call("ping"), policy=_SPAWN_RETRY
        )

    # ------------------------------------------------------------ setup
    def add_model(
        self,
        name: str,
        model,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        fallback=None,
        data_profile: dict | None = None,
        **guard_kw,
    ) -> None:
        self._call(
            "add_model", timeout=max(self._rpc_timeout_s, 120.0),
            name=name, model=model, n_features=n_features,
            buckets=tuple(buckets), fallback=fallback,
            data_profile=data_profile, guard_kw=dict(guard_kw),
        )
        self.registry._entries[name] = _RegistryEntry(model)

    def prepare_swap(
        self,
        name: str,
        model,
        n_features: int | None = None,
        buckets: Sequence[int] | None = None,
        data_profile: dict | None = None,
    ) -> ProcPreparedSwap:
        handle = self._call(
            "prepare_swap", timeout=max(self._rpc_timeout_s, 120.0),
            name=name, model=model, n_features=n_features,
            buckets=tuple(buckets) if buckets is not None else None,
            data_profile=data_profile,
        )
        return ProcPreparedSwap(name=name, handle=int(handle), model=model)

    def commit_swap(
        self, prepared: ProcPreparedSwap, fire_fault_point: bool = True
    ) -> str:
        self._call(
            "commit_swap", handle=prepared.handle, name=prepared.name
        )
        self.registry._entries[prepared.name] = _RegistryEntry(
            prepared.model
        )
        return prepared.name

    def attach_lifecycle(self, controller) -> None:
        raise NotImplementedError(
            "lifecycle controllers are in-process objects; a multi-"
            "process fleet cannot share one across workers — run the "
            "controller against an in-process ReplicaSet"
        )

    def start(self) -> "ProcServerClient":
        # warmup compiles per-bucket executables in the worker — give it
        # the spawn budget, not the per-RPC one
        self._call("start", timeout=max(
            self._rpc_timeout_s, self._spawn_timeout_s
        ))
        return self

    def stop(self) -> None:
        self._closing = True
        if self.alive():
            try:
                self._call("stop", timeout=self._rpc_timeout_s)
                self._send({"op": "exit", "id": 0})
            except (RPCError, OSError):
                pass
        proc = self._proc
        if proc is not None:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._dead.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def kill(self) -> None:
        """SIGKILL the worker — the chaos surface.  The receive thread
        sees EOF and answers every in-flight request ``unavailable``;
        a flight-recorder postmortem records the kill."""
        fault_point("fleet.proc.kill", replica=self.replica_id)
        pid = self.pid
        with _trace.span(
            "fleet.proc",
            {"event": "kill", "replica": replica_label(self.replica_id)},
        ):
            proc = self._proc
            if proc is not None and proc.poll() is None:
                os.kill(proc.pid, signal.SIGKILL)
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self.counters["fleet.proc.killed"] += 1
        self.last_postmortem = _flight.notify(
            "replica_proc_killed", "fleet.proc.kill",
            replica=self.replica_id, pid=pid or -1,
        )

    # ------------------------------------------------------------ serving
    def _submit_op(
        self, op: str, name: str, x: np.ndarray,
        deadline_s: float | None, extra: dict,
    ) -> Request:
        if name not in self.registry._entries:
            raise KeyError(
                f"model {name!r} is not registered on replica "
                f"{self.replica_id}"
            )
        if not self.alive():
            raise KeyError(f"replica {self.replica_id} process is dead")
        if not self.breaker.allow():
            self.counters["fleet.proc.short_circuited"] += 1
            raise KeyError(
                f"replica {self.replica_id} transport breaker open"
            )
        x2 = np.asarray(x, dtype=np.float32)
        if x2.ndim == 1:
            x2 = x2[None, :]
        now = time.monotonic()
        req = Request(
            x=x2, enqueued_at=now,
            deadline=(now + deadline_s) if deadline_s is not None else None,
        )
        rid = next(self._ids)
        entry = {"kind": "request", "req": req, "rows": int(x2.shape[0])}
        with self._state_lock:
            self._pending[rid] = entry
            self._inflight_rows += entry["rows"]
        try:
            self._send({
                "op": op, "id": rid, "name": name, "x": x2,
                "deadline_s": deadline_s, **extra,
            })
        except (OSError, FrameError) as e:
            with self._state_lock:
                if self._pending.pop(rid, None) is not None:
                    self._inflight_rows -= entry["rows"]
            self.breaker.record_failure()
            raise KeyError(
                f"replica {self.replica_id} rpc send failed: {e}"
            ) from e
        self.counters["fleet.proc.rpc_sent"] += 1
        return req

    def submit(
        self, name: str, x: np.ndarray, deadline_s: float | None = None
    ) -> Request:
        return self._submit_op(
            "predict", name, x, deadline_s,
            {"wait_timeout_s": 30.0},
        )

    def predict(
        self, name: str, x: np.ndarray, deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        req = self._submit_op(
            "predict", name, x, deadline_s,
            {"wait_timeout_s": wait_timeout_s},
        )
        # small margin past the worker's own wait so its deadline answer
        # (not our blunter client-timeout one) normally wins the race
        return req.wait(
            None if wait_timeout_s is None else wait_timeout_s + 2.0
        )

    def predict_tenant(
        self, name: str, tenant_id, x: np.ndarray,
        deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        req = self._submit_op(
            "predict_tenant", name, x, deadline_s,
            {"tenant_id": tenant_id, "wait_timeout_s": wait_timeout_s},
        )
        return req.wait(
            None if wait_timeout_s is None else wait_timeout_s + 2.0
        )

    def stats(self) -> dict:
        """The worker server's own counters (best-effort snapshot)."""
        return self._call("stats")


# ----------------------------------------------------------------- fleet


class ProcReplica(Replica):
    """A replica whose server is a :class:`ProcServerClient`: health and
    load reads are PARENT-side (no RPC on the routing hot path)."""

    def healthy(self) -> bool:
        return self.state == REPLICA_LIVE and self.server.alive()

    def load_rows(self) -> int:
        return self.server.inflight_rows()

    def capacity_rows(self) -> int:
        return self.server.max_queue_rows

    def breaker_open(self, model: str) -> bool:
        # one transport breaker guards every model on the replica
        return self.server.breaker.state == STATE_OPEN

    def obs_fragment(self) -> dict:
        idx = replica_label(self.index)
        snap = self.server.breaker.snapshot()
        gauges = {
            f'fleet.replica_state{{replica="{idx}"}}':
                _STATE_CODE[self.state],
            f'fleet.replica_queue_rows{{replica="{idx}"}}':
                float(self.load_rows()),
            f'fleet.breaker_state{{model="transport",replica="{idx}"}}':
                _BREAKER_CODE.get(snap["state"], -1.0),
        }
        return {
            "counters": dict(self.server.counters),
            "gauges": gauges,
            "histograms": {},
        }


class ProcReplicaSet(ReplicaSet):
    """A :class:`ReplicaSet` whose replicas are OS processes.

    Everything above the server seam — router, admission, atomic
    ``swap_model``, ``kill_replica``/``revive_replica``, health — is the
    in-process code path; only ``_build_server``/``_make_replica`` (and
    the kill path, which SIGKILLs instead of stopping) differ."""

    def __init__(
        self,
        n_replicas: int = 2,
        *,
        worker_threads: int = 2,
        spawn_timeout_s: float = 180.0,
        rpc_timeout_s: float = 30.0,
        max_frame_bytes: int = MAX_FRAME_BYTES,
        proc_env: dict | None = None,
        **kw,
    ):
        self._proc_kw = dict(
            worker_threads=worker_threads,
            spawn_timeout_s=spawn_timeout_s,
            rpc_timeout_s=rpc_timeout_s,
            max_frame_bytes=max_frame_bytes,
            env=dict(proc_env or {}),
        )
        # device placement is per-WORKER (each child owns its own jax
        # runtime); the parent only needs one routing token per replica
        kw.setdefault("devices", tuple(range(max(int(n_replicas), 1))))
        super().__init__(n_replicas=n_replicas, **kw)

    # ------------------------------------------------------------ seams
    def _build_server(self, slice_):
        return ProcServerClient(
            slice_.replica_id, self._server_kw, **self._proc_kw
        )

    def _make_replica(self, slice_):
        return ProcReplica(
            slice_.replica_id, slice_, self._build_server(slice_)
        )

    # ------------------------------------------------------------ chaos
    def kill_replica(self, index: int) -> None:
        """Abrupt replica-process death: the router stops picking it
        FIRST (state flip), then SIGKILL — in-flight requests are
        answered ``unavailable`` by the transport-down ladder, never
        stranded."""
        r = self._replicas[index]
        r.state = REPLICA_DEAD
        r.server.kill()
        self.metrics.inc("fleet.replicas_killed")
        log.warning("replica process killed", replica=index)

    def reap(self) -> list[int]:
        """Notice worker processes that died OUTSIDE the fleet API (an
        external SIGKILL, an OOM kill): flip them DEAD so
        ``revive_replica`` accepts them.  Routing already excludes them
        — ``ProcReplica.healthy()`` checks the process, not just the
        state flag."""
        reaped = []
        for r in self._replicas:
            if r.state == REPLICA_LIVE and not r.server.alive():
                r.state = REPLICA_DEAD
                self.metrics.inc("fleet.replicas_killed")
                reaped.append(r.index)
                log.warning("replica process reaped", replica=r.index)
        return reaped

    def attach_lifecycle(self, controller) -> None:
        raise NotImplementedError(
            "lifecycle controllers are in-process objects; attach one "
            "to an in-process ReplicaSet instead"
        )


# ----------------------------------------------------------------- worker


def _result_payload(res: ServeResult) -> dict:
    return {
        "value": None if res.value is None else np.asarray(res.value),
        "status": res.status,
        "degraded": res.degraded,
        "detail": res.detail,
    }


def worker_main(fd: int) -> int:
    """The replica worker: owns ONE :class:`InferenceServer` on this
    process's own jax runtime and answers frame RPCs until EOF/exit.
    The main thread only reads frames; a small pool executes ops so
    long predicts overlap (ids, not ordering, match replies)."""
    sock = socket.socket(fileno=fd)
    send_lock = threading.Lock()
    init = recv_frame(sock)
    if init is None or init.get("op") != "init":
        return 2
    try:
        from ..server import InferenceServer  # heavy: brings up jax

        server = InferenceServer(**init.get("server_kw", {}))
    except Exception as e:  # noqa: BLE001 — report, don't die silently
        try:
            send_frame(
                sock, {"id": init.get("id"), "ok": False, "error": repr(e)},
                lock=send_lock,
            )
        except OSError:
            pass
        return 3
    send_frame(
        sock,
        {"id": init.get("id"), "ok": True, "value": {"pid": os.getpid()}},
        lock=send_lock,
    )

    work: _queue.Queue = _queue.Queue()
    prepared: dict[int, Any] = {}
    handle_ids = itertools.count(1)

    def answer(rid, **out) -> None:
        try:
            send_frame(sock, {"id": rid, **out}, lock=send_lock)
        except OSError:
            pass  # parent gone; the drain below will notice EOF too

    def run_op(m: dict) -> None:
        rid, op = m.get("id"), m.get("op")
        try:
            if op == "predict":
                res = server.predict(
                    m["name"], m["x"], deadline_s=m.get("deadline_s"),
                    wait_timeout_s=m.get("wait_timeout_s", 30.0),
                )
                answer(rid, ok=True, result=_result_payload(res))
            elif op == "predict_tenant":
                res = server.predict_tenant(
                    m["name"], m["tenant_id"], m["x"],
                    deadline_s=m.get("deadline_s"),
                    wait_timeout_s=m.get("wait_timeout_s", 30.0),
                )
                answer(rid, ok=True, result=_result_payload(res))
            elif op == "add_model":
                server.add_model(
                    m["name"], m["model"],
                    n_features=m.get("n_features"),
                    buckets=m.get("buckets") or DEFAULT_BUCKETS,
                    fallback=m.get("fallback"),
                    data_profile=m.get("data_profile"),
                    **(m.get("guard_kw") or {}),
                )
                answer(rid, ok=True, value=True)
            elif op == "prepare_swap":
                p = server.prepare_swap(
                    m["name"], m["model"],
                    n_features=m.get("n_features"),
                    buckets=m.get("buckets"),
                    data_profile=m.get("data_profile"),
                )
                h = next(handle_ids)
                prepared[h] = p
                answer(rid, ok=True, value=h)
            elif op == "commit_swap":
                p = prepared.pop(m["handle"])
                server.commit_swap(p, fire_fault_point=False)
                answer(rid, ok=True, value=True)
            elif op == "start":
                server.start()
                answer(rid, ok=True, value=True)
            elif op == "stop":
                server.stop()
                answer(rid, ok=True, value=True)
            elif op == "ping":
                answer(rid, ok=True, value={"pid": os.getpid()})
            elif op == "stats":
                answer(rid, ok=True, value={
                    "counters": dict(server.metrics.registry.counters),
                })
            else:
                answer(
                    rid, ok=False, error=f"unknown op {op!r}",
                    error_type="RPCError",
                )
        except KeyError as e:
            answer(rid, ok=False, error=str(e), error_type="KeyError")
        except Exception as e:  # noqa: BLE001 — answered, not fatal
            answer(
                rid, ok=False, error=repr(e),
                error_type=type(e).__name__,
            )

    def worker_loop() -> None:
        while True:
            m = work.get()
            if m is None:
                return
            run_op(m)

    n_threads = max(int(init.get("worker_threads", 2)), 1)
    threads = [
        threading.Thread(target=worker_loop, name=f"op-{i}", daemon=True)
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()

    rc = 0
    while True:
        try:
            m = recv_frame(sock)
        except FrameError:
            # torn/garbage frame: no resync point — die loudly, the
            # parent's breaker/reroute ladder owns recovery
            rc = 4
            break
        except OSError:
            break
        if m is None or m.get("op") == "exit":
            break
        work.put(m)

    for _ in threads:
        work.put(None)
    try:
        server.stop()
    except Exception:  # noqa: BLE001 — already exiting
        pass
    return rc


def _main(argv: Sequence[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="fleet replica worker")
    ap.add_argument("--fd", type=int, required=True,
                    help="inherited socketpair fd")
    ns = ap.parse_args(argv)
    return worker_main(ns.fd)


if __name__ == "__main__":
    sys.exit(_main())
