"""Serving fleet: N replicas, a tenant-aware router, per-tenant SLOs.

The fabric over :mod:`..server` (ROADMAP item 1) — the MLlib move of one
uniform surface over many executors, applied to serving:

* :mod:`placement` — replica→devices assignment as a first-class object
  (the RecML ``Partitioner`` shape)
* :mod:`router`    — least-loaded / consistent-hash-per-tenant routing,
  health-aware, minimal reshuffle on membership change
* :mod:`admission` — per-tenant token-bucket quotas + SLO classes with
  ORDERED shed thresholds (best_effort → batch → interactive)
* :mod:`replica_set` — the composed front door: atomic fleet-wide
  promotion, replica kill/drain, pull-collector health
* :mod:`proc`      — the multi-process fleet (ISSUE 19): each replica a
  real OS process with its own jax runtime behind a length-prefixed
  frame RPC, same router/admission/swap semantics
* :mod:`loadgen`   — replayable open-loop Poisson load (diurnal bursts,
  fixed tenant mix) for the ``serve_fleet`` bench
* :mod:`watchdog`  — busy-but-no-progress stall detection; a wedge
  becomes a ``watchdog.stall`` flight dump + :class:`StallError`

See docs/ARCHITECTURE.md §Serving fleet.
"""

from .admission import (
    AdmissionController,
    AdmissionDecision,
    SLO_BATCH,
    SLO_BEST_EFFORT,
    SLO_INTERACTIVE,
    SLO_SHED_ORDER,
    SLOClass,
    TokenBucket,
    default_slo_classes,
)
from .loadgen import Arrival, ClassReport, LoadProfile, TenantMix, build_schedule, replay
from .placement import EvenPlacement, PinnedPlacement, Placement, ReplicaSlice
from .proc import (
    FrameError,
    ProcReplica,
    ProcReplicaSet,
    ProcServerClient,
    RPCError,
)
from .replica_set import (
    DEFAULT_ADMISSION,
    REPLICA_DEAD,
    REPLICA_DRAINING,
    REPLICA_LIVE,
    Replica,
    ReplicaSet,
)
from .router import (
    ConsistentHashRing,
    NoReplicaAvailable,
    POLICY_CONSISTENT_HASH,
    POLICY_LEAST_LOADED,
    Router,
)
from .watchdog import StallError, StallWatchdog

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "Arrival",
    "ClassReport",
    "ConsistentHashRing",
    "DEFAULT_ADMISSION",
    "EvenPlacement",
    "FrameError",
    "LoadProfile",
    "NoReplicaAvailable",
    "POLICY_CONSISTENT_HASH",
    "POLICY_LEAST_LOADED",
    "PinnedPlacement",
    "Placement",
    "ProcReplica",
    "ProcReplicaSet",
    "ProcServerClient",
    "REPLICA_DEAD",
    "REPLICA_DRAINING",
    "REPLICA_LIVE",
    "Replica",
    "ReplicaSet",
    "ReplicaSlice",
    "RPCError",
    "Router",
    "SLOClass",
    "SLO_BATCH",
    "SLO_BEST_EFFORT",
    "SLO_INTERACTIVE",
    "SLO_SHED_ORDER",
    "StallError",
    "StallWatchdog",
    "TenantMix",
    "TokenBucket",
    "build_schedule",
    "default_slo_classes",
    "replay",
]
