"""Replica placement: an explicit replica→devices assignment object.

The fleet's analogue of RecML's ``Partitioner`` (SNIPPETS [3]): instead
of every replica implicitly landing wherever jax's default device points,
placement is a FIRST-CLASS object — ``assign(n_replicas, devices)``
returns one :class:`ReplicaSlice` per replica, each naming exactly the
devices that replica's executables compile for and run on.  The
:class:`~.replica_set.ReplicaSet` threads each slice's primary device
through ``InferenceServer(device=...)`` → ``ServingModel`` so the
pinning is real (committed arrays, per-device executables), not
advisory metadata.

Two built-in strategies:

* :class:`EvenPlacement` — contiguous even split of the device list;
  with fewer devices than replicas it round-robins single-device slices
  (oversubscription — the CPU-proxy test topology) and says so.
* :class:`PinnedPlacement` — an explicit ``{replica: (device_idx, ...)}``
  map for operators who need a replica on a specific slice (e.g. keeping
  a canary replica off the interactive-serving chips).

Pure host-side logic over an abstract device list — unit-testable with
any sequence, no jax import required until a real device is used.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, Mapping, Sequence

from ...utils.logging import get_logger

log = get_logger("serve")


@dataclass(frozen=True)
class ReplicaSlice:
    """One replica's share of the mesh: the devices it may use and the
    primary its serving executables are committed to."""

    replica_id: int
    devices: tuple

    @property
    def primary(self):
        return self.devices[0]

    def describe(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "devices": [str(d) for d in self.devices],
        }


class Placement(abc.ABC):
    """Abstract replica→devices assignment (the RecML Partitioner shape:
    placement decided once, up front, as data — not scattered through
    the serving code)."""

    @abc.abstractmethod
    def assign(
        self, n_replicas: int, devices: Sequence[Any]
    ) -> tuple[ReplicaSlice, ...]:
        """Return one slice per replica over ``devices`` (ordered)."""

    def describe(self, n_replicas: int, devices: Sequence[Any]) -> list[dict]:
        return [s.describe() for s in self.assign(n_replicas, devices)]


class EvenPlacement(Placement):
    """Contiguous even split: ``len(devices) // n_replicas`` devices per
    replica (remainder spread over the first replicas).  More replicas
    than devices round-robins single-device slices — legitimate on the
    8-virtual-device CPU proxy, shouted about in the log so a production
    config can't silently oversubscribe a TPU."""

    def assign(
        self, n_replicas: int, devices: Sequence[Any]
    ) -> tuple[ReplicaSlice, ...]:
        # the split itself lives in the one partitioner (the logical
        # replica axis partitions the device LIST); this class adds the
        # fleet-facing slice objects and the oversubscription warning
        from ...parallel.partitioner import partition_devices

        devs = tuple(devices)
        if devs and n_replicas > len(devs):
            log.warning(
                "replica oversubscription: round-robining devices",
                n_replicas=n_replicas, n_devices=len(devs),
            )
        return tuple(
            ReplicaSlice(i, slice_devs)
            for i, slice_devs in enumerate(
                partition_devices(devs, n_replicas)
            )
        )


class PinnedPlacement(Placement):
    """Explicit assignment: ``{replica_id: (device_index, ...)}``.
    Validates full coverage of the replica range and no device shared
    between replicas — a replica slice is a capacity claim, and two
    replicas claiming one chip is a silent 2x oversubscription."""

    def __init__(self, assignment: Mapping[int, Sequence[int]]):
        self.assignment = {
            int(k): tuple(int(i) for i in v) for k, v in assignment.items()
        }

    def assign(
        self, n_replicas: int, devices: Sequence[Any]
    ) -> tuple[ReplicaSlice, ...]:
        devs = tuple(devices)
        missing = [i for i in range(n_replicas) if i not in self.assignment]
        if missing:
            raise ValueError(f"pinned placement missing replicas {missing}")
        seen: dict[int, int] = {}
        out = []
        for rid in range(n_replicas):
            idxs = self.assignment[rid]
            if not idxs:
                raise ValueError(f"replica {rid} pinned to zero devices")
            for di in idxs:
                if not 0 <= di < len(devs):
                    raise ValueError(
                        f"replica {rid}: device index {di} outside the "
                        f"{len(devs)}-device list"
                    )
                if di in seen:
                    raise ValueError(
                        f"device {di} pinned to both replica {seen[di]} "
                        f"and replica {rid}"
                    )
                seen[di] = rid
            out.append(ReplicaSlice(rid, tuple(devs[di] for di in idxs)))
        return tuple(out)
