"""Replayable open-loop load generator: seeded Poisson + diurnal bursts.

Serving benchmarks lie in two standard ways; this module is built to
dodge both:

* **closed-loop coordination** — clients that wait for an answer before
  sending the next request slow down exactly when the server does,
  hiding saturation (coordinated omission).  This generator is OPEN
  LOOP: arrivals follow a pre-built schedule whatever the fleet does;
  an overloaded fleet faces the same offered load a healthy one does.
* **unrepeatable load** — a throughput number nobody can re-drive is
  evidence of nothing.  The schedule is a pure function of
  (:class:`LoadProfile`, duration): seeded thinning over the rate
  curve, fixed tenant mix — the same profile replays the identical
  arrival sequence on any host (pinned by test).

The rate curve is the paper's hospital shape: a diurnal sinusoid over a
base rate, plus an optional burst window (morning admissions rush) —
``rate(t) = base · (1 + amp·sin(2πt/period + phase)) · burst(t)``.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from .admission import SLO_INTERACTIVE, SLO_SHED_ORDER


@dataclass(frozen=True)
class TenantMix:
    """One tenant's share of the offered load: relative ``weight``, its
    SLO class, and its request size in rows."""

    tenant_id: str
    weight: float
    slo: str = SLO_INTERACTIVE
    rows: int = 1


@dataclass(frozen=True)
class Arrival:
    """One scheduled request (offsets in seconds from replay start)."""

    t: float
    tenant_id: str
    slo: str
    rows: int


@dataclass(frozen=True)
class LoadProfile:
    """The replayable description of an offered load."""

    base_rate_rps: float                      # mean requests/s at baseline
    tenants: tuple[TenantMix, ...]
    seed: int = 0
    diurnal_amplitude: float = 0.0            # 0..<1 sinusoidal swing
    diurnal_period_s: float = 86_400.0
    diurnal_phase: float = 0.0
    burst_start_s: float | None = None        # burst window (None = no burst)
    burst_dur_s: float = 0.0
    burst_mult: float = 1.0

    def __post_init__(self):
        if self.base_rate_rps <= 0:
            raise ValueError("base_rate_rps must be positive")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        if not self.tenants:
            raise ValueError("tenant mix must name at least one tenant")
        if self.burst_mult < 1.0:
            raise ValueError("burst_mult must be >= 1")

    def rate_at(self, t: float) -> float:
        """Instantaneous request rate (req/s) at offset ``t``."""
        r = self.base_rate_rps * (
            1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s + self.diurnal_phase
            )
        )
        if (
            self.burst_start_s is not None
            and self.burst_start_s <= t < self.burst_start_s + self.burst_dur_s
        ):
            r *= self.burst_mult
        return r

    @property
    def peak_rate(self) -> float:
        return self.base_rate_rps * (1.0 + self.diurnal_amplitude) * max(
            self.burst_mult, 1.0
        )


def build_schedule(profile: LoadProfile, duration_s: float) -> list[Arrival]:
    """Deterministic open-loop schedule: thinning (Lewis & Shedler) of a
    homogeneous Poisson stream at the peak rate down to ``rate_at`` —
    exact for any bounded rate curve — then a weighted tenant draw per
    accepted arrival.  Same (profile, duration) → same schedule, bit for
    bit."""
    rng = np.random.default_rng(profile.seed)
    peak = profile.peak_rate
    weights = np.asarray([m.weight for m in profile.tenants], dtype=np.float64)
    if np.any(weights < 0) or weights.sum() <= 0:
        raise ValueError("tenant weights must be non-negative, sum > 0")
    cdf = np.cumsum(weights / weights.sum())
    out: list[Arrival] = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / peak)
        if t >= duration_s:
            break
        if rng.random() * peak > profile.rate_at(t):
            continue  # thinned: the instantaneous rate is below peak
        mix = profile.tenants[int(np.searchsorted(cdf, rng.random()))]
        out.append(Arrival(t, mix.tenant_id, mix.slo, mix.rows))
    return out


@dataclass
class ClassReport:
    """Per-SLO-class tally of one replay."""

    offered_requests: int = 0
    offered_rows: int = 0
    ok_rows: int = 0
    shed_rows: int = 0          # admission/queue refusals (rejected/unavailable)
    deadline_rows: int = 0
    other_rows: int = 0         # shutdown etc.
    #: (latency_s, rows) per OK answer — in-SLO goodput needs both
    ok_samples: list = field(default_factory=list, repr=False)

    @property
    def latencies_s(self) -> list:
        return [lat for lat, _ in self.ok_samples]

    def percentile_ms(self, q: float) -> float | None:
        lats = self.latencies_s
        if not lats:
            return None
        return round(float(np.percentile(np.asarray(lats), q)) * 1e3, 3)

    def in_slo(self, deadline_s: float) -> dict:
        """OK answers that also met ``deadline_s`` end to end — the
        goodput a latency SLO actually credits (an answer delivered
        after its deadline is ok-but-useless).  p50/p99 over the
        credited answers, so the pin bounds them by construction."""
        hit = [(lat, rows) for lat, rows in self.ok_samples if lat <= deadline_s]
        lats = np.asarray([lat for lat, _ in hit]) if hit else None
        return {
            "rows": int(sum(rows for _, rows in hit)),
            "p50_ms": None if lats is None else round(float(np.percentile(lats, 50)) * 1e3, 3),
            "p99_ms": None if lats is None else round(float(np.percentile(lats, 99)) * 1e3, 3),
        }

    def summary(self) -> dict:
        offered = max(self.offered_rows, 1)
        return {
            "offered_requests": self.offered_requests,
            "offered_rows": self.offered_rows,
            "ok_rows": self.ok_rows,
            "shed_rows": self.shed_rows,
            "deadline_rows": self.deadline_rows,
            "other_rows": self.other_rows,
            "ok_fraction": round(self.ok_rows / offered, 4),
            "shed_fraction": round(self.shed_rows / offered, 4),
            "p50_ms": self.percentile_ms(50),
            "p99_ms": self.percentile_ms(99),
        }


def replay(
    submit: Callable[[Arrival], object],
    schedule: Sequence[Arrival],
    speed: float = 1.0,
    wait_timeout_s: float = 10.0,
    mid_hook: Callable[[], None] | None = None,
    events: Sequence[tuple[float, Callable[[], None]]] | None = None,
) -> dict:
    """Drive a schedule open-loop against ``submit`` and tally the
    answers.

    ``submit(arrival)`` must return a :class:`~..queue.Request`-shaped
    object (``.wait(timeout) -> ServeResult``) and NEVER block — the
    fleet's and server's ``submit`` both qualify.  ``speed`` compresses
    the schedule's time axis (10.0 = drive a 30 s profile in 3 s).
    ``mid_hook`` fires once just past the schedule midpoint — the chaos
    lever (kill a replica mid-load).  ``events`` generalizes it: a
    sequence of ``(t, fn)`` in *schedule* time (same axis as
    ``Arrival.t``), each fired exactly once when the replay clock
    reaches ``t`` — ordered interleaving with arrivals is deterministic
    for a fixed schedule, which is what makes a seeded chaos schedule
    replayable.  Events left after the last arrival fire before harvest.
    Pacing lag is measured and reported: if this host can't generate the
    offered rate, the report says so instead of silently measuring a
    slower load.
    """
    per_class: dict[str, ClassReport] = {}
    pending: list[tuple[Arrival, object]] = []
    n = len(schedule)
    mid_at = n // 2
    ev = sorted(events, key=lambda e: e[0]) if events else []
    ev_next = 0
    max_lag = 0.0
    t0 = time.perf_counter()
    for i, a in enumerate(schedule):
        if mid_hook is not None and i == mid_at:
            mid_hook()
        while ev_next < len(ev) and ev[ev_next][0] <= a.t:
            ev[ev_next][1]()
            ev_next += 1
        target = t0 + a.t / speed
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        else:
            max_lag = max(max_lag, now - target)
        pending.append((a, submit(a)))
    while ev_next < len(ev):
        ev[ev_next][1]()
        ev_next += 1
    gen_wall = time.perf_counter() - t0
    # harvest: open loop never waited mid-stream, so waits happen here;
    # answers arrive roughly FIFO, making sequential waits cheap
    unanswered = 0
    for a, req in pending:
        rep = per_class.setdefault(a.slo, ClassReport())
        rep.offered_requests += 1
        rep.offered_rows += a.rows
        res = req.wait(wait_timeout_s)
        if res.ok:
            rep.ok_rows += a.rows
            rep.ok_samples.append((res.latency_s, a.rows))
        elif res.status in ("rejected", "unavailable"):
            rep.shed_rows += a.rows
        elif res.status == "deadline_exceeded":
            rep.deadline_rows += a.rows
            if res.detail == "client wait timed out":
                unanswered += 1
        else:
            rep.other_rows += a.rows
    wall = time.perf_counter() - t0
    ok_rows = sum(r.ok_rows for r in per_class.values())
    return {
        "offered_requests": n,
        "offered_rows": sum(r.offered_rows for r in per_class.values()),
        "ok_rows": ok_rows,
        "gen_wall_s": round(gen_wall, 4),
        "wall_s": round(wall, 4),
        "ok_rows_per_s": round(ok_rows / gen_wall, 1) if gen_wall > 0 else 0.0,
        "max_pacing_lag_s": round(max_lag, 4),
        "unanswered": unanswered,
        "per_class": {
            slo: per_class[slo].summary()
            for slo in SLO_SHED_ORDER if slo in per_class
        },
        #: the live ClassReport objects (in-SLO accounting, raw samples);
        #: callers serializing the report should drop this key
        "reports": per_class,
    }
