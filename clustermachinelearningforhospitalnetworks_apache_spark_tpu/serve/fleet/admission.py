"""Per-tenant quotas and SLO classes: admission control for the fleet.

The single server already degrades gracefully (PR 1's ladder: shed at a
saturated queue, drop at deadline, fallback answers) — but those rungs
are BLIND to who is asking and how urgent the ask is.  Under overload,
FIFO arrival order decides who suffers, which has two production
failure modes this module exists to close:

* **the noisy hospital** — one tenant flooding requests fills every
  queue and starves the other 4,000 hospitals.  Fix: a token bucket per
  tenant (``rate`` rows/s sustained, ``burst`` rows of headroom);
  over-quota traffic is shed AT THE DOOR, attributed to the tenant,
  before it costs a queue slot.
* **deadline deathspiral** — past saturation, queue sojourn exceeds the
  request deadline and EVERY admitted request expires before service:
  the server stays 100% busy producing 0 useful answers (the
  ``serve_fleet`` bench measures exactly this collapse on the bare
  server).  Fix: SLO classes with ordered load thresholds — as fleet
  load rises, ``best_effort`` sheds first, then ``batch``, and
  ``interactive`` keeps its queue short enough to meet its deadline.
  Degradation past saturation is ordered by CLASS, not by arrival.

These rungs sit ABOVE the existing ladder: an admitted request can
still be shed by its replica's bounded queue or dropped at its
deadline — admission only decides what deserves to contend at all.

Pure host-side state; the clock is injectable (breaker discipline) so
tests need no sleeps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Mapping

from ...tune import knob

#: SLO classes, in SHED order: under rising load, earlier classes are
#: refused admission first.  interactive = a clinician waiting on the
#: answer; batch = a scheduled job that can retry; best_effort =
#: speculative/backfill traffic that deserves only idle capacity.
SLO_BEST_EFFORT = "best_effort"
SLO_BATCH = "batch"
SLO_INTERACTIVE = "interactive"
SLO_SHED_ORDER = (SLO_BEST_EFFORT, SLO_BATCH, SLO_INTERACTIVE)


@dataclass(frozen=True)
class SLOClass:
    """One class's contract: the fleet load factor past which it sheds,
    and the deadline stamped on its requests when the caller gives none.
    ``shed_load`` is a fraction of total fleet queue capacity — the
    ordered ladder comes from interactive's threshold sitting above
    batch's sitting above best_effort's."""

    name: str
    shed_load: float
    default_deadline_s: float | None

    def __post_init__(self):
        if not 0.0 < self.shed_load <= 1.0:
            raise ValueError(
                f"{self.name}: shed_load must be in (0, 1], got {self.shed_load}"
            )


def default_slo_classes() -> dict[str, SLOClass]:
    """The shipped ladder.  best_effort contends only while the routed
    queue is under a quarter full, batch under ~half; interactive is
    refused only when the queue is HARD-full (shed_load 1.0 — there is
    no class above it to protect, so it keeps contending to the end).
    The thresholds are queue-sojourn budgets, not fairness knobs: a
    class's floor bounds how many lower-class rows an interactive
    request can queue behind, which is what keeps its deadline
    meetable while the fleet is saturated.

    The batch/best_effort thresholds are owned by the knob registry
    (``serve.slo.*.shed_load``) — the live retuner moves them by
    swapping a fresh frozen :class:`SLOClass` into
    ``AdmissionController.classes`` (an atomic dict-entry store), never
    by mutating one in place.  interactive's 1.0 is not a knob: it is
    the ladder's invariant (nothing sits above it to protect)."""
    return {
        SLO_INTERACTIVE: SLOClass(SLO_INTERACTIVE, 1.0, 0.030),
        SLO_BATCH: SLOClass(
            SLO_BATCH, float(knob("serve.slo.batch.shed_load")), 0.500
        ),
        SLO_BEST_EFFORT: SLOClass(
            SLO_BEST_EFFORT,
            float(knob("serve.slo.best_effort.shed_load")), 2.0,
        ),
    }


class TokenBucket:
    """Classic token bucket in ROWS (the queue's own unit): sustained
    ``rate`` rows/s with ``burst`` rows of headroom.  ``take`` never
    blocks — admission answers immediately, like ``RequestQueue.offer``."""

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0 or burst <= 0:
            raise ValueError("token bucket rate and burst must be positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()
        self._lock = threading.Lock()

    def take(self, rows: int) -> bool:
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
            self._t = now
            if self._tokens >= rows:
                self._tokens -= rows
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


@dataclass
class AdmissionDecision:
    admitted: bool
    reason: str = ""          # "quota:<tenant>" | "slo_load:<class>"
    deadline_s: float | None = None  # class default when caller gave none


class AdmissionController:
    """The fleet's front-door policy: the class's load threshold first
    (a load-shed must not charge quota), then the tenant's token
    bucket.  Stateless about replicas — the caller passes the routed
    queue's load factor, so this stays unit-testable with plain
    numbers."""

    def __init__(
        self,
        classes: Mapping[str, SLOClass] | None = None,
        default_quota: tuple[float, float] | None = None,
        tenant_quotas: Mapping[str, tuple[float, float]] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.classes = dict(classes) if classes is not None else default_slo_classes()
        #: (rate, burst) applied to any tenant without an explicit quota;
        #: None = unlimited for unlisted tenants
        self.default_quota = default_quota
        self._clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        self._explicit = {
            str(t): (float(r), float(b))
            for t, (r, b) in (tenant_quotas or {}).items()
        }
        self._lock = threading.Lock()

    def set_shed_load(self, slo: str, shed_load: float) -> None:
        """Atomically replace one class's threshold — the live-retune
        apply path.  A fresh frozen :class:`SLOClass` lands in the dict
        in ONE store; in-flight ``admit`` calls see the old or the new
        contract, never a mix."""
        cls = self.classes.get(slo)
        if cls is None:
            raise ValueError(
                f"unknown SLO class {slo!r}; one of {sorted(self.classes)}"
            )
        self.classes[slo] = SLOClass(
            cls.name, float(shed_load), cls.default_deadline_s
        )

    def set_quota(self, tenant_id: str, rate: float, burst: float) -> None:
        with self._lock:
            self._explicit[str(tenant_id)] = (float(rate), float(burst))
            self._buckets.pop(str(tenant_id), None)  # rebuild on next use

    def _bucket(self, tenant_id: str) -> TokenBucket | None:
        key = str(tenant_id)
        with self._lock:
            b = self._buckets.get(key)
            if b is not None:
                return b
            spec = self._explicit.get(key, self.default_quota)
            if spec is None:
                return None
            b = TokenBucket(spec[0], spec[1], clock=self._clock)
            self._buckets[key] = b
            return b

    def admit(
        self,
        tenant_id: str | None,
        slo: str,
        rows: int,
        load: float,
    ) -> AdmissionDecision:
        """One decision, never blocks.  ``load`` is the routed queue's
        rows / capacity (0..1).

        The load check runs FIRST: a request the ladder refuses must not
        drain its tenant's token bucket — charging quota for work the
        fleet never accepted would starve the tenant again after the
        load clears (and misattribute the shed as ``quota:``)."""
        cls = self.classes.get(slo)
        if cls is None:
            raise ValueError(
                f"unknown SLO class {slo!r}; one of {sorted(self.classes)}"
            )
        if load >= cls.shed_load:
            return AdmissionDecision(
                False, f"slo_load:{slo}", cls.default_deadline_s
            )
        if tenant_id is not None:
            bucket = self._bucket(tenant_id)
            if bucket is not None and not bucket.take(rows):
                return AdmissionDecision(
                    False, f"quota:{tenant_id}", cls.default_deadline_s
                )
        return AdmissionDecision(True, "", cls.default_deadline_s)
