"""Spawn entry for the replica worker process (``python -m ..._proc_worker``).

A separate module from :mod:`.proc` so running it with ``-m`` does not
re-execute a module the package ``__init__`` already imported (runpy's
"found in sys.modules" double-import hazard)."""

from __future__ import annotations

import sys

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.fleet.proc import (  # noqa: E501
    _main,
)

if __name__ == "__main__":
    sys.exit(_main())
