"""Tenant-aware request routing over a set of replicas.

Two policies, both health-aware (a breaker-OPEN, draining, or dead
replica is never picked):

* **least_loaded** — the replica with the fewest queued rows right now;
  the default for anonymous traffic, where stickiness buys nothing.
* **consistent_hash** — a hash ring with ``vnodes`` virtual nodes per
  replica: a tenant id always lands on the same replica (sticky slices —
  a hospital's farm traffic keeps hitting warm state), and adding or
  removing one replica reshuffles only ~1/N of tenants (the classic
  ring property; pinned by test).  When a tenant's home replica is
  unhealthy the walk continues clockwise, so failover is ALSO sticky:
  every orphaned tenant of a dead replica lands on its ring successor,
  and returns home when the replica does.

The router never answers requests itself — it picks; the
:class:`~.replica_set.ReplicaSet` owns admission and dispatch.  Pure
host-side state, unit-testable with stub replicas.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Protocol, Sequence

POLICY_LEAST_LOADED = "least_loaded"
POLICY_CONSISTENT_HASH = "consistent_hash"
POLICIES = (POLICY_LEAST_LOADED, POLICY_CONSISTENT_HASH)


class NoReplicaAvailable(RuntimeError):
    """Every replica is dead, draining, or breaker-OPEN for the model —
    the caller sheds the request (unavailable), it does not hang."""


class RoutableReplica(Protocol):
    """What the router needs to know about a replica — satisfied by
    :class:`~.replica_set.Replica` and by test stubs."""

    index: int

    def healthy(self) -> bool: ...

    def load_rows(self) -> int: ...

    def breaker_open(self, model: str) -> bool: ...


def _hash64(key: str) -> int:
    """Stable 64-bit point on the ring (blake2b — crc32's 32-bit space
    shows measurable vnode collisions at a few hundred vnodes)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class ConsistentHashRing:
    """The ring itself: replica ids at ``vnodes`` hashed points each.

    ``preference(key)`` returns every distinct replica id in clockwise
    order from the key's point — element 0 is the sticky home, element 1
    the sticky failover, and so on.  Membership changes move only the
    arcs the changed replica owned: the ≤ ~1/N reshuffle contract."""

    def __init__(self, vnodes: int = 160):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._points: list[tuple[int, int]] = []  # sorted (hash, replica_id)
        self._ids: set[int] = set()
        self._lock = threading.Lock()
        #: bumped on every membership change — invalidates routing caches
        self.generation = 0

    def add(self, replica_id: int) -> None:
        with self._lock:
            if replica_id in self._ids:
                return
            self._ids.add(replica_id)
            for v in range(self.vnodes):
                h = _hash64(f"replica:{replica_id}#vnode:{v}")
                bisect.insort(self._points, (h, replica_id))
            self.generation += 1

    def remove(self, replica_id: int) -> None:
        with self._lock:
            if replica_id not in self._ids:
                return
            self._ids.discard(replica_id)
            self._points = [
                p for p in self._points if p[1] != replica_id
            ]
            self.generation += 1

    def members(self) -> set[int]:
        with self._lock:
            return set(self._ids)

    def preference(self, key: str) -> list[int]:
        """Distinct replica ids clockwise from ``key``'s ring point."""
        with self._lock:
            if not self._points:
                return []
            start = bisect.bisect_right(self._points, (_hash64(key), -1))
            seen: list[int] = []
            n = len(self._points)
            for step in range(n):
                rid = self._points[(start + step) % n][1]
                if rid not in seen:
                    seen.append(rid)
                    if len(seen) == len(self._ids):
                        break
            return seen

    def owner(self, key: str) -> int | None:
        pref = self.preference(key)
        return pref[0] if pref else None


class Router:
    """Policy + health filter over the fleet's replicas."""

    def __init__(
        self,
        replicas: Sequence[RoutableReplica],
        policy: str = POLICY_CONSISTENT_HASH,
        vnodes: int = 160,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; one of {POLICIES}")
        self.policy = policy
        self._replicas: dict[int, RoutableReplica] = {
            r.index: r for r in replicas
        }
        self.ring = ConsistentHashRing(vnodes=vnodes)
        for r in replicas:
            self.ring.add(r.index)
        #: tenant → (ring generation, preference list): the hash + ring
        #: walk runs once per tenant per membership change, not per
        #: request.  Bounded: evicted wholesale when it outgrows the cap
        #: (garbage tenant ids must not grow it without bound).
        self._pref_cache: dict[str, tuple[int, list[int]]] = {}
        self._pref_cap = 4096

    # ------------------------------------------------------------ membership
    def add_replica(self, replica: RoutableReplica) -> None:
        self._replicas[replica.index] = replica
        self.ring.add(replica.index)

    def remove_replica(self, index: int) -> None:
        """Scale-down: the replica leaves the RING (its tenants reshuffle
        to their ring successors — ~1/N of the key space).  A merely
        UNHEALTHY replica stays on the ring so its tenants fail over to
        the successor and come home on recovery."""
        self._replicas.pop(index, None)
        self.ring.remove(index)

    # ------------------------------------------------------------ routing
    def _eligible(self, model: str | None) -> list[RoutableReplica]:
        return [
            r for r in self._replicas.values()
            if r.healthy() and not (model is not None and r.breaker_open(model))
        ]

    def route(
        self, tenant_id: str | None = None, model: str | None = None
    ) -> RoutableReplica:
        """Pick the replica for this request.  Raises
        :class:`NoReplicaAvailable` when nothing is eligible."""
        eligible = self._eligible(model)
        if not eligible:
            raise NoReplicaAvailable(
                f"no healthy replica for model={model!r} "
                f"({len(self._replicas)} registered)"
            )
        if tenant_id is not None and self.policy == POLICY_CONSISTENT_HASH:
            key = str(tenant_id)
            gen = self.ring.generation
            cached = self._pref_cache.get(key)
            if cached is not None and cached[0] == gen:
                pref = cached[1]
            else:
                pref = self.ring.preference(key)
                if len(self._pref_cache) >= self._pref_cap:
                    self._pref_cache.clear()
                self._pref_cache[key] = (gen, pref)
            ok = {r.index for r in eligible}
            for rid in pref:
                if rid in ok:
                    return self._replicas[rid]
            # ring empty / all ring members ineligible — fall through
        return min(eligible, key=lambda r: (r.load_rows(), r.index))
