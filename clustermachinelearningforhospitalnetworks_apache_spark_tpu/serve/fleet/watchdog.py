"""Stall watchdog: turn a hang into a named, dumped failure.

A soak run (or any long-lived fleet) can wedge in ways no exception
reports: a batcher thread deadlocks, a source stops producing, a view
refresh spins without committing.  Under tier-1 that reads as "the test
suite hung until the 870 s timeout" — zero diagnostics.  The watchdog
converts that failure mode into a bounded one: every subsystem registers
a *progress reading* (any monotone counter it bumps while doing work —
journal appends, served requests, committed batches), a background
thread samples them, and a source whose reading stops changing for a
configurable wall-clock window while it still *has* work is declared
stalled — flight-recorder dump naming the stalled stage, then a
:class:`StallError` raised in the driver thread at its next
:meth:`~StallWatchdog.check`.

Idle is not a stall: a source may register ``busy_fn`` returning whether
it currently has outstanding work (queue depth > 0, run in progress);
with no ``busy_fn`` the source is treated as always-busy, which is the
right reading for a driver loop that should be making progress whenever
the watchdog is armed.

The monitor thread never raises into anyone else's stack — it records
the verdict and dumps; the owning thread observes it via ``check()``
(cooperative, like the faults module's discipline) or the optional
``on_stall`` callback (for abort-by-callback wiring).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable

from ...obs import flight_recorder as _flight
from ...utils.logging import get_logger

log = get_logger("serve")


class StallError(RuntimeError):
    """A registered source made no progress for a full window while busy."""

    def __init__(self, stage: str, window_s: float, dump_path: str | None):
        self.stage = stage
        self.window_s = window_s
        self.dump_path = dump_path
        super().__init__(
            f"subsystem {stage!r} made no progress for {window_s:.1f}s "
            f"(postmortem: {dump_path or 'dump failed'})"
        )


@dataclass
class _Source:
    stage: str
    progress_fn: Callable[[], float]
    busy_fn: Callable[[], bool] | None
    last_value: float = 0.0
    last_change: float = 0.0


class StallWatchdog:
    """Samples registered progress readings; declares a stall after
    ``window_s`` of no change while busy.

    Use as a context manager around the monitored run::

        wd = StallWatchdog(window_s=5.0)
        wd.register("stream", lambda: sink.num_rows())
        wd.register("fleet", lambda: fleet.health()["served_requests"],
                    busy_fn=lambda: fleet.load_factor() > 0)
        with wd:
            ... drive ...
            wd.check()   # raises StallError if anything stalled

    A progress reading may be any number that grows (or merely changes)
    while the subsystem works; readings that *raise* are treated as
    no-change (a dying subsystem must not crash the monitor, it should
    be *reported* by it).
    """

    def __init__(
        self,
        window_s: float = 10.0,
        poll_s: float | None = None,
        on_stall: Callable[[StallError], None] | None = None,
    ):
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.window_s = float(window_s)
        self.poll_s = float(poll_s) if poll_s else max(window_s / 8.0, 0.02)
        self.on_stall = on_stall
        self._sources: list[_Source] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._verdict: StallError | None = None

    # ------------------------------------------------------------ wiring
    def register(
        self,
        stage: str,
        progress_fn: Callable[[], float],
        busy_fn: Callable[[], bool] | None = None,
    ) -> None:
        now = time.monotonic()
        src = _Source(stage, progress_fn, busy_fn)
        src.last_value = self._read(src)
        src.last_change = now
        with self._lock:
            self._sources.append(src)

    def watch_fleet(self, fleet) -> None:
        """Convenience: monitor a :class:`~.replica_set.ReplicaSet` —
        progress is served requests, busy is rows queued anywhere (an
        idle fleet with empty queues is not stalled, a fleet with queued
        work and no answers is)."""
        self.register(
            "fleet",
            lambda: float(
                fleet.metrics.collect()["counters"].get("serve.requests", 0)
            ),
            busy_fn=lambda: fleet.load_factor() > 0.0,
        )

    # ------------------------------------------------------------ running
    @staticmethod
    def _read(src: _Source) -> float:
        try:
            return float(src.progress_fn())
        except Exception:  # noqa: BLE001 — a dying subsystem reads as stuck
            return src.last_value

    def _busy(self, src: _Source) -> bool:
        if src.busy_fn is None:
            return True
        try:
            return bool(src.busy_fn())
        except Exception:  # noqa: BLE001
            return True

    def _scan(self, now: float) -> None:
        with self._lock:
            sources = list(self._sources)
        for src in sources:
            value = self._read(src)
            if value != src.last_value:
                src.last_value = value
                src.last_change = now
                continue
            if not self._busy(src):
                src.last_change = now  # idle: the no-progress clock resets
                continue
            if now - src.last_change >= self.window_s:
                self._declare(src)
                return

    def _declare(self, src: _Source) -> None:
        dump_path = _flight.notify(
            "stall", "watchdog.stall",
            stage=src.stage, window_s=self.window_s,
            last_progress=src.last_value,
        )
        err = StallError(src.stage, self.window_s, dump_path)
        log.error(
            "watchdog declared stall", stage=src.stage,
            window_s=self.window_s, dump=dump_path,
        )
        with self._lock:
            if self._verdict is None:
                self._verdict = err
        self._stop.set()  # one verdict is the run's verdict; stop sampling
        if self.on_stall is not None:
            try:
                self.on_stall(err)
            except Exception:  # noqa: BLE001 — the callback is advisory
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._scan(time.monotonic())

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ verdict
    def stalled(self) -> StallError | None:
        with self._lock:
            return self._verdict

    def check(self) -> None:
        """Raise the recorded stall (if any) in the CALLER's thread —
        the cooperative abort point a driver loop polls."""
        err = self.stalled()
        if err is not None:
            raise err
