"""ReplicaSet: N model replicas behind one tenant-aware front door.

The fabric that multiplies the single :class:`~..server.InferenceServer`
into a fleet (ROADMAP item 1): placement assigns each replica an
explicit device slice (:mod:`.placement`), every replica runs its own
full server — registry, pre-warmed bucket executables, micro-batchers,
breakers, drift guards — and three fleet-level pieces sit in front:

* the :class:`~.router.Router` (least-loaded or consistent-hash-per-
  tenant; a hospital's traffic sticks to one warm replica slice and
  fails over clockwise when it dies);
* the :class:`~.admission.AdmissionController` (per-tenant token-bucket
  quotas + SLO classes with ordered shed thresholds — the rungs ABOVE
  the per-replica shed/deadline ladder);
* atomic fleet-wide promotion: :meth:`swap_model` prepares EVERY
  replica's successor executable first (anything that can fail), then
  commits pure in-memory flips — a lifecycle canary/PROMOTED transition
  flips every replica or none.  The surface matches what
  ``lifecycle/controller.py`` calls on a single server (``add_model`` /
  ``swap_model`` / ``registry.names()`` / ``attach_lifecycle``), so a
  controller drives a fleet unchanged.

Fleet-level observability goes through the obs registry's PULL-COLLECTOR
path: each replica registers a collector on the fleet's
``MetricsRegistry``; :meth:`health` is a read of ``collect()`` — replica
counters SUM into fleet totals, per-replica gauges stay labeled by
``obs.registry.replica_label`` — never a second ad-hoc dict walk.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

import numpy as np

from ...io.model_io import load_data_profile, load_model
from ...obs import trace as _trace
from ...obs.registry import (
    MetricsRegistry,
    LATENCY_EDGES_S,
    replica_label,
    split_labels,
)
from ...tune import knob
from ...utils.faults import fault_point
from ...utils.logging import get_logger
from ..breaker import STATE_OPEN
from ..bucketing import DEFAULT_BUCKETS
from ..queue import (
    Request,
    ServeResult,
    STATUS_REJECTED,
    STATUS_UNAVAILABLE,
)
from ..server import InferenceServer
from .admission import AdmissionController, SLO_INTERACTIVE, SLO_SHED_ORDER
from .placement import EvenPlacement, Placement, ReplicaSlice
from .router import NoReplicaAvailable, POLICY_CONSISTENT_HASH, Router

log = get_logger("serve")

#: replica lifecycle states
REPLICA_LIVE = "live"
REPLICA_DRAINING = "draining"
REPLICA_DEAD = "dead"

_STATE_CODE = {REPLICA_LIVE: 0.0, REPLICA_DRAINING: 1.0, REPLICA_DEAD: 2.0}
_BREAKER_CODE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}
_BREAKER_NAME = {v: k for k, v in _BREAKER_CODE.items()}

#: sentinel: build the default AdmissionController (SLO ladder, no quotas)
DEFAULT_ADMISSION = "default"


class Replica:
    """One replica: its placement slice, its server, its health state.

    Satisfies the router's :class:`~.router.RoutableReplica` protocol and
    contributes the fleet registry's per-replica collector fragment."""

    def __init__(self, index: int, slice_: ReplicaSlice, server: InferenceServer):
        self.index = index
        self.slice = slice_
        self.server = server
        self.state = REPLICA_LIVE

    # ------------------------------------------------------------ routing
    def healthy(self) -> bool:
        return self.state == REPLICA_LIVE

    def load_rows(self) -> int:
        # snapshot before iterating: kill_replica's server.stop() clears
        # the batcher dict concurrently, and a front-door read must never
        # raise "dict changed size" at a client
        return sum(
            b.queue.depth_rows for b in list(self.server._batchers.values())
        )

    def capacity_rows(self) -> int:
        batchers = list(self.server._batchers.values())
        if not batchers:
            return self.server.max_queue_rows
        return sum(b.queue.max_rows for b in batchers)

    def breaker_open(self, model: str) -> bool:
        b = self.server._breakers.get(model)
        return b is not None and b.state == STATE_OPEN

    # ------------------------------------------------------------ obs
    def obs_fragment(self) -> dict:
        """This replica's contribution to the fleet registry pull:
        the server's own counters/histograms (counters SUM into fleet
        totals at collect) plus per-replica labeled gauges — every
        ``replica=`` label minted by ``obs.registry.replica_label``
        (the bounded form ``tools/check_obs.py`` enforces)."""
        reg = self.server.metrics.registry
        counters = dict(reg.counters)
        gauges = {
            f'fleet.replica_state{{replica="{replica_label(self.index)}"}}':
                _STATE_CODE[self.state],
            f'fleet.replica_queue_rows{{replica="{replica_label(self.index)}"}}':
                float(self.load_rows()),
        }
        for model, b in list(self.server._breakers.items()):
            snap = b.snapshot()
            gauges[
                f'fleet.breaker_state{{model="{model}",'
                f'replica="{replica_label(self.index)}"}}'
            ] = _BREAKER_CODE.get(snap["state"], -1.0)
        histograms = {}
        # list(): record_request creates histograms on first use — a
        # concurrent pull must not lose the fragment to a resize race
        for hname, h in list(reg.histograms.items()):
            histograms[
                f'{hname}{{replica="{replica_label(self.index)}"}}'
            ] = h.to_dict()
        return {
            "counters": counters, "gauges": gauges, "histograms": histograms,
        }


class _FleetModelView:
    """Model-registry facade over the fleet (``names()``/``get()``) —
    the surface ``lifecycle/controller.py`` reads off a single server's
    ``.registry``, answered fleet-wide."""

    def __init__(self, fleet: "ReplicaSet"):
        self._fleet = fleet

    def names(self) -> list[str]:
        return sorted(self._fleet._model_names)

    def get(self, name: str):
        for r in self._fleet._replicas:
            if r.state != REPLICA_DEAD:
                return r.server.registry.get(name)
        raise KeyError(f"no live replica serving {name!r}")


class ReplicaSet:
    """N replicas + router + admission: the fleet front door.

    ``admission=DEFAULT_ADMISSION`` ships the standard SLO ladder with no
    tenant quotas; pass a configured :class:`AdmissionController` for
    quotas, or ``None`` to serve with the bare per-replica ladder only
    (the pre-fleet behavior, per replica).
    """

    def __init__(
        self,
        n_replicas: int = 2,
        devices: Sequence[Any] | None = None,
        placement: Placement | None = None,
        policy: str = POLICY_CONSISTENT_HASH,
        vnodes: int = 160,
        admission: AdmissionController | str | None = DEFAULT_ADMISSION,
        max_queue_rows: int | None = None,
        max_wait_s: float | None = None,
        breaker_failure_threshold: int = 5,
        breaker_recovery_s: float = 5.0,
    ):
        if devices is None:
            import jax

            devices = jax.devices()
        self.placement = placement or EvenPlacement()
        self.slices = self.placement.assign(n_replicas, devices)
        #: per-replica server recipe, kept so revive_replica can rebuild
        #: a dead replica's server bit-for-bit on its original slice.
        #: Knob-owned bounds resolve ONCE here — every replica (and
        #: every revive) shares the value selected at fleet build time;
        #: live retuning (``set_max_wait_s``) moves the running batchers
        #: AND this recipe, so revives serve the retuned value.
        self._server_kw = dict(
            max_queue_rows=(
                int(knob("serve.queue.max_rows"))
                if max_queue_rows is None else max_queue_rows
            ),
            max_wait_s=(
                knob("serve.microbatch.max_wait_ms") / 1e3
                if max_wait_s is None else max_wait_s
            ),
            breaker_failure_threshold=breaker_failure_threshold,
            breaker_recovery_s=breaker_recovery_s,
        )
        self._replicas = [self._make_replica(s) for s in self.slices]
        self.router = Router(self._replicas, policy=policy, vnodes=vnodes)
        self.admission: AdmissionController | None = (
            AdmissionController() if admission == DEFAULT_ADMISSION
            else admission
        )
        #: fleet-level metrics; each replica is a pull-collector, so one
        #: collect() merges the whole fleet (the health() substrate)
        self.metrics = MetricsRegistry()
        for r in self._replicas:
            self.metrics.register_collector(
                f"replica:{r.index}", r, Replica.obs_fragment
            )
        self.registry = _FleetModelView(self)
        self._model_names: set[str] = set()
        self._fallbacks: dict[str, Any] = {}
        #: name → the add/swap arguments a revived replica re-registers
        self._model_specs: dict[str, dict] = {}
        self._lifecycle = None
        self._swap_lock = threading.Lock()
        self._started = False
        #: front-door fast lane: the per-SLO metric label keys are
        #: interned once instead of f-string-built per request
        self._slo_keys: dict[str, tuple[str, str]] = {
            slo: (
                f'fleet.requests_slo{{slo="{slo}"}}',
                f'fleet.shed{{slo="{slo}"}}',
            )
            for slo in SLO_SHED_ORDER
        }
        log.info(
            "replica set built", replicas=n_replicas,
            policy=policy, devices=len(tuple(devices)),
        )

    # ------------------------------------------------------------ seams
    def _build_server(self, slice_: ReplicaSlice):
        """Build one replica's server on its slice — the seam the
        multi-process fleet (:mod:`.proc`) overrides to spawn a real OS
        process instead of an in-process :class:`InferenceServer`.
        Used by both construction and :meth:`revive_replica`, so a
        revived replica is rebuilt through the same path it was born."""
        return InferenceServer(device=slice_.primary, **self._server_kw)

    def _make_replica(self, slice_: ReplicaSlice) -> Replica:
        """Wrap a slice and its freshly built server in the fleet's
        replica type (the proc fleet returns a :class:`ProcReplica`
        whose health/load reads are parent-side)."""
        return Replica(slice_.replica_id, slice_, self._build_server(slice_))

    # ------------------------------------------------------------ setup
    def add_model(
        self,
        name: str,
        model,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        fallback=None,
        data_profile: dict | None = None,
        **guard_kw,
    ) -> None:
        """Register a model on EVERY replica (loaded from disk once when
        ``model`` is a path); each replica builds its own executables on
        its own device slice.  ``guard_kw`` passes the PR 3 drift/guard
        tuning through (``input_policy``, ``drift_threshold``, ...)."""
        if isinstance(model, str):
            if data_profile is None:
                data_profile = load_data_profile(model)
            model = load_model(model)
        for r in self._replicas:
            if r.state == REPLICA_DEAD:
                continue
            r.server.add_model(
                name, model, n_features=n_features, buckets=buckets,
                fallback=fallback, data_profile=data_profile, **guard_kw,
            )
        self._model_names.add(name)
        self._fallbacks[name] = fallback
        self._model_specs[name] = dict(
            model=model, n_features=n_features, buckets=buckets,
            fallback=fallback, data_profile=data_profile,
            guard_kw=dict(guard_kw),
        )

    def swap_model(
        self,
        name: str,
        model,
        n_features: int | None = None,
        buckets: Sequence[int] | None = None,
        data_profile: dict | None = None,
    ) -> list:
        """Atomic fleet-wide hot swap — the promotion primitive a
        lifecycle PROMOTED transition drives.

        Phase 1 PREPARES a successor per replica (artifact load, build,
        per-device warmup — everything that can fail); phase 2 COMMITS
        pure in-memory flips under the fleet lock.  Any phase-1 failure
        raises with ZERO replicas flipped; once phase 2 starts nothing
        can fail short of process death — every replica or none."""
        with _trace.span("fleet.promote", {"model": name}) as sp:
            if isinstance(model, str):
                if data_profile is None:
                    data_profile = load_data_profile(model)
                model = load_model(model)
            with self._swap_lock:
                targets = [
                    r for r in self._replicas if r.state != REPLICA_DEAD
                ]
                prepared = []
                for r in targets:
                    fault_point(
                        "fleet.swap.prepare", replica=r.index, model=name
                    )
                    prepared.append((r, r.server.prepare_swap(
                        name, model, n_features=n_features,
                        buckets=buckets, data_profile=data_profile,
                    )))
                fault_point("fleet.swap.commit", model=name)
                # fire_fault_point=False: the per-replica swap site must
                # not be injectable mid-way through an all-or-none commit
                swapped = [
                    r.server.commit_swap(p, fire_fault_point=False)
                    for r, p in prepared
                ]
            self.metrics.inc("fleet.promotions")
            if sp.trace_id is not None:
                sp.note("replicas", len(swapped))
        self._model_names.add(name)
        prev = self._model_specs.get(name, {})
        self._model_specs[name] = dict(
            model=model, n_features=n_features,
            buckets=buckets if buckets is not None else prev.get("buckets"),
            fallback=prev.get("fallback"), data_profile=data_profile,
            guard_kw=prev.get("guard_kw", {}),
        )
        log.info(
            "fleet-wide hot swap", model=name, replicas=len(swapped),
        )
        return swapped

    def attach_lifecycle(self, controller) -> None:
        """Wire one lifecycle controller into every replica's request
        path (canary routing, shadow/drift observation) — the controller
        aggregates across replicas through its own locks."""
        self._lifecycle = controller
        for r in self._replicas:
            r.server.attach_lifecycle(controller)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaSet":
        for r in self._replicas:
            if r.state != REPLICA_DEAD:
                r.server.start()
        self._started = True
        log.info(
            "fleet started",
            replicas=sum(1 for r in self._replicas if r.healthy()),
            models=len(self._model_names),
        )
        return self

    def stop(self) -> None:
        for r in self._replicas:
            r.server.stop()
        self._started = False

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ replicas
    @property
    def replicas(self) -> tuple[Replica, ...]:
        return tuple(self._replicas)

    def kill_replica(self, index: int) -> None:
        """Abrupt replica death (chaos surface): the router stops picking
        it FIRST, then its server stops — queued requests are answered
        ``shutdown`` (cleanly shed, never stranded) and consistent-hash
        tenants fail over to their ring successor."""
        r = self._replicas[index]
        r.state = REPLICA_DEAD
        r.server.stop()
        self.metrics.inc("fleet.replicas_killed")
        log.warning("replica killed", replica=index)

    def revive_replica(self, index: int) -> None:
        """Bring a dead replica back: rebuild its server from the stored
        recipe on its ORIGINAL device slice, re-register every served
        model from the fleet's model specs (so it serves exactly what its
        live peers serve, including post-kill hot swaps), and rejoin the
        ring.  Consistent-hash tenants that failed over clockwise come
        home on their next request — the recovery half of the chaos
        surface :meth:`kill_replica` opens."""
        r = self._replicas[index]
        if r.state != REPLICA_DEAD:
            raise ValueError(
                f"replica {index} is {r.state!r}, not dead — revive is "
                "only defined for killed/drained replicas"
            )
        server = self._build_server(r.slice)
        for name, spec in list(self._model_specs.items()):
            server.add_model(
                name, spec["model"], n_features=spec["n_features"],
                buckets=spec["buckets"] or DEFAULT_BUCKETS,
                fallback=spec["fallback"],
                data_profile=spec["data_profile"], **spec["guard_kw"],
            )
        if self._lifecycle is not None:
            server.attach_lifecycle(self._lifecycle)
        if self._started:
            server.start()
        # old server already stopped by kill/drain; swap in place — the
        # Replica object (and its registered collector) stays the same
        r.server = server
        r.state = REPLICA_LIVE
        self.metrics.inc("fleet.replicas_revived")
        log.info("replica revived", replica=index)

    def drain_replica(self, index: int, timeout_s: float = 5.0) -> bool:
        """Graceful removal, phase 1: stop routing new work to the
        replica, wait for its queues to empty, then stop it.  Returns
        True when the drain completed inside ``timeout_s``."""
        r = self._replicas[index]
        r.state = REPLICA_DRAINING
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            if r.load_rows() == 0:
                drained = True
                break
            time.sleep(0.005)
        r.server.stop()  # in-flight batch finishes; leftovers answer shutdown
        r.state = REPLICA_DEAD
        self.metrics.inc("fleet.replicas_drained")
        return drained

    def remove_replica(self, index: int, timeout_s: float = 5.0) -> bool:
        """Scale-down: drain, then take the replica off the hash ring —
        its tenants reshuffle to ring successors (~1/N of the space,
        the consistent-hash contract)."""
        drained = self.drain_replica(index, timeout_s=timeout_s)
        self.router.remove_replica(index)
        return drained

    def load_factor(self) -> float:
        """Queued rows / queue capacity across live replicas — the
        fleet-wide load gauge ``health()`` reports.  (Admission
        thresholds against the ROUTED replica's queue, not this
        average — see ``_front_door``.)"""
        live = [r for r in self._replicas if r.healthy()]
        if not live:
            return 1.0
        cap = sum(r.capacity_rows() for r in live)
        if cap <= 0:
            return 1.0
        return min(sum(r.load_rows() for r in live) / cap, 1.0)

    # ------------------------------------------------------------ serving
    def _shed(self, x2: np.ndarray, status: str, detail: str) -> Request:
        req = Request(x=x2, enqueued_at=time.monotonic(), deadline=None)
        req.complete(ServeResult(None, status, detail=detail))
        return req

    def _front_door(
        self,
        name: str,
        x2: np.ndarray,
        tenant_id: str | None,
        slo: str,
        deadline_s: float | None,
    ) -> tuple[Replica | None, float | None, Request | None]:
        """Routing + admission for one request: returns (replica,
        effective deadline, pre-answered shed request or None).

        Routing runs FIRST and admission thresholds against the ROUTED
        replica's queue load, not a fleet average: the class ladder then
        acts as reserved headroom per queue — with the shipped ladder,
        batch stops contending at 45% of the replica's queue and
        best_effort at 25% — so the top slice of every queue is
        effectively reserved for interactive and a lower class can
        never fill the queue an interactive request is about to need.
        (Fleet-averaged load lets class-blind per-replica queue
        rejections starve interactive anyway — measured, not
        hypothetical.)"""
        if name not in self._model_names:
            # loud, like the single server's KeyError: an unknown model
            # is a caller bug, not a replica loss to reroute around
            raise KeyError(
                f"model {name!r} is not served by this fleet; "
                f"have {sorted(self._model_names)}"
            )
        m = self.metrics
        keys = self._slo_keys.get(slo)
        if keys is None:
            # unknown class: reject BEFORE counting or interning — slo
            # is a metric label and an intern key, and caller-supplied
            # garbage must not grow either without bound
            known = (
                self.admission.classes if self.admission is not None
                else ()
            )
            if slo not in known:
                raise ValueError(
                    f"unknown SLO class {slo!r}; one of "
                    f"{sorted(known) or list(SLO_SHED_ORDER)}"
                )
            keys = (  # a configured custom class: intern its keys once
                f'fleet.requests_slo{{slo="{slo}"}}',
                f'fleet.shed{{slo="{slo}"}}',
            )
            self._slo_keys[slo] = keys
        m.inc("fleet.requests")
        m.inc(keys[0])
        with _trace.span("router.route") as sp:
            try:
                replica = self.router.route(tenant_id=tenant_id, model=name)
            except NoReplicaAvailable as e:
                m.inc("fleet.no_replica")
                return None, deadline_s, self._shed(
                    x2, STATUS_UNAVAILABLE, str(e)
                )
            if sp.trace_id is not None:
                sp.note("policy", self.router.policy)
                sp.note("replica", replica_label(replica.index))
        if self.admission is not None:
            cap = replica.capacity_rows()
            load = replica.load_rows() / cap if cap > 0 else 1.0
            decision = self.admission.admit(
                tenant_id, slo, int(x2.shape[0]), load
            )
            if deadline_s is None:
                deadline_s = decision.deadline_s
            if not decision.admitted:
                m.inc(keys[1])
                m.inc(
                    "fleet.shed_quota"
                    if decision.reason.startswith("quota:")
                    else "fleet.shed_load"
                )
                return None, deadline_s, self._shed(
                    x2, STATUS_REJECTED, f"admission: {decision.reason}"
                )
        return replica, deadline_s, None

    def _reroute(self, name: str, tenant_id: str | None) -> Replica | None:
        """A replica vanished between routing and dispatch (killed
        mid-flight): pick again — the router already excludes it."""
        self.metrics.inc("fleet.rerouted")
        try:
            return self.router.route(tenant_id=tenant_id, model=name)
        except NoReplicaAvailable:
            self.metrics.inc("fleet.no_replica")
            return None

    def submit(
        self,
        name: str,
        x: np.ndarray,
        tenant_id: str | None = None,
        slo: str = SLO_INTERACTIVE,
        deadline_s: float | None = None,
    ) -> Request:
        """Admit + route + enqueue, never blocks: the open-loop entry the
        load generator drives.  Every path returns a Request that WILL be
        answered — admission sheds and dead-fleet refusals come back
        pre-answered."""
        x2 = np.asarray(x)
        if x2.ndim == 1:
            x2 = x2[None, :]
        replica, deadline_s, shed = self._front_door(
            name, x2, tenant_id, slo, deadline_s
        )
        if shed is not None:
            return shed
        # retry while a healthy replica exists: each KeyError is a replica
        # dying between routing and dispatch, and the router already
        # excludes the dead — bounded by the replica count, and a live
        # replica is never discarded mid-retry
        for _ in range(len(self._replicas) + 1):
            if replica is None:
                break
            try:
                return replica.server.submit(name, x2, deadline_s=deadline_s)
            except KeyError:
                replica = self._reroute(name, tenant_id)
        return self._shed(x2, STATUS_UNAVAILABLE, "replica lost mid-dispatch")

    def _predict_routed(
        self,
        name: str,
        x: np.ndarray,
        route_key: str | None,
        slo: str,
        deadline_s: float | None,
        dispatch,
    ) -> ServeResult:
        """The ONE synchronous dispatch core both front doors share:
        fleet.request span → admission+route (``route_key`` drives the
        sticky hash) → ``dispatch(replica, x2, deadline_s)`` with one
        reroute on replica loss → per-class latency accounting over OK
        answers ONLY (folding ~0-latency sheds into the histogram would
        make p99 read healthiest exactly during overload)."""
        sp = _trace.span("fleet.request")
        with sp:
            x2 = np.asarray(x)
            if x2.ndim == 1:
                x2 = x2[None, :]
            replica, deadline_s, shed = self._front_door(
                name, x2, route_key, slo, deadline_s
            )
            if shed is not None:
                result = shed.wait(0.0)
            else:
                # same bounded retry as submit(): never discard a live
                # replica the reroute just found
                result = None
                for _ in range(len(self._replicas) + 1):
                    if replica is None:
                        break
                    try:
                        result = dispatch(replica, x2, deadline_s)
                        break
                    except KeyError:
                        replica = self._reroute(name, route_key)
                if result is None:
                    result = ServeResult(
                        None, STATUS_UNAVAILABLE,
                        detail="replica lost mid-dispatch",
                    )
            if result.ok:
                self.metrics.observe(
                    f'fleet.latency_seconds{{slo="{slo}"}}',
                    result.latency_s, LATENCY_EDGES_S,
                )
            if sp.trace_id is not None:
                sp.note("model", name)
                sp.note("slo", slo)
                sp.note("status", result.status)
                if replica is not None:
                    sp.note("replica", replica_label(replica.index))
        return result

    def set_max_wait_s(self, max_wait_s: float) -> int:
        """Retune the micro-batch linger fleet-wide, live: one float
        attribute store per running batcher (each worker reads
        ``max_wait_s`` fresh every loop — the existing atomic path, no
        new mutation protocol) plus the revive recipe, so a replica
        revived after the retune serves the tuned value too.  This is
        the apply seam of :class:`~...tune.live.LiveRetuner`; returns
        the number of batchers moved."""
        wait = float(max_wait_s)
        self._server_kw["max_wait_s"] = wait
        moved = 0
        for r in self._replicas:
            if r.state == REPLICA_DEAD:
                continue
            for b in list(r.server._batchers.values()):
                b.max_wait_s = wait
                moved += 1
        return moved

    def predict(
        self,
        name: str,
        x: np.ndarray,
        tenant_id: str | None = None,
        slo: str = SLO_INTERACTIVE,
        deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        """Synchronous front door: admission → route → the replica's own
        ``predict`` (guards, lifecycle hooks, serve.request span) → per-
        class latency accounting.  The ``fleet.request`` span roots the
        route: one trace id covers router→replica→model→answer."""
        return self._predict_routed(
            name, x, tenant_id, slo, deadline_s,
            lambda r, x2, dl: r.server.predict(
                name, x2, deadline_s=dl, wait_timeout_s=wait_timeout_s
            ),
        )

    def predict_tenant(
        self,
        name: str,
        tenant_id,
        x: np.ndarray,
        slo: str = SLO_INTERACTIVE,
        deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        """Tenant-routed predict over a served model farm: the SAME
        normalized tenant key drives the consistent-hash replica choice
        (sticky slice) and the farm's in-band slice gather on that
        replica.  Not-routable models answer ``invalid_input`` through
        the replica's own 400 lane."""
        model_view = None
        try:
            model_view = self.registry.get(name).model
        except KeyError:
            pass
        affinity = getattr(model_view, "affinity_key", str)(tenant_id)
        return self._predict_routed(
            name, x, affinity, slo, deadline_s,
            lambda r, x2, dl: r.server.predict_tenant(
                name, tenant_id, x2, deadline_s=dl,
                wait_timeout_s=wait_timeout_s,
            ),
        )

    # ------------------------------------------------------------ observe
    def health(self) -> dict[str, Any]:
        """Fleet health, read off ONE ``metrics.collect()`` — the pull-
        collector merge (replica counters sum, per-replica gauges keep
        their ``replica=`` labels) — instead of a second ad-hoc walk
        over replica dicts.  The key set is pinned by
        ``tests/test_fleet.py``."""
        snap = self.metrics.collect()
        c, g = snap["counters"], snap["gauges"]
        per_breaker: dict[str, dict[str, str]] = {}
        for key, val in g.items():
            base, labels = split_labels(key)
            if base == "fleet.breaker_state" and "replica" in labels:
                per_breaker.setdefault(labels["replica"], {})[
                    labels["model"]
                ] = _BREAKER_NAME.get(val, "unknown")
        replicas: dict[str, dict] = {}
        for r in self._replicas:
            lbl = replica_label(r.index)
            replicas[lbl] = {
                "state": r.state,
                "queue_rows": int(g.get(
                    f'fleet.replica_queue_rows{{replica="{replica_label(r.index)}"}}',
                    0,
                )),
                "breakers": per_breaker.get(lbl, {}),
            }
        breaker_degraded = any(
            state != "closed"
            for rep in replicas.values()
            for state in rep["breakers"].values()
        )
        degraded = breaker_degraded or any(
            r.state != REPLICA_LIVE for r in self._replicas
        )
        return {
            "status": (
                "stopped" if not self._started
                else "degraded" if degraded else "ok"
            ),
            "started": self._started,
            "replicas": replicas,
            "models_serving": sorted(self._model_names),
            "requests": int(c.get("fleet.requests", 0)),
            "served_requests": int(c.get("serve.requests", 0)),
            "shed": {
                slo: int(c.get(f'fleet.shed{{slo="{slo}"}}', 0))
                for slo in SLO_SHED_ORDER
            },
            "shed_quota": int(c.get("fleet.shed_quota", 0)),
            "shed_load": int(c.get("fleet.shed_load", 0)),
            "no_replica": int(c.get("fleet.no_replica", 0)),
            "rerouted": int(c.get("fleet.rerouted", 0)),
            "promotions": int(c.get("fleet.promotions", 0)),
            "replicas_killed": int(c.get("fleet.replicas_killed", 0)),
            "replicas_revived": int(c.get("fleet.replicas_revived", 0)),
            "fallback_answers": int(c.get("serve.fallback_answers", 0)),
            "drift_trips": int(c.get("serve.drift_trips", 0)),
            "queue_rows_total": sum(
                rep["queue_rows"] for rep in replicas.values()
            ),
            "load_factor": round(self.load_factor(), 4),
        }

    def stats(self) -> dict[str, Any]:
        """Raw merged snapshot (counters/gauges/histograms) — the full
        collect(), for dashboards; ``health()`` is the curated view."""
        return self.metrics.collect()
