"""Circuit breaker for model executables: closed → open → half-open.

The serving-side analogue of the stream's poison-batch quarantine: a
primary model that keeps failing (corrupt artifact hot-loaded, device in
a bad state, injected fault) must not have every request pay its failure
latency.  After ``failure_threshold`` consecutive failures the breaker
OPENS — requests short-circuit straight to the degraded/fallback path
without touching the device.  After ``recovery_timeout_s`` it admits
``half_open_max_calls`` probe requests (HALF-OPEN); a probe success
closes the breaker, a probe failure re-opens it and restarts the clock.

Pure host-side state under one lock — no jax, unit-testable with a fake
clock (``clock=`` is injectable for exactly that).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_timeout_s: float = 5.0,
        half_open_max_calls: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str, str], None] | None = None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_timeout_s = recovery_timeout_s
        self.half_open_max_calls = max(half_open_max_calls, 1)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        #: open-transition postmortem payload, staged under the lock and
        #: DUMPED AFTER it releases (see _flush_open_dump)
        self._pending_dump: dict | None = None
        self._state = STATE_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._half_open_inflight = 0
        self._half_open_successes = 0
        self.opened_count = 0          # lifetime open transitions
        self.short_circuited = 0       # calls refused while open
        self.tripped_count = 0         # forced opens via trip()
        self._last_trip_reason = ""
        self._last_reset_reason = ""   # forced closes via reset()

    # ------------------------------------------------------------ internals
    def _to(self, state: str) -> None:
        old, self._state = self._state, state
        if state == STATE_OPEN:
            self._opened_at = self._clock()
            self.opened_count += 1
            # a breaker opening IS a failure event: STAGE a postmortem.
            # The dump itself (metrics collect + fsync'd write) must run
            # OUTSIDE self._lock — it snapshots every breaker on the
            # server, so dumping in here would stall concurrent allow()
            # calls and deadlock ABBA when two breakers open at once.
            self._pending_dump = {
                "from_state": old,
                "reason": self._last_trip_reason or "failures",
                "consecutive_failures": self._consecutive_failures,
            }
        if state == STATE_HALF_OPEN:
            self._half_open_inflight = 0
            self._half_open_successes = 0
        if state == STATE_CLOSED:
            self._consecutive_failures = 0
        if self._on_transition is not None and old != state:
            self._on_transition(old, state)

    def _flush_open_dump(self) -> None:
        """Write the staged open-transition postmortem — called by every
        public mutator AFTER its lock block, so the flight-recorder dump
        (which re-reads breaker snapshots via the metrics collectors)
        never runs while this breaker's lock is held."""
        payload, self._pending_dump = self._pending_dump, None
        if payload is not None:
            from ..obs.flight_recorder import notify

            notify("breaker_trip", "serve.breaker", **payload)

    # ------------------------------------------------------------ protocol
    def allow(self) -> bool:
        """May this call hit the primary?  (Counts half-open probes.)"""
        with self._lock:
            if self._state == STATE_OPEN:
                if self._clock() - self._opened_at >= self.recovery_timeout_s:
                    self._to(STATE_HALF_OPEN)
                else:
                    self.short_circuited += 1
                    return False
            if self._state == STATE_HALF_OPEN:
                if self._half_open_inflight >= self.half_open_max_calls:
                    self.short_circuited += 1
                    return False
                self._half_open_inflight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                self._half_open_successes += 1
                if self._half_open_successes >= self.half_open_max_calls:
                    self._to(STATE_CLOSED)
            else:
                self._consecutive_failures = 0

    def trip(self, reason: str = "") -> None:
        """Force the breaker OPEN regardless of failure counts — the
        entry point for *external* degradation signals (sustained input
        drift, operator action).  Requests short-circuit to the fallback
        until the recovery timeout, exactly like failure-opened state;
        half-open probes then test the primary as usual."""
        with self._lock:
            self.tripped_count += 1
            self._last_trip_reason = reason
            if self._state != STATE_OPEN:
                self._to(STATE_OPEN)
            else:  # already open: restart the recovery clock
                self._opened_at = self._clock()
        self._flush_open_dump()

    def reset(self, reason: str = "") -> None:
        """Force the breaker CLOSED — the promotion-side counterpart of
        :meth:`trip`.  A freshly promoted model must answer immediately:
        the opens its predecessor accumulated (drift trips included) say
        nothing about the new executable, so the failure count and the
        recovery clock start over.  ``last_trip_reason`` is left intact —
        an operator auditing why the breaker ever opened must see the
        trip's cause, not the reset's label."""
        with self._lock:
            self._last_reset_reason = reason
            if self._state != STATE_CLOSED:
                self._to(STATE_CLOSED)
            else:
                self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                self._to(STATE_OPEN)  # failed probe: back off again
            else:
                self._consecutive_failures += 1
                if (
                    self._state == STATE_CLOSED
                    and self._consecutive_failures >= self.failure_threshold
                ):
                    self._to(STATE_OPEN)
        self._flush_open_dump()

    # ------------------------------------------------------------ observe
    @property
    def state(self) -> str:
        with self._lock:
            # surface the would-transition so health checks don't report
            # "open" forever on an idle server past its recovery timeout
            if (
                self._state == STATE_OPEN
                and self._clock() - self._opened_at >= self.recovery_timeout_s
            ):
                return STATE_HALF_OPEN
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            state = self._state
            # same would-transition view as .state: an idle breaker past
            # its recovery window must not read "open" forever in health
            if (
                state == STATE_OPEN
                and self._clock() - self._opened_at >= self.recovery_timeout_s
            ):
                state = STATE_HALF_OPEN
            return {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "opened_count": self.opened_count,
                "short_circuited": self.short_circuited,
                "tripped_count": self.tripped_count,
                "last_trip_reason": self._last_trip_reason,
                "last_reset_reason": self._last_reset_reason,
            }
