"""InferenceServer: registry + per-model micro-batchers, one front door.

The deployment-shaped surface: load saved artifacts into a registry,
``start()``, then ``predict(name, row)`` from any number of client
threads.  Each model gets its own :class:`MicroBatcher` (its own queue
and worker) so a slow family cannot head-of-line-block a fast one; the
metrics sink is shared so one ``stats()`` call reports the whole server.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ..io.model_io import load_data_profile, load_model
from ..models.base import Model
from ..obs import trace as _trace
from ..obs.registry import global_registry
from ..quality.drift import DriftMonitor, InputGuard, POLICY_REJECT
from ..quality.sketches import DataProfile, PSI_DRIFT
from ..tune import knob
from ..utils.faults import fault_point
from ..utils.logging import get_logger
from ..utils.metrics import MetricsRegistry
from .batcher import Fallback, MicroBatcher
from .breaker import STATE_CLOSED, CircuitBreaker
from .bucketing import DEFAULT_BUCKETS
from .metrics import ServingMetrics
from .queue import Request, ServeResult, STATUS_INVALID_INPUT
from .registry import ModelRegistry, ServingModel

log = get_logger("serve")


class NotRoutableError(TypeError):
    """A tenant-addressed request named a model that has no tenant
    routing (``route_request``) — a client/config error (400-shaped),
    never a server fault.  Carries the model name and family so the
    shed answer (and logs) can say exactly which registration is wrong.

    Subclasses :class:`TypeError` so pre-existing callers that caught
    the old duck-typing failure keep working.
    """

    def __init__(self, model_name: str, family: str):
        self.model_name = model_name
        self.family = family
        super().__init__(
            f"model {model_name!r} ({family}) is not tenant-routable; "
            "serve a ModelFarmModel under this name or use predict()"
        )


@dataclass
class PreparedSwap:
    """A built-and-warmed successor executable plus its resolved drift
    profile — everything :meth:`InferenceServer.commit_swap` needs to
    flip, with nothing left that can fail.  The fleet's atomic promotion
    prepares one of these per replica BEFORE any replica flips."""

    name: str
    sm: ServingModel
    profile: "DataProfile | None"
    family: str


class InferenceServer:
    """Online inference over one or more registered models.

    Every model is served behind its own :class:`CircuitBreaker` —
    repeated primary failures open it and requests degrade straight to
    the model's fallback instead of paying the failure each time.
    ``ingest_metrics`` (optional) folds the streaming pipeline's registry
    into :meth:`health`, so one snapshot covers quarantined batches and
    source retries alongside breaker states.
    """

    def __init__(
        self,
        registry: ModelRegistry | None = None,
        max_queue_rows: int | None = None,
        max_wait_s: float | None = None,
        breaker_failure_threshold: int = 5,
        breaker_recovery_s: float = 5.0,
        ingest_metrics: MetricsRegistry | None = None,
        device=None,
    ):
        self.registry = registry or ModelRegistry()
        #: replica placement (serve/fleet): every executable this server
        #: builds — add_model and swap alike — compiles for this device
        self.device = device
        self.metrics: ServingMetrics = self.registry.metrics
        # None → knob registry (serve.queue.max_rows /
        # serve.microbatch.max_wait_ms) at the moment batchers are built
        self.max_queue_rows = (
            int(knob("serve.queue.max_rows"))
            if max_queue_rows is None else max_queue_rows
        )
        self.max_wait_s = (
            knob("serve.microbatch.max_wait_ms") / 1e3
            if max_wait_s is None else max_wait_s
        )
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_recovery_s = breaker_recovery_s
        self.ingest_metrics = ingest_metrics
        self._batchers: dict[str, MicroBatcher] = {}
        self._fallbacks: dict[str, Fallback] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        #: per-model input guards / drift monitors (PR 3 data firewall)
        self._guards: dict[str, InputGuard] = {}
        self._monitors: dict[str, DriftMonitor] = {}
        #: per-model (threshold, window_rows, trip_after) from add_model,
        #: so a swap_model that has to CREATE a monitor keeps the tuning
        self._drift_params: dict[str, tuple[float, int, int]] = {}
        self._monitor_width_warned: set[str] = set()
        #: attached lifecycle controller (ISSUE 9): canary routing, shadow
        #: scoring, and the health() lifecycle fragment all hang off it
        self._lifecycle = None
        #: serializes hot swaps so the registry flip and the drift-
        #: reference rebase land as one operation
        self._swap_lock = threading.Lock()
        self._started = False
        self._register_obs()

    def _register_obs(self) -> None:
        """Fold this server into the process registry (ISSUE 10) as a
        weakref pull-collector: ``serve.*`` counters, breaker states,
        drift PSI, and the lifecycle phase all surface on the global
        Prometheus/JSON exporters without the request path writing two
        places.  Skipped when this server's ServingMetrics already
        writes the global registry directly (double-count guard)."""
        g = global_registry()
        if self.metrics.registry is g:
            return
        g.register_collector(
            f"serve:{id(self):x}", self, InferenceServer.obs_fragment
        )

    # ------------------------------------------------------------ obs
    #: numeric encoding of breaker states for the state gauge
    _BREAKER_CODE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

    def obs_fragment(self) -> dict:
        """This server's contribution to a registry pull: its own
        counters/gauges/histograms plus per-model breaker-state and
        drift-PSI gauges (label syntax — ``obs/export.py`` splits them)
        and the lifecycle phase."""
        reg = self.metrics.registry
        counters = dict(reg.counters)
        gauges = dict(reg.gauges)
        for name, b in list(self._breakers.items()):
            snap = b.snapshot()
            lbl = f'{{model="{name}"}}'
            gauges[f"serve.breaker_state{lbl}"] = self._BREAKER_CODE.get(
                snap["state"], -1.0
            )
            counters[f"serve.breaker_opened{lbl}"] = float(
                snap["opened_count"]
            )
        for name, m in list(self._monitors.items()):
            s = m.snapshot()
            lbl = f'{{model="{name}"}}'
            gauges[f"serve.drift_max_psi{lbl}"] = float(s["max_psi"])
            counters[f"serve.drift_windows{lbl}"] = float(s["windows"])
        lc = self._lifecycle
        if lc is not None and lc.state is not None:
            gauges["lifecycle.cycle"] = float(lc.cycle)
            gauges[f'lifecycle.phase{{phase="{lc.state}"}}'] = 1.0
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {
                k: h.to_dict() for k, h in reg.histograms.items()
            },
        }

    def metrics_text(self) -> str:
        """Prometheus exposition text for THIS server (own registry +
        fragment) — what a ``/metrics`` endpoint would return."""
        from ..obs.export import prometheus_text
        from ..obs.registry import MetricsRegistry

        view = MetricsRegistry()
        view.register_collector("self", self, InferenceServer.obs_fragment)
        return prometheus_text(view)

    def _breaker_for(self, name: str) -> CircuitBreaker:
        if name not in self._breakers:
            self._breakers[name] = CircuitBreaker(
                failure_threshold=self.breaker_failure_threshold,
                recovery_timeout_s=self.breaker_recovery_s,
                on_transition=self.metrics.record_breaker_transition,
            )
        return self._breakers[name]

    # ------------------------------------------------------------ setup
    def add_model(
        self,
        name: str,
        model: Model | str,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        fallback: Fallback = None,
        data_profile: dict | None = None,
        input_policy: str | None = None,
        drift_threshold: float = PSI_DRIFT,
        drift_window_rows: int = 512,
        drift_trip_after: int = 3,
    ) -> ServingModel:
        """Register a fitted model (or a saved-artifact path) for serving.
        ``fallback`` answers degraded requests for THIS model.

        Data-quality guards (PR 3): ``data_profile`` is the training-time
        feature profile (``quality.DataProfile.to_dict()``; auto-loaded
        from the artifact manifest when ``model`` is a path).  With a
        profile, live traffic is PSI-scored against it every
        ``drift_window_rows`` rows and ``drift_trip_after`` consecutive
        windows above ``drift_threshold`` TRIP the model's circuit
        breaker — a drifting feed degrades to fallback answers instead of
        silently mis-predicting.  ``input_policy`` guards individual
        requests: ``"impute"`` repairs non-finite / wildly
        out-of-reference-range values with the reference mean and counts
        them; ``"reject"`` refuses the request (``invalid_input``)."""
        if isinstance(model, str):
            if data_profile is None:
                data_profile = load_data_profile(model)
            sm = self.registry.load(
                name, model, n_features=n_features, buckets=buckets,
                device=self.device,
            )
        else:
            sm = self.registry.register(
                name, model, n_features=n_features, buckets=buckets,
                device=self.device,
            )
        self._drift_params[name] = (
            drift_threshold, drift_window_rows, drift_trip_after
        )
        if data_profile is not None:
            profile = DataProfile.from_dict(data_profile)
            self._monitors[name] = DriftMonitor(
                profile,
                threshold=drift_threshold,
                window_rows=drift_window_rows,
                trip_after=drift_trip_after,
            )
            if input_policy is not None:
                self._guards[name] = InputGuard(profile, policy=input_policy)
        elif input_policy is not None:
            # no reference: guard catches non-finite values only
            self._guards[name] = InputGuard(None, policy=input_policy)
        self._fallbacks[name] = fallback
        if self._started:  # hot-add: warm and attach a batcher now
            sm.warmup()
            self._batchers[name] = MicroBatcher(
                sm, max_queue_rows=self.max_queue_rows,
                max_wait_s=self.max_wait_s, fallback=fallback,
                metrics=self.metrics, breaker=self._breaker_for(name),
            ).start()
        return sm

    def swap_model(
        self,
        name: str,
        model: Model | str,
        n_features: int | None = None,
        buckets: Sequence[int] | None = None,
        data_profile: dict | DataProfile | None = None,
    ) -> ServingModel:
        """Hot-swap the model behind ``name`` — the promotion primitive.

        The new executable is built and warmed FIRST (no request ever
        pays its compile), then under one lock:

        1. the drift monitor's PSI reference is **rebased** to
           ``data_profile`` (the candidate's training profile) — atomic
           with the flip, because scoring post-flip traffic against the
           OLD training profile would re-trip the breaker forever: the
           drift that triggered the retrain is exactly the distribution
           the new model was trained on;
        2. the registry entry and the live batcher's model flip;
        3. the circuit breaker resets — opens accumulated against the
           predecessor (drift trips included) say nothing about the
           successor.

        Rebase lands *before* the flip, so the worst interleaving is one
        window of old-model traffic scored against the new reference
        (same distribution — harmless), never new-model traffic against
        the stale one.  Requests in flight on the old executable finish
        on it; nothing is ever refused because of a swap.

        Split into :meth:`prepare_swap` (everything that can fail: load,
        build, warm) and :meth:`commit_swap` (pure in-memory flips) so
        the serving fleet can prepare EVERY replica's successor before
        any replica flips — the all-or-none promotion contract.
        """
        return self.commit_swap(self.prepare_swap(
            name, model, n_features=n_features, buckets=buckets,
            data_profile=data_profile,
        ))

    def prepare_swap(
        self,
        name: str,
        model: Model | str,
        n_features: int | None = None,
        buckets: Sequence[int] | None = None,
        data_profile: dict | DataProfile | None = None,
    ) -> PreparedSwap:
        """Phase 1 of a hot swap: load/build/warm the successor executable
        and resolve its drift profile.  Raises on any failure; installs
        nothing — the live model keeps answering untouched."""
        if isinstance(model, str):
            if data_profile is None:
                data_profile = load_data_profile(model)
            model = load_model(model)
        if buckets is None:
            try:
                buckets = self.registry.get(name).buckets
            except KeyError:
                buckets = DEFAULT_BUCKETS
        sm = ServingModel(
            model, n_features=n_features, buckets=buckets,
            metrics=self.metrics, device=self.device,
        )
        if self._started:
            sm.warmup()
        profile = None
        if data_profile is not None:
            profile = (
                data_profile if isinstance(data_profile, DataProfile)
                else DataProfile.from_dict(data_profile)
            )
        elif name in self._monitors:
            # the re-trip hazard swap_model exists to fix, reintroduced
            # by omission: the new model will be PSI-scored against its
            # predecessor's training profile — say so loudly
            log.warning(
                "model swapped WITHOUT a data_profile: drift reference "
                "stays on the predecessor's training profile and may "
                "re-trip the breaker on the new model's own distribution",
                model=name,
            )
        return PreparedSwap(
            name=name, sm=sm, profile=profile,
            family=type(model).__name__,
        )

    def commit_swap(
        self, prepared: PreparedSwap, fire_fault_point: bool = True
    ) -> ServingModel:
        """Phase 2 of a hot swap: rebase the drift reference, flip the
        registry entry and live batcher, reset the breaker — all under
        one lock, nothing here can fail short of process death.

        ``fire_fault_point=False`` is for the fleet's commit loop: its
        injectable kill site is ``fleet.swap.commit``, fired ONCE before
        any replica flips — a per-replica site inside the loop would be
        a failure point mid-way through an all-or-none commit."""
        name, sm, profile = prepared.name, prepared.sm, prepared.profile
        if fire_fault_point:
            fault_point("lifecycle.registry.swap", model=name)
        with self._swap_lock:
            if profile is not None:
                mon = self._monitors.get(name)
                if mon is not None:
                    mon.rebase(profile)
                else:
                    th, wr, ta = self._drift_params.get(
                        name, (PSI_DRIFT, 512, 3)
                    )
                    self._monitors[name] = DriftMonitor(
                        profile, threshold=th, window_rows=wr, trip_after=ta
                    )
                guard = self._guards.get(name)
                if guard is not None:
                    self._guards[name] = InputGuard(
                        profile, policy=guard.policy
                    )
            self.registry.install(name, sm)
            batcher = self._batchers.get(name)
            if batcher is not None:
                batcher.model = sm
            breaker = self._breakers.get(name)
            if breaker is not None:
                breaker.reset("model swap")
            self._monitor_width_warned.discard(name)
        log.info(
            "model hot-swapped", name=name, family=prepared.family,
            profile_rebased=profile is not None,
        )
        return sm

    def attach_lifecycle(self, controller) -> None:
        """Wire a :class:`~..lifecycle.controller.LifecycleController` into
        the request path: canary routing (``on_request``), shadow/drift
        observation (``on_result``), and the ``lifecycle`` health key."""
        self._lifecycle = controller

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InferenceServer":
        """Warm every bucket executable, then start the batcher workers —
        in that order, so no request ever races a warmup compile."""
        for name in self.registry.names():
            sm = self.registry.get(name)
            sm.warmup()
            if name not in self._batchers:
                self._batchers[name] = MicroBatcher(
                    sm, max_queue_rows=self.max_queue_rows,
                    max_wait_s=self.max_wait_s,
                    fallback=self._fallbacks.get(name),
                    metrics=self.metrics, breaker=self._breaker_for(name),
                ).start()
        self._started = True
        log.info("inference server started", models=len(self._batchers))
        return self

    def stop(self) -> None:
        for b in list(self._batchers.values()):
            b.stop()
        self._batchers.clear()
        self._started = False

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ serve
    def _batcher(self, name: str) -> MicroBatcher:
        if name not in self._batchers:
            raise KeyError(
                f"model {name!r} is not being served "
                f"(started={self._started}); have {sorted(self._batchers)}"
            )
        return self._batchers[name]

    def _guard_input(
        self, name: str, x: np.ndarray
    ) -> tuple[np.ndarray, Request | None]:
        """Input guard + drift observation for one request.  Returns the
        (possibly repaired) batch, or a pre-answered ``invalid_input``
        request when the reject policy refused it."""
        guard = self._guards.get(name)
        if guard is not None:
            fixed, n_bad, reasons = guard.inspect(x)
            if n_bad:
                if guard.policy == POLICY_REJECT:
                    self.metrics.registry.inc("serve.inputs_rejected")
                    req = Request(
                        x=np.atleast_2d(np.asarray(x, dtype=np.float64)),
                        enqueued_at=time.monotonic(), deadline=None,
                    )
                    req.complete(ServeResult(
                        None, STATUS_INVALID_INPUT,
                        detail="; ".join(reasons),
                    ))
                    self.metrics.record_request(0.0, STATUS_INVALID_INPUT)
                    return x, req
                self.metrics.registry.inc("serve.inputs_imputed", n_bad)
                x = fixed
        monitor = self._monitors.get(name)
        if monitor is not None:
            rows = np.atleast_2d(np.asarray(x, dtype=np.float64))
            if rows.shape[1] != len(monitor.reference.names):
                # an armed monitor that can never observe is worse than
                # none — say so once instead of silently never tripping
                if name not in self._monitor_width_warned:
                    self._monitor_width_warned.add(name)
                    log.warning(
                        "drift monitor inert: request width != profile",
                        model=name, request_width=int(rows.shape[1]),
                        profile_width=len(monitor.reference.names),
                    )
            else:
                monitor.observe(rows)
                if monitor.should_trip():
                    self.metrics.registry.inc("serve.drift_trips")
                    self._breaker_for(name).trip(
                        f"sustained input drift (max PSI "
                        f"{monitor.max_psi:.3f} > {monitor.threshold})"
                    )
                    log.error(
                        "drift trip: serving degraded",
                        model=name, max_psi=round(monitor.max_psi, 4),
                    )
        return x, None

    def submit(self, name: str, x: np.ndarray, deadline_s: float | None = None):
        batcher = self._batcher(name)  # unknown-model KeyError first
        x, refused = self._guard_input(name, x)
        if refused is not None:
            return refused
        lc = self._lifecycle
        if lc is not None:
            # canary split: during CANARY the controller answers a
            # deterministic fraction of requests with the candidate,
            # tagged STATUS_CANARY (ok=True — a full-quality answer,
            # attributed); None keeps the request on the primary path.
            # The clock starts BEFORE the candidate predict so the
            # latency the client (and the p50/p99 reservoir) sees is the
            # real candidate compute cost, not ~0.
            t0 = time.monotonic()
            canary = lc.on_request(name, x)
            if canary is not None:
                req = Request(
                    x=np.atleast_2d(np.asarray(x, dtype=np.float64)),
                    enqueued_at=t0, deadline=None,
                )
                req.complete(canary)
                self.metrics.record_request(canary.latency_s, canary.status)
                return req
        return batcher.submit(x, deadline_s=deadline_s)

    def predict(
        self, name: str, x: np.ndarray, deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        # the serve.request span brackets admission→answer on the
        # CALLER's thread, so its duration is the latency the client saw;
        # span() is the shared no-op singleton when tracing is off — the
        # hot path allocates nothing for it (obs_overhead bench gate)
        sp = _trace.span("serve.request")
        with sp:
            result = self._predict_traced(sp, name, x, deadline_s,
                                          wait_timeout_s)
        return result

    def route_tenant(self, name: str, tenant_id: str, x: np.ndarray) -> np.ndarray:
        """tenant id + features → the in-band routed request for ``name``.
        Raises :class:`NotRoutableError` (carrying the model name) when
        the registered model has no tenant routing — the typed form of
        what used to be a bare duck-typing ``TypeError``."""
        sm = self.registry.get(name)
        route = getattr(sm.model, "route_request", None)
        if route is None:
            raise NotRoutableError(name, type(sm.model).__name__)
        return route(
            tenant_id, np.atleast_2d(np.asarray(x, dtype=np.float64))
        )

    def predict_tenant(
        self, name: str, tenant_id: str, x: np.ndarray,
        deadline_s: float | None = None, wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        """Route a per-hospital request to its tenant's slice of a model
        farm: tenant id → farm index (the farm's own table; unknown
        tenants fall back to the pooled GLOBAL slot), carried in-band as
        the request's leading column so the standard bucket ladder +
        on-device gather answer it — zero steady-state recompiles across
        tenants and batch sizes, one executable set for the whole fleet.

        A tenant request against a NON-farm model is a malformed request,
        not a server fault: it answers ``invalid_input`` (the 400 lane —
        no fallback, no breaker count), never a 500-equivalent.  Use
        :meth:`route_tenant` directly to get the typed
        :class:`NotRoutableError` instead of a shed answer.
        """
        try:
            xt = self.route_tenant(name, tenant_id, x)
        except NotRoutableError as e:
            self.metrics.record_request(0.0, STATUS_INVALID_INPUT)
            self.metrics.registry.inc("serve.not_routable")
            return ServeResult(None, STATUS_INVALID_INPUT, detail=str(e))
        return self.predict(
            name, xt, deadline_s=deadline_s, wait_timeout_s=wait_timeout_s
        )

    def _predict_traced(
        self, sp, name: str, x: np.ndarray, deadline_s: float | None,
        wait_timeout_s: float | None,
    ) -> ServeResult:
        req = self.submit(name, x, deadline_s=deadline_s)
        result = req.wait(wait_timeout_s)
        if sp.trace_id is not None:
            sp.note("model", name)
            sp.note("status", result.status)
            sp.note("rows", int(req.x.shape[0]))
        lc = self._lifecycle
        if lc is not None and result.status != STATUS_INVALID_INPUT:
            # post-answer observation: drift windows, the metric-decay
            # trigger, shadow scoring, canary accounting.  Observes
            # req.x — the GUARDED rows the model actually saw (imputed,
            # never the refused garbage), so one NaN request cannot
            # poison the evaluation window a promotion gate scores on.
            # The async submit() path skips this hook (no rendezvous to
            # observe); lifecycle-governed traffic goes through predict().
            try:
                lc.on_result(name, req.x, result)
            except Exception as e:  # noqa: BLE001 — observation must
                # never cost a client its (already computed) answer
                log.warning("lifecycle on_result failed", error=repr(e))
        return result

    # ------------------------------------------------------------ observe
    def stats(self) -> dict[str, Any]:
        out = self.metrics.snapshot()
        # snapshot before iterating: a concurrent add_model/swap/stop
        # mutates these dicts mid-walk (the PR 10 RuntimeError class)
        out["models"] = {
            name: {
                "buckets": list(b.model.buckets),
                "n_features": b.model.n_features,
                "queue_depth_rows": b.queue.depth_rows,
                "jit_cache_size": b.model.jit_cache_size(),
                "breaker": self._breakers[name].state
                if name in self._breakers else None,
            }
            for name, b in list(self._batchers.items())
        }
        return out

    def health(self) -> dict[str, Any]:
        """Liveness/degradation snapshot: breaker state per model plus the
        self-healing counters (quarantined batches, retry totals) — what a
        ``/healthz`` endpoint or an orchestrator's probe would poll."""
        breakers = {
            name: b.snapshot() for name, b in list(self._breakers.items())
        }
        drift = {
            name: m.snapshot() for name, m in list(self._monitors.items())
        }
        # status derives from breaker state only: SUSTAINED drift reaches
        # it through trip() (trip_after consecutive hot windows), while a
        # single hot window merely shows in the per-model "drifting"
        # field — one traffic burst must not read as a degraded server
        # to an orchestrator probe
        degraded = any(
            b["state"] != STATE_CLOSED for b in breakers.values()
        )
        serve_c = self.metrics.registry.counters
        ingest_c = (
            self.ingest_metrics.counters if self.ingest_metrics is not None
            else serve_c  # a shared registry folds ingest counters in
        )
        lifecycle = None
        if self._lifecycle is not None:
            try:
                lifecycle = self._lifecycle.health_fragment()
            except Exception as e:  # noqa: BLE001 — a broken controller
                # must not take down the health endpoint reporting it
                lifecycle = {"error": repr(e)}
        return {
            "status": (
                "stopped" if not self._started
                else "degraded" if degraded else "ok"
            ),
            "started": self._started,
            "lifecycle": lifecycle,
            "models_serving": sorted(self._batchers),
            "breakers": breakers,
            "drift": drift,
            "quarantined_batches": int(ingest_c.get("stream.quarantined", 0)),
            "quarantined_rows": int(ingest_c.get("stream.rows_rejected", 0)),
            "drift_events": int(ingest_c.get("stream.drift_events", 0)),
            "retry_totals": {
                "source_reads": int(ingest_c.get("stream.retries", 0)),
                "batch_replays": int(ingest_c.get("stream.batch_failures", 0)),
                "primary_failures": int(serve_c.get("serve.primary_failures", 0)),
            },
            "fallback_answers": int(serve_c.get("serve.fallback_answers", 0)),
            "inputs_imputed": int(serve_c.get("serve.inputs_imputed", 0)),
            "inputs_rejected": int(serve_c.get("serve.inputs_rejected", 0)),
            "drift_trips": int(serve_c.get("serve.drift_trips", 0)),
        }
