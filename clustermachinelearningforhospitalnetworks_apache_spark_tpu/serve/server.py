"""InferenceServer: registry + per-model micro-batchers, one front door.

The deployment-shaped surface: load saved artifacts into a registry,
``start()``, then ``predict(name, row)`` from any number of client
threads.  Each model gets its own :class:`MicroBatcher` (its own queue
and worker) so a slow family cannot head-of-line-block a fast one; the
metrics sink is shared so one ``stats()`` call reports the whole server.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ..models.base import Model
from ..utils.logging import get_logger
from ..utils.metrics import MetricsRegistry
from .batcher import DEFAULT_MAX_WAIT_S, Fallback, MicroBatcher
from .breaker import STATE_CLOSED, CircuitBreaker
from .bucketing import DEFAULT_BUCKETS
from .metrics import ServingMetrics
from .queue import ServeResult
from .registry import ModelRegistry, ServingModel

log = get_logger("serve")


class InferenceServer:
    """Online inference over one or more registered models.

    Every model is served behind its own :class:`CircuitBreaker` —
    repeated primary failures open it and requests degrade straight to
    the model's fallback instead of paying the failure each time.
    ``ingest_metrics`` (optional) folds the streaming pipeline's registry
    into :meth:`health`, so one snapshot covers quarantined batches and
    source retries alongside breaker states.
    """

    def __init__(
        self,
        registry: ModelRegistry | None = None,
        max_queue_rows: int = 4096,
        max_wait_s: float = DEFAULT_MAX_WAIT_S,
        breaker_failure_threshold: int = 5,
        breaker_recovery_s: float = 5.0,
        ingest_metrics: MetricsRegistry | None = None,
    ):
        self.registry = registry or ModelRegistry()
        self.metrics: ServingMetrics = self.registry.metrics
        self.max_queue_rows = max_queue_rows
        self.max_wait_s = max_wait_s
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_recovery_s = breaker_recovery_s
        self.ingest_metrics = ingest_metrics
        self._batchers: dict[str, MicroBatcher] = {}
        self._fallbacks: dict[str, Fallback] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        self._started = False

    def _breaker_for(self, name: str) -> CircuitBreaker:
        if name not in self._breakers:
            self._breakers[name] = CircuitBreaker(
                failure_threshold=self.breaker_failure_threshold,
                recovery_timeout_s=self.breaker_recovery_s,
                on_transition=self.metrics.record_breaker_transition,
            )
        return self._breakers[name]

    # ------------------------------------------------------------ setup
    def add_model(
        self,
        name: str,
        model: Model | str,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        fallback: Fallback = None,
    ) -> ServingModel:
        """Register a fitted model (or a saved-artifact path) for serving.
        ``fallback`` answers degraded requests for THIS model."""
        if isinstance(model, str):
            sm = self.registry.load(
                name, model, n_features=n_features, buckets=buckets
            )
        else:
            sm = self.registry.register(
                name, model, n_features=n_features, buckets=buckets
            )
        self._fallbacks[name] = fallback
        if self._started:  # hot-add: warm and attach a batcher now
            sm.warmup()
            self._batchers[name] = MicroBatcher(
                sm, max_queue_rows=self.max_queue_rows,
                max_wait_s=self.max_wait_s, fallback=fallback,
                metrics=self.metrics, breaker=self._breaker_for(name),
            ).start()
        return sm

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InferenceServer":
        """Warm every bucket executable, then start the batcher workers —
        in that order, so no request ever races a warmup compile."""
        for name in self.registry.names():
            sm = self.registry.get(name)
            sm.warmup()
            if name not in self._batchers:
                self._batchers[name] = MicroBatcher(
                    sm, max_queue_rows=self.max_queue_rows,
                    max_wait_s=self.max_wait_s,
                    fallback=self._fallbacks.get(name),
                    metrics=self.metrics, breaker=self._breaker_for(name),
                ).start()
        self._started = True
        log.info("inference server started", models=len(self._batchers))
        return self

    def stop(self) -> None:
        for b in self._batchers.values():
            b.stop()
        self._batchers.clear()
        self._started = False

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ serve
    def _batcher(self, name: str) -> MicroBatcher:
        if name not in self._batchers:
            raise KeyError(
                f"model {name!r} is not being served "
                f"(started={self._started}); have {sorted(self._batchers)}"
            )
        return self._batchers[name]

    def submit(self, name: str, x: np.ndarray, deadline_s: float | None = None):
        return self._batcher(name).submit(x, deadline_s=deadline_s)

    def predict(
        self, name: str, x: np.ndarray, deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        return self._batcher(name).predict(
            x, deadline_s=deadline_s, wait_timeout_s=wait_timeout_s
        )

    # ------------------------------------------------------------ observe
    def stats(self) -> dict[str, Any]:
        out = self.metrics.snapshot()
        out["models"] = {
            name: {
                "buckets": list(b.model.buckets),
                "n_features": b.model.n_features,
                "queue_depth_rows": b.queue.depth_rows,
                "jit_cache_size": b.model.jit_cache_size(),
                "breaker": self._breakers[name].state
                if name in self._breakers else None,
            }
            for name, b in self._batchers.items()
        }
        return out

    def health(self) -> dict[str, Any]:
        """Liveness/degradation snapshot: breaker state per model plus the
        self-healing counters (quarantined batches, retry totals) — what a
        ``/healthz`` endpoint or an orchestrator's probe would poll."""
        breakers = {name: b.snapshot() for name, b in self._breakers.items()}
        degraded = any(b["state"] != STATE_CLOSED for b in breakers.values())
        serve_c = self.metrics.registry.counters
        ingest_c = (
            self.ingest_metrics.counters if self.ingest_metrics is not None
            else serve_c  # a shared registry folds ingest counters in
        )
        return {
            "status": (
                "stopped" if not self._started
                else "degraded" if degraded else "ok"
            ),
            "started": self._started,
            "models_serving": sorted(self._batchers),
            "breakers": breakers,
            "quarantined_batches": int(ingest_c.get("stream.quarantined", 0)),
            "retry_totals": {
                "source_reads": int(ingest_c.get("stream.retries", 0)),
                "batch_replays": int(ingest_c.get("stream.batch_failures", 0)),
                "primary_failures": int(serve_c.get("serve.primary_failures", 0)),
            },
            "fallback_answers": int(serve_c.get("serve.fallback_answers", 0)),
        }
