"""Adaptive micro-batcher: single rows in, padded bucket batches out.

The online counterpart of ``streaming/microbatch.py``'s StreamExecution
loop: where that driver coalesces FILES into micro-batches for training,
this one coalesces REQUESTS into padded device batches for inference.
The loop shape is the same — poll, coalesce, execute, commit — but the
latency budget is milliseconds, so the coalescing window adapts instead
of polling on a fixed cadence:

* queue deep (≥ one full top bucket waiting): fire immediately — waiting
  cannot improve fill, only tail latency;
* queue shallow: linger up to ``max_wait_s`` for followers, trading a
  bounded latency add for batch fill (the knob that decides whether the
  chip sees 1-row or 64-row matmuls).

Every admitted request is answered exactly once (see ``queue.py``); the
degradation ladder (shed at admission, drop at deadline, fallback answer
when configured) lives here because only the batcher knows *when* a
request finally reaches the device.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Union

import numpy as np

from ..tune import knob
from ..tune import default as knob_default
from ..utils.logging import get_logger
from .breaker import CircuitBreaker
from .metrics import ServingMetrics
from .queue import (
    DEGRADED_STATUSES,
    Request,
    RequestQueue,
    ServeResult,
    STATUS_DEADLINE_EXCEEDED,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHUTDOWN,
    STATUS_UNAVAILABLE,
)
from .registry import ServingModel

log = get_logger("serve")

#: default linger for followers when the queue is shallow — 2 ms buys
#: coalescing at realistic arrival rates without a visible latency bump.
#: Owned by the knob registry (``serve.microbatch.max_wait_ms``); this
#: compat constant is the DECLARED default — pass ``max_wait_s=None`` to
#: resolve through the installed selector instead.
DEFAULT_MAX_WAIT_S = knob_default("serve.microbatch.max_wait_ms") / 1e3

Fallback = Union["ServingModel", Callable[[np.ndarray], np.ndarray], None]


class MicroBatcher:
    """Background worker that serves a :class:`ServingModel` from a
    bounded request queue with adaptive coalescing.

    ``fallback`` handles degraded answers: a cheaper :class:`ServingModel`
    (or any ``rows -> predictions`` callable, e.g. a class prior) whose
    output is returned with ``degraded=True`` instead of a bare 503-style
    refusal.  The fallback runs on the CALLER's thread — it must be cheap
    by construction, and a saturated main queue must not serialize sheds
    behind itself.
    """

    def __init__(
        self,
        model: ServingModel,
        max_queue_rows: int | None = None,
        max_wait_s: float | None = None,
        fallback: Fallback = None,
        metrics: ServingMetrics | None = None,
        breaker: CircuitBreaker | None = None,
    ):
        self.model = model
        self.metrics = metrics or model.metrics
        # None → resolved through the knob registry (declared default
        # when no selector is installed — bit-identical to the old
        # literals, pinned by tests/test_autotune.py)
        self.queue = RequestQueue(max_rows=max_queue_rows)
        self.max_wait_s = (
            knob("serve.microbatch.max_wait_ms") / 1e3
            if max_wait_s is None else float(max_wait_s)
        )
        self.fallback = fallback
        #: wraps the primary executable: repeated failures OPEN it and
        #: requests short-circuit to the fallback without device time
        self.breaker = breaker
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="serve-microbatcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the worker (the batch in flight finishes — the join covers
        one device call); still-queued requests are answered ``shutdown``
        rather than stranded."""
        self._stop.set()
        self.queue.wake_all()
        if self._thread is not None:
            self._thread.join(timeout)
        for req in self.queue.drain_all():
            self._answer_degraded(req, STATUS_SHUTDOWN, "server stopped")

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ client API
    def submit(
        self,
        x: np.ndarray,
        deadline_s: float | None = None,
    ) -> Request:
        """Admit a request (1..top-bucket rows); returns the
        :class:`Request` whose ``.wait()`` yields the result.  A saturated
        queue answers immediately (``rejected``/fallback) — admission
        NEVER blocks."""
        x = np.asarray(x)
        if x.ndim == 1:
            x = x[None, :]
        top = self.model.buckets[-1]
        if x.shape[0] > top:
            raise ValueError(
                f"{x.shape[0]} rows exceed the top bucket {top}; bulk-score "
                "through serve.scoring instead"
            )
        now = time.monotonic()
        req = Request(
            x=x,
            enqueued_at=now,
            deadline=None if deadline_s is None else now + deadline_s,
        )
        if self._stop.is_set():  # stopped server: answer, don't strand
            self._answer_degraded(req, STATUS_SHUTDOWN, "server stopped")
        elif not self.queue.offer(req):
            self._answer_degraded(req, STATUS_REJECTED, "queue saturated")
        elif self._stop.is_set():
            # stop() ran between the check above and the offer: its drain
            # may have missed this request, so drain again — drain_all is
            # atomic, so each request is answered exactly once either way
            for r in self.queue.drain_all():
                self._answer_degraded(r, STATUS_SHUTDOWN, "server stopped")
        self.metrics.set_queue_depth(self.queue.depth_rows)
        return req

    def predict(
        self, x: np.ndarray, deadline_s: float | None = None,
        wait_timeout_s: float | None = 30.0,
    ) -> ServeResult:
        """Synchronous convenience: submit + wait."""
        return self.submit(x, deadline_s=deadline_s).wait(wait_timeout_s)

    # ------------------------------------------------------------ worker
    def _run(self) -> None:
        top = self.model.buckets[-1]
        while not self._stop.is_set():
            # adaptive window: deep queue → take a full bucket now;
            # shallow queue → linger for followers
            linger = 0.0 if self.queue.depth_rows >= top else self.max_wait_s
            batch = self.queue.take(top, wait_s=0.05, more_wait_s=linger)
            if not batch:
                continue
            self.metrics.set_queue_depth(self.queue.depth_rows)
            now = time.monotonic()
            live = [r for r in batch if not r.expired(now)]
            for r in batch:
                if r.expired(now):
                    self._answer_degraded(
                        r, STATUS_DEADLINE_EXCEEDED, "expired while queued"
                    )
            if not live:
                continue
            self._execute(live)

    def _execute(self, live: list[Request]) -> None:
        if self.breaker is not None and not self.breaker.allow():
            # circuit open: the primary doesn't even see the batch —
            # every waiter gets a fallback answer immediately
            for r in live:
                self._answer_degraded(r, STATUS_UNAVAILABLE, "circuit open")
            return
        rows = np.concatenate([r.x for r in live], axis=0)
        try:
            preds = self.model.predict_bucketed(rows)
        except Exception as e:  # noqa: BLE001 — a poisoned batch must
            # answer every waiter, not kill the worker thread
            if self.breaker is not None:
                self.breaker.record_failure()
            self.metrics.record_primary_failure()
            log.error("batch predict failed", error=repr(e), rows=rows.shape[0])
            for r in live:
                self._answer_degraded(r, STATUS_UNAVAILABLE, repr(e))
            return
        if self.breaker is not None:
            self.breaker.record_success()
        s = 0
        for r in live:
            r.complete(ServeResult(preds[s : s + r.rows], STATUS_OK))
            self.metrics.record_request(
                time.monotonic() - r.enqueued_at, STATUS_OK
            )
            s += r.rows

    # ------------------------------------------------------------ degrade
    def _answer_degraded(self, req: Request, status: str, detail: str) -> None:
        value = None
        degraded = False
        if self.fallback is not None and status in DEGRADED_STATUSES:
            try:
                fb = self.fallback
                value = (
                    fb.predict(req.x) if isinstance(fb, ServingModel)
                    else np.asarray(fb(req.x))
                )
                degraded = True
            except Exception as e:  # noqa: BLE001 — degradation must not raise
                log.warning("fallback failed", error=repr(e))
        if degraded:
            self.metrics.record_fallback_answer()
        req.complete(
            ServeResult(value, status, degraded=degraded, detail=detail)
        )
        self.metrics.record_request(
            time.monotonic() - req.enqueued_at, status
        )
