"""Model registry: saved artifact → jitted, shape-bucketed predict.

The online half of ``io/model_io.py``: ``load_model(path)`` rebuilds any
registered family, and :class:`ServingModel` wraps its stable raw-array
predict (``models/base.py::Model.serving_predict_fn``) in ONE ``jax.jit``
executable per shape bucket.  Warmup compiles the whole ladder up front;
after that a request of any size ≤ the top bucket hits a cached
executable — the serving analogue of Flare's "compile the hot path
natively, don't interpret the dataflow" (arXiv:1703.08219), with XLA
doing the compiling and the bucket ladder keeping the executable count
finite.

Recompiles are tracked two ways: a semantic counter (a request shape
outside the warmed set) and, where the jax version exposes it, the jit
cache size itself — ``tests/test_serving.py`` cross-checks both.
"""

from __future__ import annotations

import threading
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import load_model
from ..models.base import Model
from ..utils.faults import fault_point
from ..utils.logging import get_logger
from .bucketing import (
    DEFAULT_BUCKETS,
    bucket_for,
    fill_ratio,
    iter_chunks,
    pad_to_bucket,
    validate_buckets,
)
from .metrics import ServingMetrics

log = get_logger("serve")


def _donate_ok() -> bool:
    """Donation elides the output allocation on TPU (the padded batch
    buffer is dead after the call); the CPU backend just warns, so only
    donate where it pays."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # backend not initializable — caller will find out
        return False


class ServingModel:
    """A loaded model behind a fixed ladder of compiled batch shapes."""

    def __init__(
        self,
        model: Model,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        metrics: ServingMetrics | None = None,
        dtype=jnp.float32,
        donate: bool | None = None,
        device=None,
    ):
        self.model = model
        self.buckets = validate_buckets(buckets)
        self.metrics = metrics or ServingMetrics()
        self.dtype = dtype
        #: replica placement (serve/fleet): executables compile for and run
        #: on this committed device; None keeps jax's default placement
        self.device = device
        n = n_features if n_features is not None else model.num_features
        if n is None:
            raise ValueError(
                f"{type(model).__name__} does not expose num_features; pass "
                "n_features= explicitly so bucket executables can be sized"
            )
        self.n_features = int(n)
        donate = _donate_ok() if donate is None else donate
        fn = model.serving_predict_fn()
        self._jitted = jax.jit(fn, donate_argnums=(0,) if donate else ())
        self._warmed: set[int] = set()
        self._lock = threading.Lock()

    def _put(self, x: np.ndarray) -> jax.Array:
        """Host batch → device operand; a committed ``device`` pins the
        executable to the replica's slice of the mesh."""
        a = jnp.asarray(x)
        return a if self.device is None else jax.device_put(a, self.device)

    # ------------------------------------------------------------ compile
    def warmup(self, buckets: Sequence[int] | None = None) -> "ServingModel":
        """Compile (and execute once) every bucket shape so steady-state
        serving never pays a compile.  Idempotent; returns self."""
        for b in validate_buckets(buckets) if buckets else self.buckets:
            with self._lock:
                if b in self._warmed:
                    continue
                self._warmed.add(b)
            self.metrics.record_compile(b, warm=True)
            z = np.zeros((b, self.n_features), dtype=np.dtype(self.dtype))
            jax.block_until_ready(self._jitted(self._put(z)))
        return self

    def jit_cache_size(self) -> int | None:
        """The wrapped jit's compiled-executable count, when the jax
        version exposes it — None otherwise.  Stable across steady-state
        serving iff the bucket contract holds."""
        cache_size = getattr(self._jitted, "_cache_size", None)
        return cache_size() if callable(cache_size) else None

    # ------------------------------------------------------------ serve
    def predict_bucketed(self, x: np.ndarray) -> np.ndarray:
        """One padded device call: pick the bucket, pad, predict, slice.

        ``x`` must fit the largest bucket; :meth:`predict` splits larger
        inputs.  Thread-safe (jax dispatch is)."""
        x = np.ascontiguousarray(x, dtype=np.dtype(self.dtype))
        if x.ndim == 1:
            x = x[None, :]
        n = x.shape[0]
        # the primary-model fault site: chaos tests fail the executable
        # here to drive the batcher's circuit breaker
        fault_point(
            "serve.predict", model=type(self.model).__name__, rows=n
        )
        b = bucket_for(n, self.buckets)
        with self._lock:
            if b not in self._warmed:
                # a shape outside the warmed ladder: this compile happens
                # on the request path — the counter that must stay 0
                self._warmed.add(b)
                cold = True
            else:
                cold = False
        if cold:
            log.warning("steady-state compile", bucket=b, n=n)
            self.metrics.record_compile(b, warm=False)
        out = self._jitted(self._put(pad_to_bucket(x, b)))
        self.metrics.record_batch(n, b)
        return np.asarray(jax.device_get(out))[:n]

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Predict any batch size: oversized inputs stream through the top
        bucket's executable chunk by chunk (still zero recompiles)."""
        x = np.asarray(x)
        if x.ndim == 1:
            x = x[None, :]
        top = self.buckets[-1]
        if x.shape[0] <= top:
            return self.predict_bucketed(x)
        parts = [self.predict_bucketed(piece) for _, piece in iter_chunks(x, top)]
        return np.concatenate(parts, axis=0)

    def batch_fill(self, n: int) -> float:
        return fill_ratio(n, bucket_for(n, self.buckets))


class ModelRegistry:
    """Name → :class:`ServingModel`, loadable straight from saved artifact
    directories (``model.save(path)`` → ``registry.load(name, path)``)."""

    def __init__(self, metrics: ServingMetrics | None = None):
        self.metrics = metrics or ServingMetrics()
        self._models: dict[str, ServingModel] = {}
        self._lock = threading.Lock()

    def register(
        self,
        name: str,
        model: Model,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warmup: bool = False,
        dtype=jnp.float32,
        device=None,
    ) -> ServingModel:
        sm = ServingModel(
            model, n_features=n_features, buckets=buckets,
            metrics=self.metrics, dtype=dtype, device=device,
        )
        if warmup:
            sm.warmup()
        with self._lock:
            self._models[name] = sm
        log.info(
            "model registered", name=name, family=type(model).__name__,
            n_features=sm.n_features, buckets=len(sm.buckets),
        )
        return sm

    def load(
        self,
        name: str,
        path: str,
        n_features: int | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warmup: bool = False,
        device=None,
    ) -> ServingModel:
        """``io/model_io.load_model`` + wrap: any family the persistence
        registry knows round-trips straight into serving."""
        return self.register(
            name, load_model(path), n_features=n_features,
            buckets=buckets, warmup=warmup, device=device,
        )

    def install(self, name: str, sm: ServingModel) -> ServingModel:
        """Install an already-built (e.g. pre-warmed) :class:`ServingModel`
        under ``name`` — the hot-swap entry point: the previous executable
        keeps answering until this one atomic dict swap, so a promotion
        never serves a cold or half-registered model."""
        with self._lock:
            self._models[name] = sm
        log.info(
            "model installed (hot swap)", name=name,
            family=type(sm.model).__name__,
        )
        return sm

    def get(self, name: str) -> ServingModel:
        with self._lock:
            if name not in self._models:
                raise KeyError(
                    f"no model {name!r} in registry; have {sorted(self._models)}"
                )
            return self._models[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def warmup_all(self) -> None:
        for name in self.names():
            self.get(name).warmup()
