"""Serving metrics: tail latency, queue depth, batch fill, recompiles.

Rides :mod:`..obs.registry` — the ONE metrics surface (ISSUE 10) — so
serve counters, gauges, and distributions live in the same
``MetricsRegistry`` the exporters read and the training pipeline feeds.
The latency and batch-fill distributions are **fixed-bucket mergeable
histograms** (``obs.registry.FixedHistogram``, the ``quality/sketches``
discipline) instead of the pre-ISSUE-10 sampled reservoir: p50/p99 come
from bounded state that merges exactly across replicas, ``_sum/_count``
keep the exact mean, and the Prometheus exporter gets real ``_bucket``
series instead of two pre-baked percentiles.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

from ..obs.registry import (
    LATENCY_EDGES_S,
    MetricsRegistry,
    RATIO_EDGES,
)

#: registry keys for the two serving distributions
LATENCY_HIST = "serve.latency_seconds"
FILL_HIST = "serve.batch_fill"


@dataclass
class ServingMetrics:
    """Thread-safe serving-side metrics sink.

    Each sink owns its registry by default, so two servers (or two test
    cases) never bleed counters into each other; pass
    ``utils.metrics.global_metrics()`` explicitly to fold serve counters
    into the process-wide registry, or let :class:`~.server
    .InferenceServer` register its pull-collector on the global one.
    """

    registry: MetricsRegistry = field(default_factory=MetricsRegistry)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    # ------------------------------------------------------------ record
    def record_request(self, latency_s: float, status: str = "ok") -> None:
        with self._lock:
            self.registry.inc("serve.requests")
            self.registry.inc(f"serve.status.{status}")
            self.registry.observe(LATENCY_HIST, latency_s, LATENCY_EDGES_S)

    def record_batch(self, n_valid: int, bucket: int) -> None:
        with self._lock:
            self.registry.inc("serve.batches")
            self.registry.inc("serve.rows", float(n_valid))
            self.registry.inc("serve.padded_rows", float(bucket - n_valid))
            self.registry.observe(
                FILL_HIST, n_valid / bucket if bucket else 0.0, RATIO_EDGES
            )

    def record_compile(self, bucket: int, warm: bool) -> None:
        """``warm`` marks planned warmup compiles; anything else is a
        steady-state recompile — the number that must read 0."""
        with self._lock:
            self.registry.inc(
                "serve.warmup_compiles" if warm else "serve.recompiles"
            )

    def record_primary_failure(self) -> None:
        """A primary-model executable raised — the breaker's raw signal."""
        with self._lock:
            self.registry.inc("serve.primary_failures")

    def record_fallback_answer(self) -> None:
        """A degraded request was answered by the fallback path."""
        with self._lock:
            self.registry.inc("serve.fallback_answers")

    def record_breaker_transition(self, old: str, new: str) -> None:
        with self._lock:
            self.registry.inc("serve.breaker_transitions")
            self.registry.inc(f"serve.breaker.to_{new}")

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self.registry.set("serve.queue_depth_rows", float(rows))
            peak = self.registry.gauges.get("serve.queue_depth_peak", 0.0)
            if rows > peak:
                self.registry.set("serve.queue_depth_peak", float(rows))

    # ------------------------------------------------------------ read
    @property
    def recompile_count(self) -> int:
        return int(self.registry.counters.get("serve.recompiles", 0))

    def percentile(self, q: float) -> float | None:
        """Histogram-interpolated latency percentile (``q`` in 0..100)."""
        h = self.registry.histograms.get(LATENCY_HIST)
        if h is None or h.count <= 0:
            return None
        return max(h.quantile(q / 100.0), 0.0)

    def batch_fill_ratio(self) -> float | None:
        """Exact mean real-rows fraction (histogram ``sum/count``)."""
        h = self.registry.histograms.get(FILL_HIST)
        if h is None or h.count <= 0:
            return None
        return float(h.mean)

    def snapshot(self) -> dict[str, Any]:
        c = self.registry.counters
        out = {
            "requests": int(c.get("serve.requests", 0)),
            "batches": int(c.get("serve.batches", 0)),
            "rows": int(c.get("serve.rows", 0)),
            "warmup_compiles": int(c.get("serve.warmup_compiles", 0)),
            "recompiles": self.recompile_count,
            "queue_depth_rows": self.registry.gauges.get(
                "serve.queue_depth_rows", 0.0
            ),
            "queue_depth_peak": self.registry.gauges.get(
                "serve.queue_depth_peak", 0.0
            ),
            "primary_failures": int(c.get("serve.primary_failures", 0)),
            "fallback_answers": int(c.get("serve.fallback_answers", 0)),
            "breaker_transitions": int(c.get("serve.breaker_transitions", 0)),
            "statuses": {
                k.split(".", 2)[2]: int(v)
                for k, v in c.items()
                if k.startswith("serve.status.")
            },
        }
        p50, p99 = self.percentile(50), self.percentile(99)
        if p50 is not None:
            out["latency_p50_ms"] = round(p50 * 1e3, 3)
            out["latency_p99_ms"] = round(p99 * 1e3, 3)
        fill = self.batch_fill_ratio()
        if fill is not None:
            out["batch_fill_ratio"] = round(fill, 4)
        return out
