"""Sharded batch scoring: bulk jobs over the data mesh.

The offline half of the serving layer — MLlib's ``model.transform()``
batch scoring (arXiv:1505.06807 §4) re-aimed at the mesh: rows are laid
out over the ``data`` axis exactly as in training
(``parallel/sharding.py``), one jitted predict runs per fixed-shape
chunk, and only the predictions cross back to host.  Chunking reuses the
online layer's shape discipline: every chunk is padded to ONE canonical
shape so the whole scan runs through a single compiled executable — a
10M-row scoring job compiles once, not ⌈10M/chunk⌉ times.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

import jax
import numpy as np

from ..models.base import Model
from ..parallel.mesh import default_mesh
from ..parallel.partitioner import family as _partitioner_family
from ..parallel.sharding import device_dataset, unpad
from ..utils.profiling import device_fence

#: default rows per sharded scoring chunk (multiple of any data-axis size
#: that divides a power of two)
DEFAULT_CHUNK_ROWS = 262_144

#: per-model jitted predict cache for ad-hoc ``bulk_score`` calls —
#: ``serving_predict_fn()`` returns a fresh closure per call, so jitting
#: it inline retraced+recompiled EVERY job (ISSUE 13 jit-in-function
#: finding; the PR 5 retrace-per-fit class).  Keyed by ``id(model)``
#: with a weakref identity check (model dataclasses are eq-based, hence
#: unhashable — a WeakKeyDictionary can't hold them; the ref guards
#: against id() reuse after gc).  The jitted closure itself keeps the
#: model's arrays alive, so a finalizer could never fire — the cache is
#: LRU-capped instead (the sql_compile ``_KERNELS`` discipline): repeat
#: jobs against one live model reuse the warm executable, a fleet of
#: one-off models can't grow it unboundedly.
_BULK_FN_CACHE: dict[int, tuple] = {}
_BULK_FN_CACHE_CAP = 64
_BULK_FN_LOCK = threading.Lock()


def _bulk_fn(model: Model):
    # bulk_score is called from scoring-service threads: the pop/evict/
    # insert sequence must be atomic (an unsynchronized LRU evict races
    # to a KeyError).  jax.jit() only builds the wrapper — tracing and
    # compilation happen at first CALL, outside this lock.
    key = id(model)
    with _BULK_FN_LOCK:
        got = _BULK_FN_CACHE.pop(key, None)  # re-insert = move to MRU end
        if got is not None and got[0]() is model:
            _BULK_FN_CACHE[key] = got
            return got[1]
        while len(_BULK_FN_CACHE) >= _BULK_FN_CACHE_CAP:
            _BULK_FN_CACHE.pop(next(iter(_BULK_FN_CACHE)))  # evict LRU
        entry = _BULK_FN_CACHE[key] = (
            weakref.ref(model), jax.jit(model.serving_predict_fn())
        )
        return entry[1]


def bulk_score(
    model: Model,
    x: np.ndarray,
    mesh: Any | None = None,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> np.ndarray:
    """Score ``x`` (host ndarray, (n, d)) over the mesh, returning (n,)
    predictions.  Inputs larger than ``chunk_rows`` stream through one
    fixed-shape executable; the last partial chunk pads up to the same
    shape (its pad rows are sliced off on the way out)."""
    mesh = mesh or default_mesh()
    x = np.atleast_2d(np.asarray(x))
    n = x.shape[0]
    fn = _bulk_fn(model)
    if n <= chunk_rows:
        ds = device_dataset(x, mesh=mesh)
        return unpad(fn(ds.x), n)
    # row-chunk multiple from the one partitioner (divisible by data axis)
    chunk = _partitioner_family("rows").round_rows(chunk_rows, mesh)
    out = np.empty((n,), dtype=np.float32)
    for s in range(0, n, chunk):
        piece = x[s : s + chunk]
        if piece.shape[0] < chunk:  # tail: pad to the canonical shape
            piece = np.concatenate(
                [piece, np.zeros((chunk - piece.shape[0], x.shape[1]), x.dtype)]
            )
        ds = device_dataset(piece, mesh=mesh)
        got = unpad(fn(ds.x), min(chunk, n - s))
        out[s : s + got.shape[0]] = got
    return out


class ShardedScorer:
    """Reusable bulk scorer: one model, one mesh, one compiled chunk shape.

    For scoring *services* (many bulk jobs against the same model) this
    keeps the executable and mesh placement warm across calls — the
    counterpart of :class:`..serve.registry.ServingModel` for the
    throughput-bound path, where latency is measured per JOB and the right
    batch shape is "as many rows as the mesh holds", not a micro-bucket.
    """

    def __init__(
        self,
        model: Model,
        mesh: Any | None = None,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
    ):
        self.model = model
        self.mesh = mesh or default_mesh()
        self.chunk_rows = _partitioner_family("rows").round_rows(
            chunk_rows, self.mesh
        )
        self._fn = jax.jit(model.serving_predict_fn())

    def warmup(self) -> "ShardedScorer":
        d = self.model.num_features
        if d is None:
            return self  # first score() pays the compile instead
        z = np.zeros((self.chunk_rows, d), dtype=np.float32)
        ds = device_dataset(z, mesh=self.mesh)
        device_fence(self._fn(ds.x))
        return self

    def score(self, x: np.ndarray) -> np.ndarray:
        """Every job — large or small — streams through the ONE canonical
        chunk shape (small jobs pad up), so a long-lived scorer never
        recompiles; ``bulk_score`` is the one-shot alternative that sizes
        to the job instead."""
        x = np.atleast_2d(np.asarray(x))
        n = x.shape[0]
        out = np.empty((n,), dtype=np.float32)
        for s in range(0, n, self.chunk_rows):
            piece = x[s : s + self.chunk_rows]
            m = piece.shape[0]
            if m < self.chunk_rows:
                piece = np.concatenate(
                    [piece,
                     np.zeros((self.chunk_rows - m, x.shape[1]), x.dtype)]
                )
            ds = device_dataset(piece, mesh=self.mesh)
            out[s : s + m] = unpad(self._fn(ds.x), m)
        return out
