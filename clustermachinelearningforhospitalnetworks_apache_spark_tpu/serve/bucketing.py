"""Shape buckets: the zero-recompile contract of the serving layer.

``jax.jit`` specializes an executable per input *shape*; an online server
that forwarded raw request batches would recompile on every new batch size
— tens of seconds per shape on a real chip, fatal for tail latency.  The
serving layer therefore admits only a fixed ladder of power-of-two batch
sizes: every request batch is padded up to the smallest bucket that holds
it, so after one warmup pass over the ladder the steady state triggers
ZERO compiles regardless of arrival pattern.  Pad rows are sliced off on
the way out; predictions are row-local in every served family, so padding
can never leak into a real row's result (asserted by
``tests/test_serving.py::test_bucket_padding_parity``).

This is the serving-side analogue of ``parallel/sharding.py``'s training
contract (pad + validity weights); here validity is positional (first
``n`` rows) because a predict has no reductions over rows.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Iterator, Sequence, Tuple

import numpy as np

#: default ladder: singles ride the 1-bucket, bulk requests cap at 1024
#: rows per executable — larger inputs are split (see :func:`iter_chunks`).
DEFAULT_BUCKETS: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def validate_buckets(buckets: Sequence[int]) -> tuple[int, ...]:
    """Sorted, deduplicated, all-positive bucket ladder."""
    out = tuple(sorted(set(int(b) for b in buckets)))
    if not out or out[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    return out


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ ``n`` (callers split inputs larger than the top
    bucket with :func:`iter_chunks` first)."""
    if n < 1:
        raise ValueError(f"need at least one row, got {n}")
    i = bisect_left(buckets, n)
    if i == len(buckets):
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket {buckets[-1]}; "
            "split it with iter_chunks()"
        )
    return buckets[i]


def pad_to_bucket(x: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad rows up to ``bucket`` (no-op view when already full)."""
    n = x.shape[0]
    if n == bucket:
        return x
    if n > bucket:
        raise ValueError(f"{n} rows do not fit bucket {bucket}")
    out = np.zeros((bucket,) + x.shape[1:], dtype=x.dtype)
    out[:n] = x
    return out


def iter_chunks(
    x: np.ndarray, max_bucket: int
) -> Iterator[Tuple[int, np.ndarray]]:
    """Split an arbitrarily large request into ≤``max_bucket``-row pieces,
    yielding ``(start_row, piece)`` — full pieces reuse the top bucket's
    executable, the tail pads into whatever bucket fits it."""
    n = x.shape[0]
    for s in range(0, n, max_bucket):
        yield s, x[s : s + max_bucket]


def fill_ratio(n_valid: int, bucket: int) -> float:
    """Fraction of the padded batch that is real rows — the serving
    analogue of MXU utilization; the adaptive batcher's coalescing exists
    to push this toward 1.0."""
    return n_valid / bucket if bucket else 0.0
