"""OneVsRest — multiclass reduction over any binary classifier.

Parity with ``pyspark.ml.classification.OneVsRest``: fit one binary
model per class (label == c → 1), predict by the highest per-class
confidence.  Spark runs the k fits as k sequential MLlib jobs; here each
is one of this framework's sharded fits, and the *scoring* side stays on
the mesh — all k models score in one pass and the argmax never leaves the
device.

Works with any classifier whose model exposes ``predict_proba`` (the
class-1 column is the confidence) or, failing that, ``predict_raw``
(margin).  Persists as a composite artifact (one sub-directory per class
model), the same layout machinery as PipelineModel/CrossValidatorModel.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import (
    METADATA_FILE,
    load_model,
    finalize_artifact_dir,
    prepare_artifact_dir,
    register_composite,
    save_model,
    validate_persistable,
    write_metadata,
)
from ..parallel.sharding import DeviceDataset
from ..version import __version__
from .base import Estimator, Model, as_device_dataset

_OVR_CLASS = "OneVsRestModel"


def _confidence(model: Any, x: jax.Array) -> jax.Array:
    """(n,) class-1 confidence from whatever surface the model has."""
    if hasattr(model, "predict_proba"):
        p = model.predict_proba(x)
        return p[:, 1] if p.ndim == 2 else p
    if hasattr(model, "predict_raw"):
        r = model.predict_raw(x)
        return r[:, 1] if r.ndim == 2 else r
    raise TypeError(
        f"{type(model).__name__} exposes neither predict_proba nor "
        "predict_raw; OneVsRest needs a per-class confidence"
    )


@dataclass
class OneVsRestModel(Model):
    models: tuple[Any, ...]          # one binary model per class, in order

    @property
    def num_classes(self) -> int:
        return len(self.models)

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """(n, k) per-class confidences — one device pass per class, no
        host round trips between classes."""
        return jnp.stack([_confidence(m, x) for m in self.models], axis=1)

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_raw(x), axis=1).astype(jnp.float32)

    # persistence (composite: one sub-artifact per class) ----------------
    def save(self, path: str, overwrite: bool = True) -> None:
        for i, m in enumerate(self.models):
            validate_persistable(m, label=f"class {i} model")
        prepare_artifact_dir(path, overwrite)
        os.makedirs(os.path.join(path, "models"))
        dirs = []
        for i, m in enumerate(self.models):
            name, meta, arrays = m._artifacts()
            d = f"{i}_{name}"
            save_model(os.path.join(path, "models", d), name, meta, arrays)
            dirs.append(d)
        write_metadata(
            path,
            {
                "model_class": _OVR_CLASS,
                "framework_version": __version__,
                "model_dirs": dirs,
            },
        )
        finalize_artifact_dir(path)  # commit: drop sentinel, discard .old

    @classmethod
    def load(cls, path: str, _meta: dict | None = None) -> "OneVsRestModel":
        if _meta is None:
            with open(os.path.join(path, METADATA_FILE)) as f:
                _meta = json.load(f)
        return cls(
            tuple(
                load_model(os.path.join(path, "models", d))
                for d in _meta["model_dirs"]
            )
        )


@dataclass(frozen=True)
class OneVsRest(Estimator):
    classifier: Any = None            # a BINARY classifier estimator
    label_col: str = "LOS_binary"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None) -> OneVsRestModel:
        if self.classifier is None:
            raise ValueError("OneVsRest needs a classifier estimator")
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            # pass-through composition: each one-vs-all fit streams blocks
            # through the INNER estimator's own out-of-core path — the
            # relabeled y is a host array, so the k sub-datasets cost
            # nothing beyond the label vector
            if data.y is None:
                raise ValueError("OneVsRest needs labels: HostDataset(y=...)")
            if getattr(self.classifier, "weight_col", None) is not None:
                raise ValueError(
                    "set weight_col on OneVsRest itself, not the inner "
                    "classifier (the one-vs-all HostDataset already carries "
                    "the weights)"
                )
            y_host = np.asarray(data.y)
            w_host = (
                np.asarray(data.w)
                if data.w is not None
                else np.ones(data.n, np.float32)
            )
            if not np.any(w_host > 0):
                raise ValueError("OneVsRest fit on an empty dataset")
            k = int(y_host[w_host > 0].max()) + 1
            if k < 2:
                raise ValueError("OneVsRest needs at least 2 classes")
            models = []
            for c in range(k):
                sub = HostDataset(
                    x=data.x,
                    y=(y_host == float(c)).astype(np.float32),
                    w=data.w,
                    max_device_rows=data.max_device_rows,
                )
                models.append(self.classifier.fit(sub, mesh=mesh))
            return OneVsRestModel(tuple(models))
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        if not np.any(w_host > 0):
            raise ValueError("OneVsRest fit on an empty dataset")
        k = int(y_host[w_host > 0].max()) + 1
        if k < 2:
            raise ValueError("OneVsRest needs at least 2 classes")
        if getattr(self.classifier, "weight_col", None) is not None:
            raise ValueError(
                "set weight_col on OneVsRest itself, not the inner "
                "classifier (the one-vs-all DeviceDataset already carries "
                "the weights)"
            )
        models = []
        for c in range(k):
            # one-vs-all labels baked into the DeviceDataset; the inner
            # estimator's label_col is ignored for DeviceDataset inputs
            yc = (ds.y == float(c)).astype(jnp.float32)
            sub = DeviceDataset(x=ds.x, y=yc, w=ds.w)
            models.append(self.classifier.fit(sub, mesh=mesh))
        return OneVsRestModel(tuple(models))


register_composite(
    _OVR_CLASS,
    "clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.one_vs_rest:OneVsRestModel",
)
