"""LinearRegression — sharded weighted least squares.

Parity with ``pyspark.ml.regression.LinearRegression`` at reference
``mllearnforhospitalnetwork.py:146-148`` (fit on ``features`` →
``length_of_stay``, then ``transform`` on the test split).

MLlib solves this with WLS when the feature count is small: per-partition
Gram/moment accumulation combined via ``treeAggregate``, then a normal-
equations solve on the driver (SURVEY.md §3.3).  The TPU-native form is the
same algorithm with the communication inverted into XLA: the Gram matrix
``XᵀWX`` and moment vector ``XᵀWy`` are computed by one jit'd matmul over
the row-sharded dataset — the cross-shard sum lowers to a ``psum`` over
ICI — and the (d+1)×(d+1) solve runs on device.  Ridge (``reg_param``)
matches Spark's L2 regularization (applied to coefficients, not the
intercept, on standardized features when ``standardize=True``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset


def standardized_design(x, w, reg_param, fit_intercept: bool, standardize: bool):
    """Shared GLM preamble (LinearRegression + LogisticRegression): the
    intercept-augmented design matrix and the Spark-semantics ridge vector
    (L2 on *standardized* coefficients, intercept unpenalized).

    → (xa, ridge, nfeat, n) — traceable inside a jitted fit."""
    n = jnp.maximum(jnp.sum(w), 1.0)
    wcol = w[:, None]
    mean = jnp.sum(x * wcol, axis=0) / n
    var = jnp.sum(x * x * wcol, axis=0) / n - mean * mean
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    scale = std if standardize else jnp.ones_like(std)
    if fit_intercept:
        xa = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    else:
        xa = x
    nfeat = x.shape[1]
    ridge = jnp.zeros((xa.shape[1],), x.dtype).at[:nfeat].set(
        reg_param * n * scale * scale
    )
    return xa, ridge, nfeat, n


@partial(jax.jit, static_argnames=("fit_intercept", "standardize"))
def _wls_fit(x, y, w, reg_param, fit_intercept: bool, standardize: bool):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(x, w, reg_param, fit_intercept, standardize)
    d = xa.shape[1]
    wcol = w[:, None]
    # Gram + moments: the treeAggregate replacement — one matmul each,
    # cross-shard reduction is an XLA psum.
    gram = (xa * wcol).T @ xa + jnp.diag(ridge)
    mom = (xa * wcol).T @ y
    theta = jnp.linalg.solve(
        gram + 1e-8 * jnp.eye(d, dtype=x.dtype), mom
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)
    return coef, intercept


@register_model("LinearRegressionModel")
@dataclass
class LinearRegressionModel(Model):
    coefficients: jax.Array
    intercept: jax.Array

    def predict(self, x: jax.Array) -> jax.Array:
        return x.astype(jnp.float32) @ self.coefficients + self.intercept

    def _artifacts(self):
        return (
            "LinearRegressionModel",
            {},
            {
                "coefficients": np.asarray(self.coefficients),
                "intercept": np.asarray(self.intercept),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=jnp.asarray(arrays["coefficients"]),
            intercept=jnp.asarray(arrays["intercept"]),
        )


@dataclass(frozen=True)
class LinearRegression(Estimator):
    features_col: str = "features"
    label_col: str = "length_of_stay"
    reg_param: float = 0.0
    fit_intercept: bool = True
    standardize: bool = True

    def fit(self, data, label_col: str | None = None, mesh=None) -> LinearRegressionModel:
        ds: DeviceDataset = as_device_dataset(data, label_col or self.label_col, mesh=mesh)
        coef, intercept = _wls_fit(
            ds.x, ds.y, ds.w, jnp.float32(self.reg_param), self.fit_intercept, self.standardize
        )
        return LinearRegressionModel(coefficients=coef, intercept=intercept)
