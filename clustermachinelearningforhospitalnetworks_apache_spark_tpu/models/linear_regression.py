"""LinearRegression — sharded weighted least squares.

Parity with ``pyspark.ml.regression.LinearRegression`` at reference
``mllearnforhospitalnetwork.py:146-148`` (fit on ``features`` →
``length_of_stay``, then ``transform`` on the test split).

MLlib solves this with WLS when the feature count is small: per-partition
Gram/moment accumulation combined via ``treeAggregate``, then a normal-
equations solve on the driver (SURVEY.md §3.3).  The TPU-native form is the
same algorithm with the communication inverted into XLA: the Gram matrix
``XᵀWX`` and moment vector ``XᵀWy`` are computed by one jit'd matmul over
the row-sharded dataset — the cross-shard sum lowers to a ``psum`` over
ICI — and the (d+1)×(d+1) solve runs on device.  Ridge (``reg_param``)
matches Spark's L2 regularization (applied to coefficients, not the
intercept, on standardized features when ``standardize=True``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.outofcore import add_stats as _lr_add_stats
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset, check_features


def weighted_moments(x, w):
    """Weighted per-feature moments with one degenerate-variance rule
    shared by every GLM path: a (near-)constant feature gets std 1.0, so
    standardization never divides by ~0 and the L2/L1 penalty applies at
    full strength to its (undetermined, centered-to-zero) coefficient.

    → (n, mean, std) — traceable inside a jitted fit."""
    n = jnp.maximum(jnp.sum(w), 1.0)
    wcol = w[:, None]
    mean = jnp.sum(x * wcol, axis=0) / n
    var = jnp.sum(x * x * wcol, axis=0) / n - mean * mean
    std = jnp.where(var > 1e-12, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    return n, mean, std


def standardized_design(x, w, reg_param, fit_intercept: bool, standardize: bool):
    """Shared GLM preamble (LinearRegression + LogisticRegression): the
    intercept-augmented design matrix and the Spark-semantics ridge vector
    (L2 on *standardized* coefficients, intercept unpenalized).

    → (xa, ridge, nfeat, n) — traceable inside a jitted fit."""
    n, mean, std = weighted_moments(x, w)
    scale = std if standardize else jnp.ones_like(std)
    if fit_intercept:
        xa = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    else:
        xa = x
    nfeat = x.shape[1]
    ridge = jnp.zeros((xa.shape[1],), x.dtype).at[:nfeat].set(
        reg_param * n * scale * scale
    )
    return xa, ridge, nfeat, n


def _fista(g, c, l1, l2, tol, max_iter: int):
    """FISTA proximal loop on a precomputed standardized (d, d) Gram —
    minimizes ½β̃ᵀGβ̃ − cᵀβ̃ + l1‖β̃‖₁ + l2/2‖β̃‖².  Traceable; shared by
    the resident elastic-net fit and the out-of-core gram path."""
    d_feat = g.shape[0]

    # Lipschitz constant of ∇f: λmax(G) + l2, via power iteration.
    def pow_body(_, v):
        v = g @ v
        return v / jnp.maximum(jnp.linalg.norm(v), 1e-30)

    v0 = jnp.ones((d_feat,), g.dtype) / jnp.sqrt(jnp.float32(d_feat))
    v = jax.lax.fori_loop(0, 32, pow_body, v0)
    lips = jnp.maximum(v @ (g @ v), 1e-12) + l2

    def soft(u, t):
        return jnp.sign(u) * jnp.maximum(jnp.abs(u) - t, 0.0)

    def cond(carry):
        _, _, _, it, delta = carry
        return (it < max_iter) & (delta > tol)

    def body(carry):
        beta, z, t, it, _ = carry
        grad = g @ z - c + l2 * z
        beta_new = soft(z - grad / lips, l1 / lips)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = beta_new + ((t - 1.0) / t_new) * (beta_new - beta)
        delta = jnp.max(jnp.abs(beta_new - beta))
        return beta_new, z_new, t_new, it + 1, delta

    beta0 = jnp.zeros((d_feat,), g.dtype)
    beta, _, _, n_iter, _ = jax.lax.while_loop(
        cond, body, (beta0, beta0, jnp.float32(1.0), 0, jnp.float32(jnp.inf))
    )
    return beta, n_iter


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter"))
def _elastic_net_fit(
    x, y, w, reg_param, en_param, tol,
    fit_intercept: bool, standardize: bool, max_iter: int,
):
    """Elastic-net WLS via FISTA on the Gram matrix.

    Spark's ``elasticNetParam`` path (the OWL-QN branch of the estimator
    behind ``mllearnforhospitalnetwork.py:146-148``): minimize

        1/(2n) Σ wᵢ (yᵢ − xᵢβ − b)²  +  λ(α‖β̃‖₁ + (1−α)/2 ‖β̃‖²)

    with β̃ the standardized-scale coefficients when ``standardize`` and
    the intercept unpenalized.  TPU shape: ONE sharded pass builds the
    (d, d) Gram + moments (matmuls whose cross-shard sum is a psum), then
    FISTA runs on the tiny Gram entirely on device — no per-iteration data
    pass, unlike OWL-QN's per-step treeAggregate.
    """
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    n, mean, std = weighted_moments(x, w)
    wcol = w[:, None]
    scale = std if standardize else jnp.ones_like(std)
    ybar = jnp.sum(y * w) / n
    if fit_intercept:
        xc_mean, yc = mean, ybar
    else:
        xc_mean = jnp.zeros_like(mean)
        yc = jnp.zeros_like(ybar)

    # Gram/moments of the centered, scaled design — the only data pass.
    xs = (x - xc_mean[None, :]) / scale[None, :]
    g = (xs * wcol).T @ xs / n                       # (d, d)
    c = (xs * wcol).T @ (y - yc) / n                 # (d,)

    beta, n_iter = _fista(
        g, c, reg_param * en_param, reg_param * (1.0 - en_param), tol, max_iter
    )
    coef = beta / scale
    intercept = (
        ybar - mean @ coef if fit_intercept else jnp.zeros((), x.dtype)
    )
    return coef, intercept, n_iter


@partial(jax.jit, static_argnames=("fit_intercept", "standardize"))
def _wls_fit(x, y, w, reg_param, fit_intercept: bool, standardize: bool):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(x, w, reg_param, fit_intercept, standardize)
    d = xa.shape[1]
    wcol = w[:, None]
    # Gram + moments: the treeAggregate replacement — one matmul each,
    # cross-shard reduction is an XLA psum.
    gram = (xa * wcol).T @ xa + jnp.diag(ridge)
    mom = (xa * wcol).T @ y
    theta = jnp.linalg.solve(
        gram + 1e-8 * jnp.eye(d, dtype=x.dtype), mom
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)
    return coef, intercept


@jax.jit
def _lr_block_stats(x, y, w, shift):
    """Per-block weighted moment/Gram statistics on SHIFTED features
    (xs = x − shift; the shift — a host-sample mean — kills the
    Gram-minus-mean-outer catastrophic cancellation in f32, the same trick
    as the GMM E-step's recentering).  Reductions over the row-sharded
    block lower to psums; the out-of-core driver sums the per-block
    results — identical statistics to one resident pass."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xs = x - shift[None, :]
    wcol = w[:, None]
    return (
        jnp.sum(w),                        # Σw
        jnp.sum(xs * wcol, axis=0),        # Σw·xs
        jnp.sum(xs * xs * wcol, axis=0),   # Σw·xs²
        jnp.sum(y * w),                    # Σw·y
        (xs * wcol).T @ xs,                # XsᵀWXs
        (xs * wcol).T @ y,                 # XsᵀWy
    )


@partial(
    jax.jit,
    static_argnames=("fit_intercept", "standardize", "elastic", "max_iter"),
)
def _lr_solve_from_stats(
    stats, shift, reg_param, en_param, tol,
    fit_intercept: bool, standardize: bool, elastic: bool, max_iter: int,
):
    """Accumulated block stats → (coef, intercept).

    Solves in centered-standardized coordinates: (g + λ·I)β̃ = c for
    ridge/OLS (algebraically identical to :func:`_wls_fit`'s augmented
    system with Spark's unpenalized intercept), or FISTA for elastic net —
    the same solver the resident path uses.
    """
    sw, sx, sxx, sy, gram, mom = stats
    n = jnp.maximum(sw, 1.0)
    mean_s = sx / n                       # mean of shifted features
    var = sxx / n - mean_s * mean_s
    std = jnp.where(var > 1e-12, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    scale = std if standardize else jnp.ones_like(std)
    ybar = sy / n
    if fit_intercept:
        g_c = gram / n - jnp.outer(mean_s, mean_s)
        c_c = mom / n - mean_s * ybar
    else:  # caller guarantees shift == 0 here
        g_c = gram / n
        c_c = mom / n
    g = g_c / jnp.outer(scale, scale)
    c = c_c / scale
    if elastic:
        beta, _ = _fista(
            g, c, reg_param * en_param, reg_param * (1.0 - en_param), tol, max_iter
        )
    else:
        d = g.shape[0]
        beta = jnp.linalg.solve(
            g + (reg_param + 1e-8) * jnp.eye(d, dtype=g.dtype), c
        )
    coef = beta / scale
    if fit_intercept:
        intercept = ybar - (mean_s + shift) @ coef
    else:
        intercept = jnp.zeros((), g.dtype)
    return coef, intercept


@partial(jax.jit, static_argnames=("fit_intercept",))
def _wls_partial_stats(x, y, w, fit_intercept: bool):
    """One silo's WLS sufficient statistics — the summation-mergeable
    decomposition of :func:`_wls_fit`'s reductions: raw feature moments
    (for the standardization scale), the intercept-augmented Gram, and
    the moment vector.  Shipped as ``Partials``; summing them across
    silos and feeding :func:`_wls_fit_from_stats` reproduces the pooled
    fit (bit-tight when the per-silo sums are exact, e.g. integer-valued
    features — float data matches to merge-reassociation rounding)."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    if fit_intercept:
        xa = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    else:
        xa = x
    wcol = w[:, None]
    return (
        jnp.sum(w),                       # Σw
        jnp.sum(x * wcol, axis=0),        # Σw·x
        jnp.sum(x * x * wcol, axis=0),    # Σw·x²
        (xa * wcol).T @ xa,               # XᵀWX (augmented)
        (xa * wcol).T @ y,                # XᵀWy
    )


@partial(jax.jit, static_argnames=("fit_intercept", "standardize"))
def _wls_fit_from_stats(
    sw, sx, sxx, gram, mom, reg_param, fit_intercept: bool, standardize: bool
):
    """Merged statistics → (coef, intercept), mirroring the tail of
    :func:`_wls_fit` operation-for-operation (same moments rule, same
    Spark ridge vector, same jitter) so the federated solve and the
    pooled solve share bits, not just math."""
    n = jnp.maximum(sw, 1.0)
    mean = sx / n
    var = sxx / n - mean * mean
    std = jnp.where(var > 1e-12, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    scale = std if standardize else jnp.ones_like(std)
    nfeat = sx.shape[0]
    dd = gram.shape[0]
    ridge = jnp.zeros((dd,), gram.dtype).at[:nfeat].set(
        reg_param * n * scale * scale
    )
    theta = jnp.linalg.solve(
        gram + jnp.diag(ridge) + 1e-8 * jnp.eye(dd, dtype=gram.dtype), mom
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), gram.dtype)
    return coef, intercept


@register_model("LinearRegressionModel")
@dataclass
class LinearRegressionModel(Model):
    coefficients: jax.Array
    intercept: jax.Array
    _summary: object | None = field(default=None, repr=False, compare=False)

    @property
    def has_summary(self) -> bool:
        return self._summary is not None

    def release_summary(self) -> None:
        """Drop the summary's reference to the training dataset, unpinning
        it from device memory (see models/summary.py memory note)."""
        self._summary = None

    @property
    def summary(self):
        """Training summary (rmse/r2/residuals/t-values …) — fresh fits
        only, like Spark's ``hasSummary``."""
        if self._summary is None:
            from .summary import summary_unavailable

            raise summary_unavailable("LinearRegressionModel")
        return self._summary

    def predict(self, x: jax.Array) -> jax.Array:
        check_features(x, self.coefficients.shape[0], "LinearRegressionModel")
        return x.astype(jnp.float32) @ self.coefficients + self.intercept

    def _artifacts(self):
        return (
            "LinearRegressionModel",
            {},
            {
                "coefficients": np.asarray(self.coefficients),
                "intercept": np.asarray(self.intercept),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=jnp.asarray(arrays["coefficients"]),
            intercept=jnp.asarray(arrays["intercept"]),
        )


@dataclass(frozen=True)
class LinearRegression(Estimator):
    """``elastic_net_param`` mirrors Spark's ``elasticNetParam``: 0 = pure
    L2 ridge (closed-form WLS), 1 = lasso, in between = elastic net
    (FISTA on the sharded Gram — see ``_elastic_net_fit``).  ``max_iter``/
    ``tol`` only apply to the iterative elastic-net path."""

    features_col: str = "features"
    label_col: str = "length_of_stay"
    reg_param: float = 0.0
    elastic_net_param: float = 0.0
    max_iter: int = 100        # Spark default
    tol: float = 1e-6          # Spark default
    fit_intercept: bool = True
    standardize: bool = True
    weight_col: str | None = None  # Spark's weightCol

    def fit(self, data, label_col: str | None = None, mesh=None) -> LinearRegressionModel:
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds: DeviceDataset = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        if self.elastic_net_param > 0.0 and self.reg_param > 0.0:
            coef, intercept, _ = _elastic_net_fit(
                ds.x, ds.y, ds.w,
                jnp.float32(self.reg_param), jnp.float32(self.elastic_net_param),
                jnp.float32(self.tol), self.fit_intercept, self.standardize,
                self.max_iter,
            )
        else:
            coef, intercept = _wls_fit(
                ds.x, ds.y, ds.w, jnp.float32(self.reg_param), self.fit_intercept, self.standardize
            )
        model = LinearRegressionModel(coefficients=coef, intercept=intercept)
        # lazy training summary (Spark: fresh fits carry .summary) — holds
        # only references; every metric computes on first read
        from .summary import LinearRegressionTrainingSummary

        model._summary = LinearRegressionTrainingSummary(
            model, ds, self.reg_param, self.elastic_net_param, self.fit_intercept
        )
        return model

    # ---------------------------------------------------- partials protocol
    partials_family = "linear"

    def supports_partials(self) -> bool:
        # the elastic-net path centers the design around the POOLED mean
        # before its FISTA Gram — that coupling does not decompose into
        # per-silo summations, so it stays pooled-only
        return not (self.elastic_net_param > 0.0 and self.reg_param > 0.0)

    def init_partials_state(self, n_features: int, mesh=None):
        return None  # single-shot family: no state between rounds

    def partial_fit_stats(
        self, data, label_col: str | None = None, mesh=None,
        state=None, final: bool = False,
    ):
        from ..federated.partials import Partials

        if not self.supports_partials():
            raise NotImplementedError(
                "elastic-net LinearRegression centers the design on the "
                "pooled mean — not partials-decomposable; use reg_param "
                "with elastic_net_param=0 (ridge) for federated fits"
            )
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh,
            weight_col=self.weight_col,
        )
        sw, sx, sxx, gram, mom = _wls_partial_stats(
            ds.x, ds.y, ds.w, self.fit_intercept
        )
        sw = np.asarray(jax.device_get(sw))
        return Partials(
            family=self.partials_family,
            stats={
                "sw": sw,
                "sx": np.asarray(jax.device_get(sx)),
                "sxx": np.asarray(jax.device_get(sxx)),
                "gram": np.asarray(jax.device_get(gram)),
                "mom": np.asarray(jax.device_get(mom)),
            },
            n_rows=float(sw),
        )

    def apply_partials(self, state, merged):
        return state, True  # one update, then done

    def fit_from_partials(self, merged, state=None) -> LinearRegressionModel:
        coef, intercept = _wls_fit_from_stats(
            jnp.asarray(merged.stats["sw"]),
            jnp.asarray(merged.stats["sx"]),
            jnp.asarray(merged.stats["sxx"]),
            jnp.asarray(merged.stats["gram"]),
            jnp.asarray(merged.stats["mom"]),
            jnp.float32(self.reg_param), self.fit_intercept, self.standardize,
        )
        return LinearRegressionModel(coefficients=coef, intercept=intercept)

    def _fit_outofcore(self, hd, mesh=None) -> LinearRegressionModel:
        """Rows ≫ HBM: accumulate the WLS/elastic-net sufficient statistics
        (weighted moments + Gram) over streamed ``max_device_rows`` blocks
        — one pass regardless of n — then solve on the tiny (d, d) system.
        The training ``summary`` is unavailable on this path (it would pin
        the full dataset on device, defeating the point); Spark's
        disk-backed equivalent is every ``.fit`` at reference
        ``mllearnforhospitalnetwork.py:146-148``."""
        from ..parallel.mesh import default_mesh
        from ..parallel.outofcore import HostDataset

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError("LinearRegression needs labels: HostDataset(y=...)")
        if hd.n == 0:
            raise ValueError("LinearRegression fit on an empty dataset")
        # Recentering shift from a bounded host sample (f32 Gram stability);
        # must be exactly 0 when there is no intercept to absorb it.
        sample = hd.sample_rows(65536, seed=0) if self.fit_intercept else None
        if sample is not None and sample.shape[0] > 0:
            shift = jnp.asarray(sample.mean(axis=0), jnp.float32)
        else:  # no intercept, or all weights zero (resident path returns
            # finite zero coefficients there; shift=0 preserves that)
            shift = jnp.zeros((hd.n_features,), jnp.float32)
        tot = None
        for blk in hd.blocks(mesh):
            s = _lr_block_stats(blk.x, blk.y, blk.w, shift)
            tot = s if tot is None else _lr_add_stats(tot, s)
        elastic = self.elastic_net_param > 0.0 and self.reg_param > 0.0
        coef, intercept = _lr_solve_from_stats(
            tot, shift,
            jnp.float32(self.reg_param), jnp.float32(self.elastic_net_param),
            jnp.float32(self.tol), self.fit_intercept, self.standardize,
            elastic, self.max_iter,
        )
        return LinearRegressionModel(coefficients=coef, intercept=intercept)
