"""Frequent pattern mining — FPGrowth (``pyspark.ml.fpm``).

Han's FP-growth over an FP-tree, the algorithm Spark parallelizes as
PFP (per-suffix conditional trees on executors).  Pattern mining is
symbolic, branchy, and dictionary-heavy — exactly what an accelerator
is worst at — so this runs on HOST (the honest placement; the arrays
the MINED RULES are applied to can be device-resident downstream).
Surface parity: ``freq_itemsets``, single-consequent
``association_rules`` with confidence/lift/support (Spark's columns),
and ``transform`` (union of rule consequents whose antecedents are
contained in the row, minus items already present).
"""

from __future__ import annotations

from collections import defaultdict
from functools import cached_property
from dataclasses import dataclass

import numpy as np

from ..io.model_io import register_model


class _Node:
    __slots__ = ("item", "count", "parent", "children")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children: dict = {}


def _build_tree(rows, min_count, order=None):
    """→ (root, header links item → [nodes]), items below min_count
    dropped, rows sorted by global frequency order."""
    if order is None:
        counts = defaultdict(int)
        for row, mult in rows_with_mult(rows):
            for it in set(row):
                counts[it] += mult
        order = {
            it: i
            for i, (it, c) in enumerate(
                sorted(counts.items(), key=lambda kv: (-kv[1], str(kv[0])))
            )
            if c >= min_count
        }
    root = _Node(None, None)
    header = defaultdict(list)
    for row, mult in rows_with_mult(rows):
        items = sorted(
            {it for it in row if it in order}, key=lambda it: order[it]
        )
        node = root
        for it in items:
            child = node.children.get(it)
            if child is None:
                child = _Node(it, node)
                node.children[it] = child
                header[it].append(child)
            child.count += mult
            node = child
    return root, header, order


def rows_with_mult(rows):
    for r in rows:
        if isinstance(r, tuple) and len(r) == 2 and isinstance(r[1], int):
            yield r[0], r[1]
        else:
            yield r, 1


def _mine(header, order, min_count, suffix, out):
    """Classic conditional-tree recursion (items in REVERSE frequency
    order so every suffix's conditional base is complete)."""
    for it in sorted(header, key=lambda i: -order[i]):
        nodes = header[it]
        support = sum(n.count for n in nodes)
        if support < min_count:
            continue
        itemset = (it,) + suffix
        out[frozenset(itemset)] = support
        # conditional pattern base: prefix paths with this item's counts
        cond_rows = []
        for n in nodes:
            path = []
            p = n.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            if path:
                cond_rows.append((path, n.count))
        if cond_rows:
            _, sub_header, sub_order = _build_tree(cond_rows, min_count)
            if sub_header:
                _mine(sub_header, sub_order, min_count, itemset, out)


@register_model("FPGrowthModel")
@dataclass
class FPGrowthModel:
    freq_itemsets: list               # [(items tuple, count), ...]
    n_rows: int
    min_confidence: float = 0.8

    @cached_property
    def association_rules(self):
        """[(antecedent, consequent item, confidence, lift, support), ...]
        — Spark's single-consequent rules, filtered by minConfidence."""
        support = {frozenset(items): c for items, c in self.freq_itemsets}
        rules = []
        for items, c in self.freq_itemsets:
            if len(items) < 2:
                continue
            fs = frozenset(items)
            for cons in items:
                ant = fs - {cons}
                ant_c = support.get(ant)
                if not ant_c:
                    continue
                conf = c / ant_c
                if conf < self.min_confidence:
                    continue
                cons_c = support.get(frozenset((cons,)), 0)
                lift = (
                    conf / (cons_c / self.n_rows) if cons_c else float("nan")
                )
                rules.append(
                    (tuple(sorted(ant, key=str)), cons, conf, lift, c / self.n_rows)
                )
        rules.sort(key=lambda r: (-r[2], str(r[0])))
        return rules

    def transform(self, itemsets) -> list:
        """Per row: sorted union of rule consequents whose antecedent is
        contained in the row and whose consequent is absent (Spark's
        ``prediction`` column)."""
        rules = self.association_rules
        out = []
        for row in itemsets:
            have = set(row)
            pred = {
                cons
                for ant, cons, _, _, _ in rules
                if set(ant) <= have and cons not in have
            }
            out.append(sorted(pred, key=str))
        return out

    def _artifacts(self):
        return (
            "FPGrowthModel",
            {
                "n_rows": int(self.n_rows),
                "min_confidence": float(self.min_confidence),
                # items persist VERBATIM (ints/strings are both JSON-safe;
                # stringifying would break set-containment after reload)
                "freq_itemsets": [
                    [list(items), int(c)] for items, c in self.freq_itemsets
                ],
            },
            {},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            freq_itemsets=[
                (tuple(items), int(c)) for items, c in params["freq_itemsets"]
            ],
            n_rows=int(params["n_rows"]),
            min_confidence=float(params.get("min_confidence", 0.8)),
        )


@dataclass(frozen=True)
class FPGrowth:
    """Spark defaults: minSupport 0.3, minConfidence 0.8."""

    min_support: float = 0.3
    min_confidence: float = 0.8

    def fit(self, itemsets) -> FPGrowthModel:
        """``itemsets``: iterable of per-row item collections (duplicates
        within a row collapse, Spark's set semantics)."""
        rows = [list(r) for r in itemsets]
        if not rows:
            raise ValueError("FPGrowth fit on an empty transaction set")
        if not 0.0 < self.min_support <= 1.0:
            raise ValueError(
                f"min_support must be in (0, 1], got {self.min_support}"
            )
        min_count = max(int(np.ceil(self.min_support * len(rows))), 1)
        _, header, order = _build_tree(rows, min_count)
        mined: dict = {}
        _mine(header, order, min_count, (), mined)
        freq = [
            (tuple(sorted(items, key=str)), c) for items, c in mined.items()
        ]
        freq.sort(key=lambda kv: (-kv[1], kv[0]))
        return FPGrowthModel(
            freq_itemsets=freq,
            n_rows=len(rows),
            min_confidence=self.min_confidence,
        )


# ------------------------------------------------------------- PrefixSpan
def _seq_contains(seq: list, pattern: list) -> bool:
    """Greedy earliest-embedding subsequence test: pattern elements map to
    strictly increasing sequence elements with itemset containment (the
    PrefixSpan pattern-occurrence rule; greedy matching is complete for
    existence)."""
    i = 0
    for elem in seq:
        if i < len(pattern) and pattern[i] <= elem:
            i += 1
    return i == len(pattern)


@dataclass(frozen=True)
class PrefixSpan:
    """Sequential pattern mining (``pyspark.ml.fpm.PrefixSpan``).

    Spark defaults: minSupport 0.1, maxPatternLength 10.  Sequences are
    lists of itemsets; a pattern occurs in a sequence when its elements
    map to strictly increasing sequence positions with itemset
    containment.  Host-side DFS with support-based pruning (symbolic
    search — the same placement argument as FP-growth); candidate
    extensions are drawn only from sequences still supporting the
    current prefix, and both s-extensions (new element) and i-extensions
    (grow the last element) are explored, so the enumeration is exactly
    the PrefixSpan pattern space."""

    min_support: float = 0.1
    max_pattern_length: int = 10

    def find_frequent_sequential_patterns(self, sequences) -> list:
        """→ [(pattern as tuple of sorted item tuples, count), ...] sorted
        by descending count (Spark's freq column)."""
        all_seqs = [
            [frozenset(elem) for elem in seq if len(elem) > 0]
            for seq in sequences
        ]
        n_total = len(all_seqs)          # Spark's support denominator
        db = [s for s in all_seqs if s]  # empty sequences support nothing
        if n_total == 0:
            raise ValueError("PrefixSpan on an empty sequence database")
        if not 0.0 < self.min_support <= 1.0:
            raise ValueError(
                f"min_support must be in (0, 1], got {self.min_support}"
            )
        if self.max_pattern_length < 1:
            raise ValueError(
                f"max_pattern_length must be >= 1, got {self.max_pattern_length}"
            )
        # minCount over ALL input sequences (Spark counts empties in the
        # denominator even though they can never support a pattern)
        min_count = max(int(np.ceil(self.min_support * n_total)), 1)
        if not db:
            return []
        out: list = []

        def extensions(support_ids, pattern):
            """Candidate (kind, item) extensions from supporting seqs."""
            s_items: set = set()
            i_items: set = set()
            last = pattern[-1] if pattern else None
            for sid in support_ids:
                for elem in db[sid]:
                    s_items |= elem
                    if last is not None:
                        # i-extension candidates: items co-occurring with
                        # the full last element, ordered after its max
                        if last <= elem:
                            i_items |= {
                                it for it in elem
                                if it not in last
                                and str(it) > max(map(str, last))
                            }
            return s_items, i_items

        def dfs(pattern, support_ids):
            length = sum(len(e) for e in pattern)
            if length >= self.max_pattern_length:
                return
            s_items, i_items = extensions(support_ids, pattern)
            for kind, items in (("s", s_items), ("i", i_items)):
                for it in sorted(items, key=str):
                    if kind == "s":
                        cand = pattern + [frozenset((it,))]
                    else:
                        cand = pattern[:-1] + [pattern[-1] | {it}]
                    sup = [
                        sid for sid in support_ids
                        if _seq_contains(db[sid], cand)
                    ]
                    if len(sup) >= min_count:
                        out.append(
                            (
                                tuple(
                                    tuple(sorted(e, key=str)) for e in cand
                                ),
                                len(sup),
                            )
                        )
                        dfs(cand, sup)

        dfs([], list(range(len(db))))
        # str-keyed ordering like every other sort here (mixed-type items
        # would TypeError under raw tuple comparison)
        out.sort(key=lambda kv: (-kv[1], str(kv[0])))
        return out
