"""GeneralizedLinearRegression — sharded IRLS over exponential families.

Parity with ``pyspark.ml.regression.GeneralizedLinearRegression``
(families gaussian/binomial/poisson/gamma with their canonical and the
common alternative links; L2 ``reg_param`` on standardized coefficients
with the intercept unpenalized — the same Spark convention as
LinearRegression/LogisticRegression here).

MLlib trains GLR with IRLS over ``treeAggregate``'d (XᵀWX, XᵀWz)
statistics.  The TPU-native form keeps that exact algorithm and inverts
the communication into XLA: each IRLS iteration is one jit'd pass over
the row-sharded dataset — the working-response moment matrices are two
MXU matmuls whose cross-shard sums lower to ``psum`` — followed by a tiny
on-device solve; the whole fit is a single ``lax.while_loop`` device
computation (one host sync per fit, like the KMeans/GMM loops).

Per-family pieces (μ = g⁻¹(η)):

    family    V(μ)      canonical link g
    gaussian  1         identity
    binomial  μ(1−μ)    logit
    poisson   μ         log
    gamma     μ²        inverse

Working response z = η + (y−μ)·g'(μ); IRLS weight ω = w / (g'(μ)²·V(μ)).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features
from .linear_regression import standardized_design

_FAMILY_LINKS = {
    "gaussian": ("identity", ("identity", "log")),
    "binomial": ("logit", ("logit",)),
    "poisson": ("log", ("log", "identity", "sqrt")),
    "gamma": ("inverse", ("inverse", "log", "identity")),
    # tweedie uses POWER links (g(μ) = μ^linkPower, log when 0), selected
    # via link_power — Spark's family="tweedie" surface
    "tweedie": ("power", ("power",)),
}


def _link_fns(link: str, link_power: float = 0.0):
    """(g(μ), g⁻¹(η), g'(μ)) — all traceable.  ``link="power"`` is the
    tweedie family's μ^link_power (log when link_power == 0)."""
    if link == "power":
        lp = float(link_power)
        if lp == 0.0:
            return _link_fns("log")
        if lp == 1.0:
            return _link_fns("identity")
        if lp == -1.0:
            return _link_fns("inverse")
        return (
            lambda mu: mu ** lp,
            # η < 0 is outside the power link's domain for fractional
            # exponents; surface it as NaN so IRLS divergence is visible
            # (the named links do the same via log/inverse blowing up)
            # instead of clamping to an extreme μ.  η = 0 stays in-domain:
            # μ = 0^(1/lp) (0 for lp > 0, inf for lp < 0 — Spark's
            # math.pow semantics).
            lambda eta: jnp.where(eta >= 0, eta, jnp.nan) ** (1.0 / lp),
            lambda mu: lp * mu ** (lp - 1.0),
        )
    if link == "identity":
        return (lambda mu: mu, lambda eta: eta, lambda mu: jnp.ones_like(mu))
    if link == "log":
        return (jnp.log, jnp.exp, lambda mu: 1.0 / mu)
    if link == "logit":
        return (
            lambda mu: jnp.log(mu / (1.0 - mu)),
            jax.nn.sigmoid,
            lambda mu: 1.0 / (mu * (1.0 - mu)),
        )
    if link == "inverse":
        return (
            lambda mu: 1.0 / mu,
            lambda eta: 1.0 / eta,
            lambda mu: -1.0 / (mu * mu),
        )
    if link == "sqrt":
        return (jnp.sqrt, lambda eta: eta * eta, lambda mu: 0.5 / jnp.sqrt(mu))
    raise ValueError(f"unknown link {link!r}")


def _variance_fn(family: str, var_power: float = 0.0):
    if family == "tweedie":
        vp = float(var_power)
        return lambda mu: mu ** vp
    return {
        "gaussian": lambda mu: jnp.ones_like(mu),
        "binomial": lambda mu: mu * (1.0 - mu),
        "poisson": lambda mu: mu,
        "gamma": lambda mu: mu * mu,
    }[family]


def _mu_clip(family: str, mu, var_power: float = 0.0):
    """Keep μ inside the family's domain so V(μ) and g'(μ) stay finite.
    tweedie with variance_power 0 IS gaussian (μ unrestricted — clamping
    would silently corrupt fits on negative-mean data)."""
    if family == "binomial":
        return jnp.clip(mu, 1e-6, 1.0 - 1e-6)
    if family in ("poisson", "gamma") or (
        family == "tweedie" and float(var_power) != 0.0
    ):
        return jnp.maximum(mu, 1e-8)
    return mu


@partial(
    jax.jit,
    static_argnames=(
        "family", "link", "fit_intercept", "standardize", "max_iter",
        "var_power", "link_power",
    ),
)
def _irls_glm(
    x, y, w, offset, reg_param, tol,
    family: str, link: str, fit_intercept: bool, standardize: bool, max_iter: int,
    var_power: float = 0.0, link_power: float = 0.0,
):
    """``offset`` (n,) is Spark's offsetCol: a fixed additive term of the
    linear predictor η = Xβ [+ b] + offset (e.g. log-exposure for poisson
    rate models) — excluded from the solve's working response."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    offset = offset.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(
        x, w, reg_param, fit_intercept, standardize
    )
    d = xa.shape[1]
    g, ginv, gprime = _link_fns(link, link_power)
    vfn = _variance_fn(family, var_power)

    # μ init (Spark/statsmodels convention): nudge y into the domain —
    # the SAME helper the out-of-core first pass uses, so the two paths
    # start from an identical η₀ by construction
    n = jnp.maximum(jnp.sum(w), 1.0)
    ybar = jnp.sum(y * w) / n
    eta0 = _glm_mu0_eta(y, ybar, family, link, var_power, link_power)

    def irls_step(theta, eta):
        mu = _mu_clip(family, ginv(eta), var_power)
        gp = gprime(mu)
        z = eta + (y - mu) * gp
        om = w / jnp.maximum(gp * gp * vfn(mu), 1e-12)
        gram = (xa * om[:, None]).T @ xa + jnp.diag(ridge)
        # the offset is a FIXED part of η: subtract it from the working
        # response so the solve fits only Xβ (McCullagh & Nelder §4.4)
        mom = (xa * om[:, None]).T @ (z - offset)
        jitter = 1e-7 * jnp.trace(gram) / d + 1e-9
        theta_new = jnp.linalg.solve(gram + jitter * jnp.eye(d, dtype=x.dtype), mom)
        return theta_new, xa @ theta_new + offset

    def cond(carry):
        it, theta, _, delta = carry
        return (it < max_iter) & (delta > tol)

    def body(carry):
        it, theta, eta, _ = carry
        theta_new, eta_new = irls_step(theta, eta)
        delta = jnp.max(jnp.abs(theta_new - theta)) / jnp.maximum(
            jnp.max(jnp.abs(theta_new)), 1.0
        )
        return it + 1, theta_new, eta_new, delta

    theta0 = jnp.zeros((d,), x.dtype)
    it, theta, eta, _ = lax.while_loop(
        cond, body, (jnp.int32(0), theta0, eta0, jnp.float32(jnp.inf))
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)

    # deviance of the final fit (family-specific; Spark summary surface)
    mu = _mu_clip(family, ginv(xa @ theta + offset), var_power)
    deviance = jnp.sum(_unit_deviance(family, y, mu, var_power) * w)
    return coef, intercept, it, deviance


def _glm_mu0_eta(y, ybar, family: str, link: str, var_power: float, link_power: float):
    """Spark/statsmodels μ-init → η₀, per row (shared by the resident
    ``_irls_glm`` init and the out-of-core first pass)."""
    g, _, _ = _link_fns(link, link_power)
    if family == "binomial":
        mu0 = jnp.clip((y + 0.5) / 2.0, 1e-3, 1.0 - 1e-3)
    elif family in ("poisson", "gamma") or (
        family == "tweedie" and var_power != 0.0
    ):
        mu0 = jnp.maximum(y, 0.0) + 0.1 * jnp.maximum(ybar, 0.1)
    else:
        mu0 = y
    return g(_mu_clip(family, mu0, var_power))


@partial(
    jax.jit,
    static_argnames=(
        "family", "link", "fit_intercept", "first", "var_power", "link_power",
    ),
)
def _glm_block_irls_stats(
    x, y, w, theta, ybar,
    family: str, link: str, fit_intercept: bool, first: bool,
    var_power: float, link_power: float,
):
    """One block's (gram, moment) IRLS contribution at the current θ.

    ``first=True`` derives η from the family's μ-init (a pure function of
    y and ȳ — exactly what the resident loop starts from); afterwards
    η = X_aθ, which is also what the resident loop carries between
    iterations."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa = (
        jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        if fit_intercept
        else x
    )
    _, ginv, gprime = _link_fns(link, link_power)
    vfn = _variance_fn(family, var_power)
    if first:
        eta = _glm_mu0_eta(y, ybar, family, link, var_power, link_power)
    else:
        eta = xa @ theta
    mu = _mu_clip(family, ginv(eta), var_power)
    gp = gprime(mu)
    z = eta + (y - mu) * gp
    om = w / jnp.maximum(gp * gp * vfn(mu), 1e-12)
    return (xa * om[:, None]).T @ xa, (xa * om[:, None]).T @ z


@partial(
    jax.jit,
    static_argnames=("family", "link", "fit_intercept", "var_power", "link_power"),
)
def _glm_block_deviance(
    x, y, w, theta,
    family: str, link: str, fit_intercept: bool,
    var_power: float, link_power: float,
):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    xa = (
        jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        if fit_intercept
        else x
    )
    _, ginv, _ = _link_fns(link, link_power)
    mu = _mu_clip(family, ginv(xa @ theta), var_power)
    return jnp.sum(_unit_deviance(family, y, mu, var_power) * w)


@jax.jit
def _glm_update_from_stats(theta, gram, mom, ridge):
    """The resident loop's damped solve on ACCUMULATED statistics."""
    d = gram.shape[0]
    g = gram + jnp.diag(ridge)
    jitter = 1e-7 * jnp.trace(g) / d + 1e-9
    theta_new = jnp.linalg.solve(g + jitter * jnp.eye(d, dtype=gram.dtype), mom)
    delta = jnp.max(jnp.abs(theta_new - theta)) / jnp.maximum(
        jnp.max(jnp.abs(theta_new)), 1.0
    )
    return theta_new, delta


def _unit_deviance(family: str, y, mu, var_power: float = 0.0):
    """Per-row deviance contribution d(y, μ) (McCullagh & Nelder) — shared
    by the fit's final deviance, the summary's nullDeviance (μ = intercept-
    only mean), and ``residuals("deviance")``."""
    if family == "gaussian":
        return (y - mu) ** 2
    if family == "binomial":
        return 2.0 * (
            y * jnp.log(jnp.maximum(y, 1e-12) / mu)
            + (1.0 - y) * jnp.log(jnp.maximum(1.0 - y, 1e-12) / (1.0 - mu))
        )
    if family == "poisson":
        ylog = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        return 2.0 * (ylog - (y - mu))
    if family == "tweedie":
        p = float(var_power)
        if p == 0.0:
            return _unit_deviance("gaussian", y, mu)
        if p == 1.0:
            return _unit_deviance("poisson", y, mu)
        if p == 2.0:
            return _unit_deviance("gamma", y, mu)
        # the general compound-Poisson form; y = 0 is in-domain for
        # 1 < p < 2 (the y^(2-p) term vanishes there)
        yp = jnp.maximum(y, 0.0)
        return 2.0 * (
            jnp.where(yp > 0, yp ** (2.0 - p), 0.0) / ((1.0 - p) * (2.0 - p))
            - y * mu ** (1.0 - p) / (1.0 - p)
            + mu ** (2.0 - p) / (2.0 - p)
        )
    # gamma
    return 2.0 * (-jnp.log(jnp.maximum(y, 1e-12) / mu) + (y - mu) / mu)


@dataclass
class GeneralizedLinearRegressionTrainingSummary:
    """``pyspark.ml.regression.GeneralizedLinearRegressionTrainingSummary``
    surface: deviance / nullDeviance / dispersion / AIC / Pearson χ² and
    per-coefficient inference (std errors, t, p) — the evaluation surface
    the reference consumes for its regressors at
    ``mllearnforhospitalnetwork.py:162-169``, extended to GLM families.

    Lazy like the LR summary (models/summary.py): fit stores only (model,
    dataset) references; each statistic is one device reduction on first
    access, cached.  Inference statistics follow Spark's rules: available
    only on unregularized fits; p-values use the normal distribution when
    the dispersion is fixed (binomial/poisson) and Student's t otherwise.
    """

    _model: "GeneralizedLinearRegressionModel" = field(repr=False)
    _ds: object = field(repr=False)
    _reg_param: float = 0.0
    _fit_intercept: bool = True
    _offset: object | None = field(default=None, repr=False)  # (n_pad,) or None

    # -- shared one-pass statistics ------------------------------------
    @cached_property
    def _stats(self) -> dict[str, float]:
        """ONE jitted pass over the mesh → every scalar the summary needs."""
        m = self._model
        fam = m.family
        _, ginv, _ = _link_fns(m.link, m.link_power)
        vfn = _variance_fn(fam, m.variance_power)

        g_link, _, _ = _link_fns(m.link, m.link_power)
        has_offset = self._offset is not None
        fit_intercept = self._fit_intercept
        vfn_ = vfn
        vp_s = m.variance_power

        @jax.jit
        def stats(x, y, w, off):
            x = x.astype(jnp.float32)
            y = y.astype(jnp.float32)
            w = w.astype(jnp.float32)
            off = off.astype(jnp.float32)
            eta = (
                x @ jnp.asarray(m.coefficients, jnp.float32)
                + jnp.float32(m.intercept)
                + off
            )
            mu = _mu_clip(fam, ginv(eta), vp_s)
            wsum = jnp.sum(w)
            nrows = jnp.sum((w > 0).astype(jnp.float32))
            ybar = jnp.sum(y * w) / jnp.maximum(wsum, 1e-12)
            if not has_offset:
                # intercept-only MLE is the weighted mean for EVERY link
                # (the one-parameter score Σ wᵢ(yᵢ−μ)/(V(μ)g'(μ))
                # vanishes at ȳ) — one closed form, no iteration
                mu0 = (
                    _mu_clip(fam, ybar * jnp.ones_like(y), vp_s)
                    if fit_intercept
                    else _mu_clip(fam, ginv(jnp.zeros_like(y)), vp_s)
                )
            elif not fit_intercept:
                mu0 = _mu_clip(fam, ginv(off), vp_s)
            else:
                # offset null model: η₀ = b₀ + offset has no closed form —
                # a few scalar-IRLS sweeps converge b₀ (Spark refits the
                # intercept-only model with the offset the same way)
                _, ginv_, gprime_ = _link_fns(m.link, m.link_power)

                def b0_step(_, b0):
                    mu_ = _mu_clip(fam, ginv_(b0 + off), vp_s)
                    gp_ = gprime_(mu_)
                    om_ = w / jnp.maximum(gp_ * gp_ * vfn_(mu_), 1e-12)
                    z_ = b0 + (y - mu_) * gp_   # working response − offset
                    return jnp.sum(om_ * z_) / jnp.maximum(jnp.sum(om_), 1e-12)

                b0 = jax.lax.fori_loop(
                    0, 25, b0_step,
                    g_link(_mu_clip(fam, jnp.maximum(ybar, 1e-8) * jnp.ones(()), vp_s)),
                )
                mu0 = _mu_clip(fam, ginv(b0 + off), vp_s)
            vp = m.variance_power
            dev = jnp.sum(_unit_deviance(fam, y, mu, vp) * w)
            dev0 = jnp.sum(_unit_deviance(fam, y, mu0, vp) * w)
            pearson = jnp.sum(w * (y - mu) ** 2 / jnp.maximum(vfn(mu), 1e-12))
            # family log-likelihood pieces (dispersion-free parts; the
            # gaussian/gamma AIC closes over deviance/dispersion on host)
            if fam == "binomial":
                ll = jnp.sum(
                    w * (y * jnp.log(mu) + (1.0 - y) * jnp.log1p(-mu))
                )
            elif fam == "poisson":
                ll = jnp.sum(
                    w * (y * jnp.log(jnp.maximum(mu, 1e-12)) - mu
                         - jax.lax.lgamma(y + 1.0))
                )
            else:
                ll = jnp.zeros(())
            # Σ w log y, Σ w log μ and Σ w·y/μ feed the gamma AIC's
            # host-side finish (same single pass)
            logy = jnp.sum(jnp.where(w > 0, jnp.log(jnp.maximum(y, 1e-12)), 0.0) * w)
            logmu = jnp.sum(jnp.where(w > 0, jnp.log(jnp.maximum(mu, 1e-12)), 0.0) * w)
            y_over_mu = jnp.sum(w * y / jnp.maximum(mu, 1e-12))
            return dict(
                deviance=dev, null_deviance=dev0, pearson=pearson, ll=ll,
                wsum=wsum, nrows=nrows, logy=logy, logmu=logmu,
                y_over_mu=y_over_mu,
            )

        off = (
            self._offset
            if self._offset is not None
            else jnp.zeros_like(self._ds.y)
        )
        return {
            k: float(v)
            for k, v in jax.device_get(
                stats(self._ds.x, self._ds.y, self._ds.w, off)
            ).items()
        }

    @property
    def deviance(self) -> float:
        return self._stats["deviance"]

    @property
    def null_deviance(self) -> float:
        return self._stats["null_deviance"]

    @property
    def pearson_chi_squared(self) -> float:
        """Σ w·(y−μ)²/V(μ) — the Pearson goodness-of-fit statistic."""
        return self._stats["pearson"]

    @cached_property
    def num_instances(self) -> int:
        return int(self._stats["nrows"])

    @property
    def rank(self) -> int:
        """Rank of the fitted design (full-rank solve: p [+ intercept])."""
        return np.asarray(self._model.coefficients).shape[0] + (
            1 if self._fit_intercept else 0
        )

    @property
    def degrees_of_freedom(self) -> int:
        return max(self.num_instances - self.rank, 0)

    # Spark's names for (n − rank) and (n − 1 + has_intercept − 1):
    @property
    def residual_degree_of_freedom(self) -> int:
        return self.degrees_of_freedom

    @property
    def residual_degree_of_freedom_null(self) -> int:
        return max(self.num_instances - (1 if self._fit_intercept else 0), 0)

    @cached_property
    def dispersion(self) -> float:
        """1.0 for binomial/poisson (fixed); Pearson χ²/dof otherwise —
        Spark's (and McCullagh & Nelder's) moment estimator."""
        if self._model.family in ("binomial", "poisson"):
            return 1.0
        return self.pearson_chi_squared / max(self.degrees_of_freedom, 1)

    @cached_property
    def aic(self) -> float:
        """Akaike information criterion, Spark's per-family form:
        ``family.aic + 2·rank`` with the dispersion parameter's +2 charged
        inside the gaussian/gamma family terms."""
        from scipy.special import gammaln

        s = self._stats
        fam = self._model.family
        if fam == "tweedie":
            # Spark's TweedieFamily likewise has no closed-form AIC
            raise RuntimeError(
                "AIC is not defined for the tweedie family (no closed-form "
                "likelihood); Spark's GeneralizedLinearRegression raises "
                "here too"
            )
        if fam == "gaussian":
            # −2ℓ at the MLE σ̂² = deviance/Σw, + 2 for estimating σ²
            fam_aic = (
                s["wsum"] * (np.log(2.0 * np.pi * s["deviance"] / s["wsum"]) + 1.0)
                + 2.0
            )
        elif fam in ("binomial", "poisson"):
            fam_aic = -2.0 * s["ll"]
        else:  # gamma: −2ℓ at shape a = 1/dispersion, scale = μ·dispersion
            a = 1.0 / self.dispersion
            # log f(y; a, θ=μ/a) = (a−1)log y − a·y/μ − a·log μ + a·log a − lnΓ(a)
            ll = (
                (a - 1.0) * s["logy"]
                - a * s["y_over_mu"]
                - a * s["logmu"]
                + s["wsum"] * (a * np.log(a) - gammaln(a))
            )
            fam_aic = -2.0 * ll + 2.0
        return float(fam_aic + 2.0 * self.rank)

    # -- residuals ------------------------------------------------------
    def residuals(self, residuals_type: str = "deviance") -> np.ndarray:
        """Per-row residuals (valid rows only) — Spark's
        ``residuals(residualsType)``: deviance | pearson | working |
        response.  Weighted rows scale the deviance/pearson forms by √w."""
        m = self._model
        _, ginv, gprime = _link_fns(m.link, m.link_power)
        vfn = _variance_fn(m.family, m.variance_power)
        x = self._ds.x
        y = np.asarray(jax.device_get(self._ds.y), np.float64)
        w = np.asarray(jax.device_get(self._ds.w), np.float64)
        mu = np.asarray(
            jax.device_get(m.predict(x, offset=self._offset)), np.float64
        )
        valid = w > 0
        y, w, mu = y[valid], w[valid], mu[valid]
        if residuals_type == "response":
            return y - mu
        if residuals_type == "working":
            return (y - mu) * np.asarray(gprime(jnp.asarray(mu)))
        if residuals_type == "pearson":
            v = np.maximum(np.asarray(vfn(jnp.asarray(mu))), 1e-12)
            return (y - mu) / np.sqrt(v) * np.sqrt(w)
        if residuals_type == "deviance":
            d = np.asarray(
                _unit_deviance(
                    m.family, jnp.asarray(y), jnp.asarray(mu), m.variance_power
                )
            )
            return np.sign(y - mu) * np.sqrt(np.maximum(d, 0.0) * w)
        raise ValueError(
            "residuals_type must be deviance|pearson|working|response, got "
            f"{residuals_type!r}"
        )

    # -- coefficient inference -----------------------------------------
    def _require_unregularized(self) -> None:
        if self._reg_param != 0.0:
            raise RuntimeError(
                "coefficient standard errors / t / p values are only "
                "available for an unregularized fit (reg_param=0), "
                "matching Spark's IRLS-solver restriction"
            )

    @cached_property
    def coefficient_standard_errors(self) -> np.ndarray:
        """√(diag((XᵀΩX)⁻¹)·dispersion) with Ω the IRLS weights at the
        fitted coefficients — ordering (coefficients..., intercept), like
        Spark.  Raises on a (near-)singular weighted Gram."""
        self._require_unregularized()
        m = self._model
        _, ginv, gprime = _link_fns(m.link, m.link_power)
        vfn = _variance_fn(m.family, m.variance_power)
        fit_intercept = self._fit_intercept

        @jax.jit
        def gram(x, w, off):
            x = x.astype(jnp.float32)
            eta = (
                x @ jnp.asarray(m.coefficients, jnp.float32)
                + jnp.float32(m.intercept)
                + off.astype(jnp.float32)
            )
            mu = _mu_clip(m.family, ginv(eta), m.variance_power)
            gp = gprime(mu)
            om = w / jnp.maximum(gp * gp * vfn(mu), 1e-12)
            xa = (
                jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
                if fit_intercept
                else x
            )
            return (xa * om[:, None]).T @ xa

        off = (
            self._offset
            if self._offset is not None
            else jnp.zeros_like(self._ds.w)
        )
        g = np.asarray(
            jax.device_get(gram(self._ds.x, self._ds.w, off)), np.float64
        )
        cond = np.linalg.cond(g)
        if not np.isfinite(cond) or cond > 1e7:
            raise RuntimeError(
                "weighted design matrix is (near-)collinear (Gram condition "
                f"number {cond:.2e}); standard errors are undefined"
            )
        return np.sqrt(np.maximum(np.diag(np.linalg.inv(g)) * self.dispersion, 0.0))

    @cached_property
    def t_values(self) -> np.ndarray:
        self._require_unregularized()
        beta = np.asarray(self._model.coefficients, np.float64)
        if self._fit_intercept:
            beta = np.r_[beta, float(self._model.intercept)]
        return beta / self.coefficient_standard_errors

    @cached_property
    def p_values(self) -> np.ndarray:
        """Two-sided; normal when dispersion is fixed (binomial/poisson),
        Student's t with residual dof otherwise — Spark's rule."""
        self._require_unregularized()
        from scipy import stats

        t = np.abs(self.t_values)
        if self._model.family in ("binomial", "poisson"):
            return 2.0 * stats.norm.sf(t)
        return 2.0 * stats.t.sf(t, max(self.degrees_of_freedom, 1))


@register_model("GeneralizedLinearRegressionModel")
@dataclass
class GeneralizedLinearRegressionModel(Model):
    coefficients: np.ndarray
    intercept: float
    family: str
    link: str
    n_iter: int = 0
    deviance: float = 0.0
    # tweedie family parameters (Spark's variancePower/linkPower); inert
    # (0.0) for the named-link families
    variance_power: float = 0.0
    link_power: float = 0.0
    _summary: object | None = field(default=None, repr=False, compare=False)

    @property
    def has_summary(self) -> bool:
        return self._summary is not None

    def release_summary(self) -> None:
        """Drop the summary's training-dataset reference (unpins device
        memory — see models/summary.py memory note)."""
        self._summary = None

    @property
    def summary(self) -> GeneralizedLinearRegressionTrainingSummary:
        """Training summary (deviance/AIC/dispersion/inference) — fresh
        fits only, like Spark's ``hasSummary``."""
        if self._summary is None:
            from .summary import summary_unavailable

            raise summary_unavailable("GeneralizedLinearRegressionModel")
        return self._summary

    def predict(self, x: jax.Array, offset: jax.Array | None = None) -> jax.Array:
        """Mean prediction μ = g⁻¹(xβ + b [+ offset]) (Spark's prediction
        column; pass the serving rows' offset when the model was fitted
        with ``offset_col``)."""
        _, ginv, _ = _link_fns(self.link, self.link_power)
        return ginv(self.predict_link(x, offset))

    def predict_link(self, x: jax.Array, offset: jax.Array | None = None) -> jax.Array:
        """Linear predictor η (Spark's linkPrediction column)."""
        check_features(x, np.asarray(self.coefficients).shape[0], type(self).__name__)
        eta = x.astype(jnp.float32) @ jnp.asarray(
            self.coefficients, jnp.float32
        ) + jnp.float32(self.intercept)
        if offset is not None:
            eta = eta + jnp.asarray(offset, jnp.float32)
        return eta

    def _artifacts(self):
        return (
            "GeneralizedLinearRegressionModel",
            {
                "family": self.family,
                "link": self.link,
                "intercept": float(self.intercept),
                "n_iter": int(self.n_iter),
                "deviance": float(self.deviance),
                "variance_power": float(self.variance_power),
                "link_power": float(self.link_power),
            },
            {"coefficients": np.asarray(self.coefficients)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=arrays["coefficients"],
            intercept=float(params["intercept"]),
            family=params["family"],
            link=params["link"],
            n_iter=int(params.get("n_iter", 0)),
            deviance=float(params.get("deviance", 0.0)),
            variance_power=float(params.get("variance_power", 0.0)),
            link_power=float(params.get("link_power", 0.0)),
        )


@dataclass(frozen=True)
class GeneralizedLinearRegression(Estimator):
    family: str = "gaussian"          # Spark default
    link: str | None = None           # None = family's canonical link
    reg_param: float = 0.0
    max_iter: int = 25                # Spark default
    tol: float = 1e-6                 # Spark default
    fit_intercept: bool = True
    standardize: bool = True
    label_col: str = "length_of_stay"
    features_col: str = "features"
    weight_col: str | None = None
    # tweedie family (Spark's variancePower/linkPower): V(μ) = μ^p with
    # p ∈ {0} ∪ [1, ∞); link g(μ) = μ^linkPower (log when 0), defaulting
    # to 1 − p.  Both ignored for the named-link families.
    variance_power: float = 0.0
    link_power: float | None = None
    # Spark's offsetCol: a table column added VERBATIM to the linear
    # predictor (η = Xβ + b + offset — e.g. log-exposure in poisson rate
    # models); predictions need the serving offset passed explicitly
    # (``model.predict(x, offset=...)``).
    offset_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None):
        if self.family not in _FAMILY_LINKS:
            raise ValueError(
                f"family must be one of {sorted(_FAMILY_LINKS)}, got "
                f"{self.family!r}"
            )
        default, allowed = _FAMILY_LINKS[self.family]
        link = self.link or default
        if link not in allowed:
            raise ValueError(
                f"link {link!r} is not supported for family "
                f"{self.family!r}; one of {allowed}"
                + (" (tweedie selects its link via link_power)"
                   if self.family == "tweedie" else "")
            )
        vp = float(self.variance_power)
        lp = 0.0
        if self.family == "tweedie":
            if not (vp == 0.0 or vp >= 1.0):
                raise ValueError(
                    f"variance_power must be 0 or >= 1 (Spark's tweedie "
                    f"domain); got {vp}"
                )
            lp = float(self.link_power) if self.link_power is not None else 1.0 - vp
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, link, vp, lp, mesh)
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        offset = None
        if self.offset_col is not None:
            from ..features.assembler import AssembledTable
            from ..parallel.sharding import shard_rows

            if not isinstance(data, AssembledTable):
                raise ValueError(
                    f"offset_col={self.offset_col!r} needs a table input to "
                    f"resolve the column; got {type(data).__name__}"
                )
            if self.offset_col not in data.table.schema:
                raise KeyError(
                    f"offset_col {self.offset_col!r} is not a column of the "
                    f"table; available: {data.table.schema.names}"
                )
            off = np.zeros((ds.n_padded,), np.float32)
            vals = np.asarray(
                data.table.column(self.offset_col), np.float32
            )
            off[: vals.shape[0]] = vals
            offset = shard_rows(off, mesh)
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        self._validate_labels(y_host[w_host > 0], link, vp)
        coef, intercept, it, deviance = _irls_glm(
            ds.x, ds.y, ds.w,
            offset if offset is not None else jnp.zeros_like(ds.y),
            jnp.float32(self.reg_param), jnp.float32(self.tol),
            self.family, link, self.fit_intercept, self.standardize,
            self.max_iter, vp, lp,
        )
        model = GeneralizedLinearRegressionModel(
            coefficients=np.asarray(jax.device_get(coef)),
            intercept=float(intercept),
            family=self.family,
            link=link,
            n_iter=int(it),
            deviance=float(deviance),
            variance_power=vp,
            link_power=lp,
        )
        model._summary = GeneralizedLinearRegressionTrainingSummary(
            model, ds, self.reg_param, self.fit_intercept, offset
        )
        return model

    def _validate_labels(self, yv: np.ndarray, link: str, vp: float) -> None:
        if yv.size == 0:
            raise ValueError("GeneralizedLinearRegression fit on an empty dataset")
        if self.family == "binomial" and not np.all(np.isin(yv, (0.0, 1.0))):
            raise ValueError("binomial family needs 0/1 labels")
        if self.family in ("poisson", "gamma"):
            lo = 0.0 if self.family == "poisson" else np.nextafter(0, 1)
            if yv.min() < lo:
                raise ValueError(
                    f"{self.family} family needs "
                    f"{'non-negative' if self.family == 'poisson' else 'positive'}"
                    " labels"
                )
        if self.family == "tweedie":
            # 1 ≤ p < 2 admits exact zeros (compound Poisson); p ≥ 2 needs
            # strictly positive labels (gamma-and-beyond); p = 0 is
            # gaussian (unrestricted)
            if vp >= 2.0 and yv.min() <= 0.0:
                raise ValueError(
                    f"tweedie with variance_power={vp} needs positive labels"
                )
            if 1.0 <= vp < 2.0 and yv.min() < 0.0:
                raise ValueError(
                    f"tweedie with variance_power={vp} needs non-negative "
                    "labels"
                )
        if self.family == "gaussian" and link == "log" and yv.min() <= 0.0:
            # η₀ = log(y) — a non-positive label would NaN the first IRLS
            # step and silently return an all-NaN model
            raise ValueError("gaussian family with log link needs positive labels")

    def _fit_outofcore(self, hd, link: str, vp: float, lp: float, mesh=None):
        """Rows ≫ HBM IRLS (VERDICT r4 #5): every IRLS iteration streams
        ``max_device_rows`` host blocks through the mesh accumulating the
        SAME weighted (XᵀΩX, XᵀΩz) statistics the resident ``_irls_glm``
        computes in one shot, then runs the identical damped solve — the
        round-4 logistic pattern applied to the whole GLM family surface.
        The first iteration derives η from the family's μ-init exactly as
        the resident loop does; afterwards η = X_aθ.  ``offset_col`` and
        the training ``summary`` are unavailable on this path (the offset
        needs a table column; the summary would pin the dataset)."""
        from ..parallel.mesh import default_mesh
        from ..parallel.outofcore import add_stats

        mesh = mesh or default_mesh()
        if self.offset_col is not None:
            raise ValueError(
                "offset_col needs a table input to resolve the column; "
                "HostDataset has no columns"
            )
        if hd.y is None:
            raise ValueError(
                "GeneralizedLinearRegression needs labels: HostDataset(y=...)"
            )
        y_host = np.asarray(hd.y)
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        self._validate_labels(y_host[w_host > 0], link, vp)

        # pass 0: moments → standardized ridge + ȳ for the μ-init (the
        # shared out-of-core pre-pass, parallel/outofcore.py)
        from ..parallel.outofcore import standardized_ridge, streamed_standardization

        n, _, std, sy = streamed_standardization(hd, mesh, extra="ysum")
        ybar = jnp.float32(sy / n)
        nfeat = hd.n_features
        dd = nfeat + (1 if self.fit_intercept else 0)
        ridge = jnp.asarray(
            standardized_ridge(
                n, std, self.reg_param, nfeat, self.fit_intercept,
                self.standardize,
            )
        )

        theta = jnp.zeros((dd,), jnp.float32)
        it = 0
        for it in range(1, self.max_iter + 1):
            tot = None
            for blk in hd.blocks(mesh):
                s = _glm_block_irls_stats(
                    blk.x, blk.y, blk.w, theta, ybar,
                    self.family, link, self.fit_intercept, it == 1, vp, lp,
                )
                tot = s if tot is None else add_stats(tot, s)
            theta, delta = _glm_update_from_stats(theta, *tot, ridge)
            if float(delta) <= self.tol:
                break

        dev = 0.0
        for blk in hd.blocks(mesh):
            dev += float(
                jax.device_get(
                    _glm_block_deviance(
                        blk.x, blk.y, blk.w, theta,
                        self.family, link, self.fit_intercept, vp, lp,
                    )
                )
            )
        theta_h = np.asarray(jax.device_get(theta))
        return GeneralizedLinearRegressionModel(
            coefficients=theta_h[:nfeat],
            intercept=float(theta_h[nfeat]) if self.fit_intercept else 0.0,
            family=self.family,
            link=link,
            n_iter=it,
            deviance=dev,
            variance_power=vp,
            link_power=lp,
        )
