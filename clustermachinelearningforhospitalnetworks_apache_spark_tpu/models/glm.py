"""GeneralizedLinearRegression — sharded IRLS over exponential families.

Parity with ``pyspark.ml.regression.GeneralizedLinearRegression``
(families gaussian/binomial/poisson/gamma with their canonical and the
common alternative links; L2 ``reg_param`` on standardized coefficients
with the intercept unpenalized — the same Spark convention as
LinearRegression/LogisticRegression here).

MLlib trains GLR with IRLS over ``treeAggregate``'d (XᵀWX, XᵀWz)
statistics.  The TPU-native form keeps that exact algorithm and inverts
the communication into XLA: each IRLS iteration is one jit'd pass over
the row-sharded dataset — the working-response moment matrices are two
MXU matmuls whose cross-shard sums lower to ``psum`` — followed by a tiny
on-device solve; the whole fit is a single ``lax.while_loop`` device
computation (one host sync per fit, like the KMeans/GMM loops).

Per-family pieces (μ = g⁻¹(η)):

    family    V(μ)      canonical link g
    gaussian  1         identity
    binomial  μ(1−μ)    logit
    poisson   μ         log
    gamma     μ²        inverse

Working response z = η + (y−μ)·g'(μ); IRLS weight ω = w / (g'(μ)²·V(μ)).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features
from .linear_regression import standardized_design

_FAMILY_LINKS = {
    "gaussian": ("identity", ("identity", "log")),
    "binomial": ("logit", ("logit",)),
    "poisson": ("log", ("log", "identity", "sqrt")),
    "gamma": ("inverse", ("inverse", "log", "identity")),
}


def _link_fns(link: str):
    """(g(μ), g⁻¹(η), g'(μ)) — all traceable."""
    if link == "identity":
        return (lambda mu: mu, lambda eta: eta, lambda mu: jnp.ones_like(mu))
    if link == "log":
        return (jnp.log, jnp.exp, lambda mu: 1.0 / mu)
    if link == "logit":
        return (
            lambda mu: jnp.log(mu / (1.0 - mu)),
            jax.nn.sigmoid,
            lambda mu: 1.0 / (mu * (1.0 - mu)),
        )
    if link == "inverse":
        return (
            lambda mu: 1.0 / mu,
            lambda eta: 1.0 / eta,
            lambda mu: -1.0 / (mu * mu),
        )
    if link == "sqrt":
        return (jnp.sqrt, lambda eta: eta * eta, lambda mu: 0.5 / jnp.sqrt(mu))
    raise ValueError(f"unknown link {link!r}")


def _variance_fn(family: str):
    return {
        "gaussian": lambda mu: jnp.ones_like(mu),
        "binomial": lambda mu: mu * (1.0 - mu),
        "poisson": lambda mu: mu,
        "gamma": lambda mu: mu * mu,
    }[family]


def _mu_clip(family: str, mu):
    """Keep μ inside the family's domain so V(μ) and g'(μ) stay finite."""
    if family == "binomial":
        return jnp.clip(mu, 1e-6, 1.0 - 1e-6)
    if family in ("poisson", "gamma"):
        return jnp.maximum(mu, 1e-8)
    return mu


@partial(
    jax.jit,
    static_argnames=("family", "link", "fit_intercept", "standardize", "max_iter"),
)
def _irls_glm(
    x, y, w, reg_param, tol,
    family: str, link: str, fit_intercept: bool, standardize: bool, max_iter: int,
):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(
        x, w, reg_param, fit_intercept, standardize
    )
    d = xa.shape[1]
    g, ginv, gprime = _link_fns(link)
    vfn = _variance_fn(family)

    # μ init (Spark/statsmodels convention): nudge y into the domain.
    n = jnp.maximum(jnp.sum(w), 1.0)
    ybar = jnp.sum(y * w) / n
    if family == "binomial":
        mu0 = jnp.clip((y + 0.5) / 2.0, 1e-3, 1.0 - 1e-3)
    elif family in ("poisson", "gamma"):
        mu0 = jnp.maximum(y, 0.0) + 0.1 * jnp.maximum(ybar, 0.1)
    else:
        mu0 = y
    eta0 = g(_mu_clip(family, mu0))

    def irls_step(theta, eta):
        mu = _mu_clip(family, ginv(eta))
        gp = gprime(mu)
        z = eta + (y - mu) * gp
        om = w / jnp.maximum(gp * gp * vfn(mu), 1e-12)
        gram = (xa * om[:, None]).T @ xa + jnp.diag(ridge)
        mom = (xa * om[:, None]).T @ z
        jitter = 1e-7 * jnp.trace(gram) / d + 1e-9
        theta_new = jnp.linalg.solve(gram + jitter * jnp.eye(d, dtype=x.dtype), mom)
        return theta_new, xa @ theta_new

    def cond(carry):
        it, theta, _, delta = carry
        return (it < max_iter) & (delta > tol)

    def body(carry):
        it, theta, eta, _ = carry
        theta_new, eta_new = irls_step(theta, eta)
        delta = jnp.max(jnp.abs(theta_new - theta)) / jnp.maximum(
            jnp.max(jnp.abs(theta_new)), 1.0
        )
        return it + 1, theta_new, eta_new, delta

    theta0 = jnp.zeros((d,), x.dtype)
    it, theta, eta, _ = lax.while_loop(
        cond, body, (jnp.int32(0), theta0, eta0, jnp.float32(jnp.inf))
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)

    # deviance of the final fit (family-specific; Spark summary surface)
    mu = _mu_clip(family, ginv(xa @ theta))
    if family == "gaussian":
        dev_i = (y - mu) ** 2
    elif family == "binomial":
        dev_i = 2.0 * (
            y * jnp.log(jnp.maximum(y, 1e-12) / mu)
            + (1.0 - y) * jnp.log(jnp.maximum(1.0 - y, 1e-12) / (1.0 - mu))
        )
    elif family == "poisson":
        ylog = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        dev_i = 2.0 * (ylog - (y - mu))
    else:  # gamma
        dev_i = 2.0 * (-jnp.log(jnp.maximum(y, 1e-12) / mu) + (y - mu) / mu)
    deviance = jnp.sum(dev_i * w)
    return coef, intercept, it, deviance


@register_model("GeneralizedLinearRegressionModel")
@dataclass
class GeneralizedLinearRegressionModel(Model):
    coefficients: np.ndarray
    intercept: float
    family: str
    link: str
    n_iter: int = 0
    deviance: float = 0.0

    def predict(self, x: jax.Array) -> jax.Array:
        """Mean prediction μ = g⁻¹(xβ + b) (Spark's prediction column)."""
        check_features(x, np.asarray(self.coefficients).shape[0], type(self).__name__)
        _, ginv, _ = _link_fns(self.link)
        eta = x.astype(jnp.float32) @ jnp.asarray(self.coefficients, jnp.float32) + (
            jnp.float32(self.intercept)
        )
        return ginv(eta)

    def predict_link(self, x: jax.Array) -> jax.Array:
        """Linear predictor η (Spark's linkPrediction column)."""
        check_features(x, np.asarray(self.coefficients).shape[0], type(self).__name__)
        return x.astype(jnp.float32) @ jnp.asarray(
            self.coefficients, jnp.float32
        ) + jnp.float32(self.intercept)

    def _artifacts(self):
        return (
            "GeneralizedLinearRegressionModel",
            {
                "family": self.family,
                "link": self.link,
                "intercept": float(self.intercept),
                "n_iter": int(self.n_iter),
                "deviance": float(self.deviance),
            },
            {"coefficients": np.asarray(self.coefficients)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=arrays["coefficients"],
            intercept=float(params["intercept"]),
            family=params["family"],
            link=params["link"],
            n_iter=int(params.get("n_iter", 0)),
            deviance=float(params.get("deviance", 0.0)),
        )


@dataclass(frozen=True)
class GeneralizedLinearRegression(Estimator):
    family: str = "gaussian"          # Spark default
    link: str | None = None           # None = family's canonical link
    reg_param: float = 0.0
    max_iter: int = 25                # Spark default
    tol: float = 1e-6                 # Spark default
    fit_intercept: bool = True
    standardize: bool = True
    label_col: str = "length_of_stay"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None):
        if self.family not in _FAMILY_LINKS:
            raise ValueError(
                f"family must be one of {sorted(_FAMILY_LINKS)}, got "
                f"{self.family!r}"
            )
        default, allowed = _FAMILY_LINKS[self.family]
        link = self.link or default
        if link not in allowed:
            raise ValueError(
                f"link {link!r} is not supported for family "
                f"{self.family!r}; one of {allowed}"
            )
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        yv = y_host[w_host > 0]
        if yv.size == 0:
            raise ValueError("GeneralizedLinearRegression fit on an empty dataset")
        if self.family == "binomial" and not np.all(np.isin(yv, (0.0, 1.0))):
            raise ValueError("binomial family needs 0/1 labels")
        if self.family in ("poisson", "gamma"):
            lo = 0.0 if self.family == "poisson" else np.nextafter(0, 1)
            if yv.min() < lo:
                raise ValueError(
                    f"{self.family} family needs "
                    f"{'non-negative' if self.family == 'poisson' else 'positive'}"
                    " labels"
                )
        if self.family == "gaussian" and link == "log" and yv.min() <= 0.0:
            # η₀ = log(y) — a non-positive label would NaN the first IRLS
            # step and silently return an all-NaN model
            raise ValueError("gaussian family with log link needs positive labels")
        coef, intercept, it, deviance = _irls_glm(
            ds.x, ds.y, ds.w,
            jnp.float32(self.reg_param), jnp.float32(self.tol),
            self.family, link, self.fit_intercept, self.standardize,
            self.max_iter,
        )
        return GeneralizedLinearRegressionModel(
            coefficients=np.asarray(jax.device_get(coef)),
            intercept=float(intercept),
            family=self.family,
            link=link,
            n_iter=int(it),
            deviance=float(deviance),
        )
