from .base import Estimator, Model, PredictionResult, as_device_dataset
from .linear_regression import LinearRegression, LinearRegressionModel
from .logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
    MultinomialLogisticRegressionModel,
)
from .kmeans import KMeans, KMeansModel
from .naive_bayes import NaiveBayes, NaiveBayesModel
from .glm import GeneralizedLinearRegression, GeneralizedLinearRegressionModel
from .isotonic import IsotonicRegression, IsotonicRegressionModel
from .als import ALS, ALSModel
from .mlp import MultilayerPerceptronClassifier, MultilayerPerceptronModel
from .fm import FMClassifier, FMModel, FMRegressor
from .aft import AFTSurvivalRegression, AFTSurvivalRegressionModel
from .lda import LDA, LDAModel
from .pic import PowerIterationClustering
from .fpm import FPGrowth, FPGrowthModel, PrefixSpan
from .linear_svc import LinearSVC, LinearSVCModel
from .gmm import GaussianMixture, GaussianMixtureModel
from .one_vs_rest import OneVsRest, OneVsRestModel
from .bisecting_kmeans import BisectingKMeans, BisectingKMeansModel
from .streaming_kmeans import StreamingKMeans, StreamingKMeansModel
from .streaming_linear import StreamingLinearRegression, StreamingLogisticRegression
from .tree import (
    GBTClassifier,
    GBTModel,
    GBTRegressor,
    DecisionTreeClassifier,
    DecisionTreeModel,
    DecisionTreeRegressor,
    RandomForestClassifier,
    RandomForestModel,
    RandomForestRegressor,
)

__all__ = [
    "ALS",
    "ALSModel",
    "MultilayerPerceptronClassifier",
    "MultilayerPerceptronModel",
    "FMClassifier",
    "FMModel",
    "FMRegressor",
    "AFTSurvivalRegression",
    "AFTSurvivalRegressionModel",
    "LDA",
    "LDAModel",
    "PowerIterationClustering",
    "FPGrowth",
    "FPGrowthModel",
    "PrefixSpan",
    "StreamingLinearRegression",
    "StreamingLogisticRegression",
    "Estimator",
    "Model",
    "PredictionResult",
    "as_device_dataset",
    "GeneralizedLinearRegression",
    "GeneralizedLinearRegressionModel",
    "IsotonicRegression",
    "IsotonicRegressionModel",
    "LinearSVC",
    "LinearSVCModel",
    "OneVsRest",
    "OneVsRestModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "MultinomialLogisticRegressionModel",
    "KMeans",
    "GBTClassifier",
    "GBTModel",
    "GBTRegressor",
    "NaiveBayes",
    "NaiveBayesModel",
    "KMeansModel",
    "GaussianMixture",
    "GaussianMixtureModel",
    "BisectingKMeans",
    "BisectingKMeansModel",
    "StreamingKMeans",
    "StreamingKMeansModel",
    "DecisionTreeClassifier",
    "DecisionTreeModel",
    "DecisionTreeRegressor",
    "RandomForestClassifier",
    "RandomForestModel",
    "RandomForestRegressor",
]
