from .base import Estimator, Model, PredictionResult, as_device_dataset
from .linear_regression import LinearRegression, LinearRegressionModel

__all__ = [
    "Estimator",
    "Model",
    "PredictionResult",
    "as_device_dataset",
    "LinearRegression",
    "LinearRegressionModel",
]
