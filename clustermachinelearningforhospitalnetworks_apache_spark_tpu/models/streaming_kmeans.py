"""StreamingKMeans — incremental k-means over micro-batches (BASELINE
config 5: "StreamingKMeans on HL7/FHIR admission micro-batches").

Capability parity: ``pyspark.mllib.clustering.StreamingKMeans`` — the
forgetful update rule with a decay factor (or half-life in batches/points):

    cₜ₊₁ = (cₜ·nₜ·α + Σ_{batch} x) / (nₜ·α + mₜ)
    nₜ₊₁ = nₜ·α + mₜ

Each micro-batch update is one jit'd assignment pass (the same MXU distance
matmul as batch KMeans) plus the decayed merge — constant work per batch,
no growth with stream length.  Dying clusters (decayed count below a
threshold) are re-seeded by splitting the largest cluster, as Spark does.

Plugs into the streaming micro-batch driver (streaming/microbatch.py) as a
``foreachBatch``-style consumer — the working version of the reference's
dead incremental-training hook (``mllearnforhospitalnetwork.py:87-106``,
SURVEY.md C6/D2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.mesh import default_mesh
from ..parallel.sharding import (
    DeviceDataset,
    batch_rows,
    mesh_of_dataset,
    microbatch_mesh,
    place_replicated,
)
from .base import Model, as_device_dataset
from .kmeans import KMeansModel


@jax.jit
def _batch_stats(x, w, centers):
    # The assignment argmin runs over d² MINUS the row-constant ‖x‖² term
    # (adding a per-row constant never changes a row's argmin).  With the
    # old full-d² formulation the (n,) square-norm pass sat INSIDE the
    # argmin operand where XLA cannot prove it row-constant; here it only
    # appears in ``cost``, so callers that ignore cost (the streaming
    # update body) get it dead-code-eliminated — one fewer O(n·d) pass on
    # the per-batch hot path.
    c2 = jnp.sum(centers * centers, axis=1)
    score = x @ (-2.0 * centers.T) + c2[None, :]
    assign = jnp.argmin(score, axis=1)
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=x.dtype) * w[:, None]
    sums = onehot.T @ x
    counts = jnp.sum(onehot, axis=0)
    # true squared distance restores the ‖x‖² term; clamp the fp
    # cancellation residue so cost can't go (slightly) negative
    mind2 = jnp.maximum(jnp.min(score, axis=1) + jnp.sum(x * x, axis=1), 0.0)
    cost = jnp.sum(mind2 * w)
    return sums, counts, cost


def _step_body(k: int, alpha_mode: str, alpha_param: float):
    """The micro-batch update rule: assignment stats, decayed merge, and
    the dying-cluster reseed.  Shared verbatim between the one-batch step
    and the ``update_many`` scan so their results are bit-identical.

    Weights are carried as a Kahan (value, compensation) pair: JAX on TPU
    has no f64, and with decay 1.0 a single f32 accumulator stops growing
    once a cluster passes 2²⁴ points — the compensated sum keeps absorbing
    per-batch counts exactly."""

    def body(x, w, centers, w_hi, w_lo, key):
        sums, counts, _ = _batch_stats(x, w, centers)
        m = jnp.sum(counts)
        if alpha_mode == "points":
            alpha = jnp.where(
                m > 0, jnp.float32(0.5) ** (m / alpha_param), jnp.float32(1.0)
            ) if alpha_param > 0 else jnp.float32(0.0)
        elif alpha_mode == "batches":
            alpha = jnp.float32(0.5 ** (1.0 / alpha_param)) if alpha_param > 0 else jnp.float32(0.0)
        else:  # fixed decay factor
            alpha = jnp.float32(alpha_param)

        # decay both limbs, then Kahan-add this batch's counts
        hi, lo = w_hi * alpha, w_lo * alpha
        add = counts + lo
        new_hi = hi + add
        new_lo = (hi - new_hi) + add           # exact residual of the add
        decayed = hi + lo
        new_w = new_hi + new_lo
        safe = jnp.maximum(new_w, 1e-12)
        merged = (centers * decayed[:, None] + sums) / safe[:, None]
        # A cluster with no mass this step and no retained history keeps
        # its old center rather than collapsing to zero (Spark λ=0).
        centers = jnp.where(new_w[:, None] > 1e-12, merged, centers)
        # Dying-cluster reseed (Spark rule): walk clusters in index order,
        # splitting the current-heaviest for each effectively-dead one.
        # Touched entries collapse their Kahan pair (hi=split, lo=0).
        def reseed(i, carry):
            cen, hi, lo, key = carry
            eff = hi + lo
            total = jnp.sum(eff)
            big = jnp.argmax(eff)
            act = (eff[i] < 1e-8 * total) & (big != i) & (total > 0)
            key, sub = jax.random.split(key)
            jitter = 1e-4 * (jnp.abs(cen[big]) + 1e-4)
            noise = jax.random.normal(sub, cen[big].shape, cen.dtype) * jitter
            cen = cen.at[i].set(jnp.where(act, cen[big] + noise, cen[i]))
            wb = eff[big]
            hi = hi.at[i].set(jnp.where(act, wb / 2, hi[i]))
            lo = lo.at[i].set(jnp.where(act, 0.0, lo[i]))
            hi = hi.at[big].set(jnp.where(act, wb / 2, hi[big]))
            lo = lo.at[big].set(jnp.where(act, 0.0, lo[big]))
            return cen, hi, lo, key

        centers, new_hi, new_lo, _ = jax.lax.fori_loop(
            0, k, reseed, (centers, new_hi, new_lo, key)
        )
        return centers, new_hi, new_lo

    return body


@lru_cache(maxsize=32)
def _make_update_step(k: int, alpha_mode: str, alpha_param: float, seed: int):
    """One jitted device call per micro-batch — no host synchronization.
    The stream state (centers, weights) stays on device between batches;
    only ``latest_model`` pulls it to host.  The per-step RNG key is
    derived INSIDE the jit (``fold_in(key(seed), step)``), so an update
    dispatches exactly one executable — on tunneled chips every extra host
    op is a network round trip."""
    body = _step_body(k, alpha_mode, alpha_param)

    def step(x, w, centers, w_hi, w_lo, steps):
        key = jax.random.fold_in(jax.random.key(seed), steps)
        return body(x, w, centers, w_hi, w_lo, key)

    # donated state: centers/weights update IN PLACE (input-output
    # aliasing), so steady-state batches allocate no new device buffers —
    # the estimator reassigns its fields from the outputs immediately, so
    # the consumed inputs are never read again
    return jax.jit(step, donate_argnums=(2, 3, 4))


@lru_cache(maxsize=32)
def _make_update_many(k: int, alpha_mode: str, alpha_param: float, seed: int):
    """Backlog drain: ``lax.scan`` of the SAME per-batch body over a
    stacked (B, n, d) batch tensor — one transfer + one dispatch for the
    whole backlog instead of B of each, with results bit-identical to B
    sequential ``update`` calls (each scan step folds in its own key and
    applies its own decayed merge)."""
    body = _step_body(k, alpha_mode, alpha_param)

    def drain(xs, ws, centers, w_hi, w_lo, steps0):
        base = jax.random.key(seed)

        def scan_step(carry, xw):
            centers, hi, lo, steps = carry
            x, w = xw
            key = jax.random.fold_in(base, steps)
            centers, hi, lo = body(x, w, centers, hi, lo, key)
            return (centers, hi, lo, steps + 1), None

        (centers, w_hi, w_lo, _), _ = jax.lax.scan(
            scan_step, (centers, w_hi, w_lo, steps0), (xs, ws)
        )
        return centers, w_hi, w_lo

    # donated state (the triple is reassigned from the outputs, so the
    # consumed buffers are never read again); the xs/ws staging stack is
    # NOT donated — nothing output-shaped can alias it, and jax warns on
    # unusable donations
    return jax.jit(drain, donate_argnums=(2, 3, 4))


def _host_rows(batch) -> tuple[np.ndarray, np.ndarray]:
    """Coerce one backlog entry to host ``(x, w)`` — the same input forms
    :meth:`StreamingKMeans.update` accepts (bare array, ``(x, y[, w])``
    tuple, AssembledTable, DeviceDataset); clustering ignores labels.  A
    DeviceDataset's pad rows are dropped and its (possibly fractional)
    sample weights are carried so the drain matches per-batch ``update``."""
    from ..features.assembler import AssembledTable

    if isinstance(batch, DeviceDataset):
        x = np.asarray(jax.device_get(batch.x), dtype=np.float32)
        w = np.asarray(jax.device_get(batch.w), dtype=np.float32)
        keep = w > 0
        return np.atleast_2d(x[keep]), w[keep]
    if isinstance(batch, AssembledTable):
        x = np.atleast_2d(np.asarray(batch.features, dtype=np.float32))
        return x, np.ones(x.shape[0], dtype=np.float32)
    if isinstance(batch, tuple) and len(batch) == 3:
        x = np.atleast_2d(np.asarray(batch[0], dtype=np.float32))
        return x, np.asarray(batch[2], dtype=np.float32).reshape(-1)
    if isinstance(batch, tuple) and len(batch) == 2:
        x = np.atleast_2d(np.asarray(batch[0], dtype=np.float32))
        return x, np.ones(x.shape[0], dtype=np.float32)
    x = np.atleast_2d(np.asarray(batch, dtype=np.float32))
    return x, np.ones(x.shape[0], dtype=np.float32)


@register_model("StreamingKMeansModel")
@dataclass
class StreamingKMeansModel(KMeansModel):
    cluster_weights: np.ndarray | None = None  # decayed nₜ per cluster

    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        arrays["cluster_weights"] = (
            np.asarray(self.cluster_weights)
            if self.cluster_weights is not None
            else np.zeros((self.k,))
        )
        return ("StreamingKMeansModel", meta, arrays)

    @classmethod
    def from_artifacts(cls, params, arrays):
        m = super().from_artifacts(params, arrays)
        m.cluster_weights = arrays.get("cluster_weights")
        return m


@dataclass
class StreamingKMeans:
    """Stateful estimator: ``update(batch)`` per micro-batch.

    decay_factor=1.0 → all history weighted equally; 0.0 → only the latest
    batch.  ``half_life`` (in points or batches) overrides decay_factor,
    matching Spark's ``setHalfLife``.
    """

    k: int = 8
    decay_factor: float = 1.0
    half_life: float | None = None
    time_unit: str = "batches"  # or "points"
    seed: int = 0
    #: shard a micro-batch over the mesh only when every device gets at
    #: least this many rows; smaller batches run on ONE device (see
    #: ``parallel.sharding.microbatch_mesh`` — for typical micro-batch
    #: sizes the collectives + multi-device dispatch cost more than the
    #: parallelism buys, and per-chip throughput is what streaming pays
    #: for).  None → the CMLHN_STREAM_SHARD_MIN_ROWS env default.
    shard_min_rows_per_device: int | None = None
    _centers: np.ndarray | None = field(default=None, repr=False)
    _weights: np.ndarray | None = field(default=None, repr=False)
    _weights_lo: np.ndarray | None = field(default=None, repr=False)
    _steps: int = field(default=0, repr=False)
    _state_mesh: object = field(default=None, repr=False)

    def set_initial_centers(self, centers: np.ndarray, weights: np.ndarray | None = None):
        # Stream state lives on device between batches (jnp arrays);
        # latest_model pulls it to host on demand.  Weights are a Kahan
        # (value, compensation) pair — see _make_update_step.
        self._centers = jnp.asarray(np.asarray(centers), jnp.float32)
        self._weights = (
            jnp.asarray(np.asarray(weights), jnp.float32)
            if weights is not None
            else jnp.zeros((self._centers.shape[0],), jnp.float32)
        )
        self._weights_lo = jnp.zeros_like(self._weights)
        self._state_mesh = None  # fresh (uncommitted) state: re-place lazily
        return self

    def set_random_centers(self, dim: int, weight: float = 0.0):
        rng = np.random.default_rng(self.seed)
        return self.set_initial_centers(
            rng.normal(size=(self.k, dim)), np.full((self.k,), weight)
        )

    @property
    def latest_model(self) -> StreamingKMeansModel:
        if self._centers is None:
            raise ValueError("StreamingKMeans has no centers yet; call update or set_*")
        cen, hi, lo = jax.device_get(
            (self._centers, self._weights, self._weights_lo)
        )
        return StreamingKMeansModel(
            cluster_centers=np.asarray(cen, dtype=np.float32),
            n_iter=self._steps,
            cluster_weights=np.asarray(hi, dtype=np.float64)
            + np.asarray(lo, dtype=np.float64),
        )

    def update(self, batch, mesh=None) -> "StreamingKMeans":
        """Consume one micro-batch; returns ``self`` for chaining.  The
        updated state stays on device — read ``latest_model`` to
        materialize it (one host transfer).

        .. note:: prior to round 1's device-resident rework this returned a
           ``StreamingKMeansModel``; callers doing
           ``model = sk.update(batch)`` must now read ``sk.latest_model``
           for ``cluster_centers``/``cluster_weights`` (the estimator
           itself has no such attributes)."""
        mesh = mesh or default_mesh()
        if not isinstance(batch, DeviceDataset):
            mesh = microbatch_mesh(
                batch_rows(batch), mesh, self.shard_min_rows_per_device
            )
        ds = as_device_dataset(batch, mesh=mesh)
        self._ensure_centers(ds)
        self._place_state(ds)
        mode, param = self._alpha()
        step = _make_update_step(self.k, mode, param, self.seed)
        self._centers, self._weights, self._weights_lo = step(
            ds.x, ds.w, self._centers, self._weights, self._weights_lo,
            np.int32(self._steps),
        )
        self._steps += 1
        return self

    def update_many(self, batches, mesh=None) -> "StreamingKMeans":
        """Drain a backlog: apply every micro-batch's decayed update in one
        stacked transfer + one device dispatch (``lax.scan`` over batches)
        — the same per-batch rule as :meth:`update`, bit-identical for
        equal-length batches (identical shapes → identical XLA reduction
        tiling) and ulp-identical for ragged ones (shorter batches are
        padded with inert zero-weight rows, which shifts f32 reduction
        order only).  On tunneled chips — where each dispatch pays a
        network round trip — this is the difference between per-batch
        latency and compute-bound throughput.
        """
        mesh = mesh or default_mesh()
        batches = [_host_rows(b) for b in batches]
        if not batches:
            return self
        from ..parallel.mesh import DATA_AXIS
        from ..parallel.partitioner import family as _partitioner_family
        from ..parallel.sharding import pad_rows, stack_ragged

        mesh = microbatch_mesh(
            max(b.shape[0] for b, _ in batches), mesh,
            self.shard_min_rows_per_device,
        )
        if self._centers is None:
            fx, fw = batches[0]
            # 3-tuple keeps the first batch's sample weights in play
            self.update((fx, np.zeros(fx.shape[0], np.float32), fw), mesh=mesh)
            batches = batches[1:]
            if not batches:
                return self
        n_pad = pad_rows(max(b.shape[0] for b, _ in batches), mesh.shape[DATA_AXIS])
        # ragged batches -> one padded stack + weight mask (the shared
        # pad-and-weight contract; np.empty + tail-zero idiom lives there)
        xs, ws = stack_ragged(
            [b for b, _ in batches], [bw for _, bw in batches], pad_to=n_pad
        )
        _pt = _partitioner_family("streaming_kmeans")
        xs = _pt.put("stack/x", xs, mesh)
        ws = _pt.put("stack/w", ws, mesh)
        self._place_state_mesh(mesh)
        mode, param = self._alpha()
        drain = _make_update_many(self.k, mode, param, self.seed)
        self._centers, self._weights, self._weights_lo = drain(
            xs, ws, self._centers, self._weights, self._weights_lo,
            np.int32(self._steps),
        )
        self._steps += len(batches)
        return self

    def _place_state(self, ds: DeviceDataset) -> None:
        """Commit the stream state to the mesh the batch actually lives
        on (derived from the batch's own sharding, so caller-built
        DeviceDatasets are honored).  Adaptive placement switches between
        the full mesh and a single device as batch sizes change; the
        state triple is tiny (k×d + 2k floats), so re-placing it is one
        cheap transfer and jit never sees mixed-committed inputs."""
        mesh = mesh_of_dataset(ds)
        if mesh is not None:
            self._place_state_mesh(mesh)

    def _place_state_mesh(self, mesh) -> None:
        if self._centers is None or self._state_mesh == mesh:
            return
        self._centers, self._weights, self._weights_lo = place_replicated(
            mesh, (self._centers, self._weights, self._weights_lo)
        )
        self._state_mesh = mesh

    def _ensure_centers(self, ds: DeviceDataset) -> None:
        if self._centers is not None:
            return
        # lazily init from the first batch: k-means++ seeding + short
        # Lloyd refinement (raw ++ points alone are a poor init when two
        # clusters are close)
        from ..parallel.sharding import sample_valid_rows
        from .kmeans import _kmeans_pp_init, _lloyd_refine

        host = sample_valid_rows(ds, 65536, self.seed)
        self.set_initial_centers(
            _lloyd_refine(host, _kmeans_pp_init(host, self.k, self.seed), iters=10)
        )

    def _alpha(self) -> tuple[str, float]:
        if self.half_life is not None:
            if self.time_unit not in ("points", "batches"):
                raise ValueError(
                    f"time_unit must be 'points' or 'batches', got {self.time_unit!r}"
                )
            return self.time_unit, float(self.half_life)
        return "decay", float(self.decay_factor)

    def predict(self, x):
        return self.latest_model.predict(x)
