"""StreamingKMeans — incremental k-means over micro-batches (BASELINE
config 5: "StreamingKMeans on HL7/FHIR admission micro-batches").

Capability parity: ``pyspark.mllib.clustering.StreamingKMeans`` — the
forgetful update rule with a decay factor (or half-life in batches/points):

    cₜ₊₁ = (cₜ·nₜ·α + Σ_{batch} x) / (nₜ·α + mₜ)
    nₜ₊₁ = nₜ·α + mₜ

Each micro-batch update is one jit'd assignment pass (the same MXU distance
matmul as batch KMeans) plus the decayed merge — constant work per batch,
no growth with stream length.  Dying clusters (decayed count below a
threshold) are re-seeded by splitting the largest cluster, as Spark does.

Plugs into the streaming micro-batch driver (streaming/microbatch.py) as a
``foreachBatch``-style consumer — the working version of the reference's
dead incremental-training hook (``mllearnforhospitalnetwork.py:87-106``,
SURVEY.md C6/D2).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.distance import assign_clusters
from ..parallel.mesh import default_mesh
from ..parallel.sharding import DeviceDataset
from .base import Model, as_device_dataset
from .kmeans import KMeansModel


@jax.jit
def _batch_stats(x, w, centers):
    assign, mind2 = assign_clusters(x, centers)
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=x.dtype) * w[:, None]
    sums = onehot.T @ x
    counts = jnp.sum(onehot, axis=0)
    cost = jnp.sum(mind2 * w)
    return sums, counts, cost


@register_model("StreamingKMeansModel")
@dataclass
class StreamingKMeansModel(KMeansModel):
    cluster_weights: np.ndarray | None = None  # decayed nₜ per cluster

    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        arrays["cluster_weights"] = (
            np.asarray(self.cluster_weights)
            if self.cluster_weights is not None
            else np.zeros((self.k,))
        )
        return ("StreamingKMeansModel", meta, arrays)

    @classmethod
    def from_artifacts(cls, params, arrays):
        m = super().from_artifacts(params, arrays)
        m.cluster_weights = arrays.get("cluster_weights")
        return m


@dataclass
class StreamingKMeans:
    """Stateful estimator: ``update(batch)`` per micro-batch.

    decay_factor=1.0 → all history weighted equally; 0.0 → only the latest
    batch.  ``half_life`` (in points or batches) overrides decay_factor,
    matching Spark's ``setHalfLife``.
    """

    k: int = 8
    decay_factor: float = 1.0
    half_life: float | None = None
    time_unit: str = "batches"  # or "points"
    seed: int = 0
    _centers: np.ndarray | None = field(default=None, repr=False)
    _weights: np.ndarray | None = field(default=None, repr=False)
    _steps: int = field(default=0, repr=False)

    def set_initial_centers(self, centers: np.ndarray, weights: np.ndarray | None = None):
        self._centers = np.asarray(centers, dtype=np.float32)
        self._weights = (
            np.asarray(weights, dtype=np.float64)
            if weights is not None
            else np.zeros((self._centers.shape[0],), dtype=np.float64)
        )
        return self

    def set_random_centers(self, dim: int, weight: float = 0.0):
        rng = np.random.default_rng(self.seed)
        return self.set_initial_centers(
            rng.normal(size=(self.k, dim)), np.full((self.k,), weight)
        )

    @property
    def latest_model(self) -> StreamingKMeansModel:
        if self._centers is None:
            raise ValueError("StreamingKMeans has no centers yet; call update or set_*")
        return StreamingKMeansModel(
            cluster_centers=self._centers.copy(),
            n_iter=self._steps,
            cluster_weights=self._weights.copy(),
        )

    def update(self, batch, mesh=None) -> StreamingKMeansModel:
        mesh = mesh or default_mesh()
        ds = as_device_dataset(batch, mesh=mesh)
        x = ds.x.astype(jnp.float32)
        if self._centers is None:
            # lazily init from the first batch: k-means++ seeding + short
            # Lloyd refinement (raw ++ points alone are a poor init when two
            # clusters are close)
            from ..parallel.sharding import sample_valid_rows
            from .kmeans import _kmeans_pp_init, _lloyd_refine

            host = sample_valid_rows(
                DeviceDataset(x, ds.y, ds.w), 65536, self.seed
            )
            self.set_initial_centers(
                _lloyd_refine(host, _kmeans_pp_init(host, self.k, self.seed), iters=10)
            )
        sums, counts, _ = _batch_stats(x, ds.w, jnp.asarray(self._centers))
        sums = np.asarray(jax.device_get(sums), dtype=np.float64)
        counts = np.asarray(jax.device_get(counts), dtype=np.float64)

        m = counts.sum()
        if self.half_life is not None:
            if self.time_unit == "points":
                alpha = 0.5 ** (m / self.half_life) if self.half_life > 0 else 0.0
            else:
                alpha = 0.5 ** (1.0 / self.half_life) if self.half_life > 0 else 0.0
        else:
            alpha = self.decay_factor

        decayed = self._weights * alpha
        new_w = decayed + counts
        safe = np.maximum(new_w, 1e-12)
        merged = (self._centers * decayed[:, None] + sums) / safe[:, None]
        # A cluster with no mass this step and no retained history keeps its
        # old center rather than collapsing to zero (Spark's λ=0 behavior).
        self._centers = np.where(
            new_w[:, None] > 1e-12, merged, self._centers
        ).astype(np.float32)
        self._weights = new_w
        self._steps += 1
        self._reseed_dying()
        return self.latest_model

    def _reseed_dying(self, threshold_ratio: float = 1e-8):
        """Split the heaviest cluster to replace any effectively-dead one
        (Spark's dying-cluster rule)."""
        total = self._weights.sum()
        if total <= 0:
            return
        dead = np.where(self._weights < threshold_ratio * total)[0]
        if len(dead) == 0:
            return
        rng = np.random.default_rng(self.seed + self._steps)
        for idx in dead:
            big = int(np.argmax(self._weights))
            if big == idx:
                continue
            jitter = 1e-4 * (np.abs(self._centers[big]) + 1e-4)
            self._centers[idx] = self._centers[big] + rng.normal(size=jitter.shape) * jitter
            self._weights[idx] = self._weights[big] / 2
            self._weights[big] = self._weights[big] / 2

    def predict(self, x):
        return self.latest_model.predict(x)
