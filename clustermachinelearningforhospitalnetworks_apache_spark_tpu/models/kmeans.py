"""KMeans — the north-star workload (BASELINE.json: k=256 Lloyd loop on a
TPU mesh at ≥10× Spark-CPU throughput).

Capability parity: ``pyspark.ml.clustering.KMeans`` (named by the BASELINE
configs; the reference script itself trains only supervised models —
SURVEY.md §0 scope note).  Spark's implementation runs Lloyd iterations as
RDD jobs: per-partition assignment + center sums combined via
``treeAggregate`` (SURVEY.md §3.3).  The TPU-native design maps one Lloyd
iteration onto the mesh as a single jit'd ``shard_map``:

- **data axis**: rows are sharded; each device scans its rows in fixed-size
  chunks (``lax.scan`` — static shapes, VMEM-friendly) computing the
  (chunk, k) distance matrix as one MXU matmul (ops/distance.py).
- **model axis**: for large k the *centroid* axis is sharded — each model
  shard scores only its k/m centroids, a cross-shard ``all_gather`` of the
  per-shard minima (m scalars per row, tiny) resolves the global argmin,
  and each shard accumulates sums only for its own centroids.  This is the
  classical-ML analogue of tensor parallelism (SURVEY.md §2C).
- Center sums/counts are ``psum``'d over the data axis — the
  ``treeAggregate`` replacement, riding ICI.

Empty clusters keep their previous center (Spark behavior).  Convergence:
max centroid movement < tol, or max_iter (Spark defaults 20, 1e-4).
Initialization: ``k-means++`` on a host-side sample (Spark's default is
k-means|| — a distributed approximation of the same objective; on TPU the
sample fits on host so the exact sequential form is used) or ``random``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..io.model_io import register_model
from ..ops.distance import normalize_rows, pairwise_sqdist, sq_norms
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, default_mesh
from ..parallel.partitioner import family as _partitioner_family

#: the one declarative rule table for Lloyd layouts (parallel/partitioner.py)
_PT = _partitioner_family("kmeans")
from ..parallel.outofcore import add_stats as _add_stats
from ..parallel.sharding import (
    DeviceDataset,
    chunk_layout,
    chunked_pad,
    pad_slots,
    padded_slots,
    slot_mask,
)
from .base import ClusteringModel, Estimator, Model, as_device_dataset, check_features

# np scalar, not jnp: a module-level jnp constant would initialize
# the backend at import time (hangs when the TPU tunnel is down)
_BIG = np.float32(1e30)


def _centroid_rule(sums, counts, centers, c_valid, cosine: bool):
    """The one copy of the centroid-update rule, shared by the resident
    step tail (:func:`_finalize_lloyd`) and the out-of-core update
    (:func:`_centroid_update`): empty clusters keep their previous center
    (Spark behavior); cosine re-normalizes after every update (Spark's
    CosineDistanceMeasure — without it the ||c||² term in the distance
    stops ordering by cosine similarity)."""
    new_centers = jnp.where(
        (counts > 0)[:, None], sums / jnp.maximum(counts, 1.0)[:, None], centers
    )
    if cosine:
        new_centers = normalize_rows(new_centers)
    move = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1) * c_valid)
    return new_centers, move


def _finalize_lloyd(sums, counts, cost, centers, c_valid, cosine: bool):
    """Shared tail of both step builders: combine per-shard stats over the
    data axis, apply the centroid update, compute convergence movement."""
    sums = lax.psum(sums, DATA_AXIS)
    counts = lax.psum(counts, DATA_AXIS)
    # cost is numerically identical on every model shard (built from the
    # global per-row minima); pmax collapses the model-axis variance so it
    # can be emitted replicated.
    cost = lax.pmax(lax.psum(cost, DATA_AXIS), MODEL_AXIS)
    new_centers, move = _centroid_rule(sums, counts, centers, c_valid, cosine)
    move = lax.pmax(move, MODEL_AXIS)
    return new_centers, counts, cost, move


# shared scan-chunk geometry (parallel/sharding.py); the old private name
# stays importable for the sibling families that grew up on it
_chunked = chunk_layout


def _lloyd_shard_stats(
    n_loc: int, k_pad: int, d: int, chunk_rows: int, m: int,
    precision: str = "highest", fuse_stats: bool = False,
):
    """Shard-local Lloyd sufficient statistics — the chunk-scanned
    assignment + accumulation shared by the resident train step and the
    out-of-core block-stats step.  Returns a function
    ``(x, w, centers, c_valid) -> (sums, counts, cost)`` (pre-psum).

    ``fuse_stats`` (bf16-mode only; bench-A/B'd before the headline
    adopts it) restructures the accumulation half of the step for MXU
    rate: the assignment argmin runs on the x²-free basis ``c_sq −
    2·x·cᵀ`` (row-constant x² cannot change the argmin — one fewer VPU
    pass over the (chunk, k) tile, with x² re-added only for the scalar
    cost), and sums+counts come from ONE bf16 one-hot matmul against
    ``[x | 1]`` (f32 accumulation) instead of an f32 matmul plus a
    separate reduction — the sums matmul otherwise costs the same
    2·k·d FLOPs as the distance matmul but at the slower precision.
    Loop-internal cost carries bf16 cross-term rounding exactly like the
    plain bf16 mode; the fit's final cost/sizes stay exact (see
    ``_make_train_loop``)."""
    if fuse_stats and precision != "bf16":
        raise ValueError("fuse_stats requires matmul_precision='bf16'")
    n_chunks, chunk = chunk_layout(n_loc, chunk_rows)
    k_loc = k_pad // m

    def stats(x, w, centers, c_valid):
        # x: (n_loc, d) data-shard; centers: (k_loc, d) model-shard;
        # c_valid: (k_loc,) 1.0 for real centroids, 0.0 for k-padding.
        my_m = lax.axis_index(MODEL_AXIS)
        xc, wc = chunked_pad(x, w, n_chunks, chunk)
        c_sq = sq_norms(centers)
        cen_bf = centers.astype(jnp.bfloat16) if fuse_stats else None

        def body(carry, inputs):
            sums, counts, cost = carry
            xb, wb = inputs
            if fuse_stats:
                cross = jnp.dot(
                    xb.astype(jnp.bfloat16), cen_bf.T,
                    preferred_element_type=jnp.float32,
                )
                # x²-free argmin basis: x_sq is row-constant, so both the
                # local argmin AND the cross-shard owner comparison are
                # unchanged (every shard sees the same row's x_sq)
                basis = c_sq[None, :] - 2.0 * cross
                basis = jnp.where(c_valid[None, :] > 0, basis, _BIG)
                loc_min = jnp.min(basis, axis=1)
                loc_arg = jnp.argmin(basis, axis=1).astype(jnp.int32)
            else:
                d2 = pairwise_sqdist(xb, centers, c_sq=c_sq, precision=precision)
                d2 = jnp.where(c_valid[None, :] > 0, d2, _BIG)
                loc_min = jnp.min(d2, axis=1)
                loc_arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
            # Resolve global argmin across the model axis: m scalars/row.
            all_min = lax.all_gather(loc_min, MODEL_AXIS)        # (m, chunk)
            owner = jnp.argmin(all_min, axis=0).astype(jnp.int32)  # (chunk,)
            g_min = jnp.min(all_min, axis=0)
            mine = (owner == my_m) & (wb > 0)
            if fuse_stats:
                g_min = jnp.maximum(g_min + sq_norms(xb), 0.0)
                oh = jax.nn.one_hot(loc_arg, k_loc, dtype=jnp.bfloat16)
                oh = oh * (
                    mine.astype(jnp.bfloat16) * wb.astype(jnp.bfloat16)
                )[:, None]
                x1 = jnp.concatenate(
                    [xb.astype(jnp.bfloat16), jnp.ones((chunk, 1), jnp.bfloat16)],
                    axis=1,
                )
                sc = jnp.dot(oh.T, x1, preferred_element_type=jnp.float32)
                sums = sums + sc[:, :d]
                counts = counts + sc[:, d]
            else:
                onehot = jax.nn.one_hot(loc_arg, k_loc, dtype=xb.dtype)
                onehot = onehot * (mine.astype(xb.dtype) * wb)[:, None]
                sums = sums + onehot.T @ xb
                counts = counts + jnp.sum(onehot, axis=0)
            cost = cost + jnp.sum(g_min * wb)
            return (sums, counts, cost), None

        init = jax.tree.map(
            lambda z: lax.pcast(z, (DATA_AXIS, MODEL_AXIS), to="varying"),
            (
                jnp.zeros((k_loc, d), x.dtype),
                jnp.zeros((k_loc,), x.dtype),
                jnp.zeros((), x.dtype),
            ),
        )
        (sums, counts, cost), _ = lax.scan(body, init, (xc, wc))
        return sums, counts, cost

    return stats


@lru_cache(maxsize=64)
def _make_train_step(
    mesh: Mesh, n_loc: int, k_pad: int, d: int, chunk_rows: int,
    cosine: bool = False, precision: str = "highest",
    fuse_stats: bool = False,
):
    """One full Lloyd iteration as a shard_map over (data, model).
    ``precision`` picks the assignment matmul mode (``"bf16"`` = native
    one-pass MXU rate with f32 accumulation; see ops/distance.py);
    ``fuse_stats`` additionally runs the accumulation half at that rate
    (see :func:`_lloyd_shard_stats`)."""
    m = mesh.shape[MODEL_AXIS]
    stats = _lloyd_shard_stats(
        n_loc, k_pad, d, chunk_rows, m, precision, fuse_stats
    )

    def shard_fn(x, w, centers, c_valid):
        sums, counts, cost = stats(x, w, centers, c_valid)
        return _finalize_lloyd(sums, counts, cost, centers, c_valid, cosine)

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2), _PT.spec("batch/w", 1),
                      _PT.spec("state/centers", 2), _PT.spec("state/c_valid", 1)),
            out_specs=(_PT.spec("stats/sums", 2), _PT.spec("stats/counts", 1),
                       _PT.spec("scalar/cost"), _PT.spec("scalar/move")),
        )
    )


@lru_cache(maxsize=64)
def _make_stats_step(
    mesh: Mesh, n_loc: int, k_pad: int, d: int, chunk_rows: int,
    precision: str = "highest", fuse_stats: bool = False,
):
    """Per-BLOCK Lloyd sufficient statistics (sums, counts, cost), psum'd
    over the mesh but WITHOUT the centroid update — the out-of-core driver
    accumulates these across host row blocks, then applies one
    :func:`_centroid_update` per Lloyd iteration."""
    m = mesh.shape[MODEL_AXIS]
    stats = _lloyd_shard_stats(
        n_loc, k_pad, d, chunk_rows, m, precision, fuse_stats
    )

    def shard_fn(x, w, centers, c_valid):
        sums, counts, cost = stats(x, w, centers, c_valid)
        sums = lax.psum(sums, DATA_AXIS)
        counts = lax.psum(counts, DATA_AXIS)
        cost = lax.pmax(lax.psum(cost, DATA_AXIS), MODEL_AXIS)
        return sums, counts, cost

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2), _PT.spec("batch/w", 1),
                      _PT.spec("state/centers", 2), _PT.spec("state/c_valid", 1)),
            out_specs=(_PT.spec("stats/sums", 2), _PT.spec("stats/counts", 1),
                       _PT.spec("scalar/cost")),
        )
    )


@partial(jax.jit, static_argnames=("cosine",))
def _centroid_update(sums, counts, centers, c_valid, cosine: bool):
    """Centroid update from fully-accumulated out-of-core stats — the same
    :func:`_centroid_rule` the resident step applies per iteration."""
    return _centroid_rule(sums, counts, centers, c_valid, cosine)


@jax.jit
def _cosine_prep(x, w):
    """Unit rows with pad rows zeroed — the cosine-mode preprocessing the
    resident fit applies once, applied per streamed block instead."""
    return normalize_rows(x.astype(jnp.float32)) * (w[:, None] > 0)


@lru_cache(maxsize=64)
def _make_train_step_fused(mesh: Mesh, k_pad: int, cosine: bool):
    """Lloyd iteration with the Pallas fused stats kernel per data shard
    (ops/pallas_kernels.py) — one VMEM-resident pass producing center
    sums/counts/cost without materializing the (rows, k) distance or
    one-hot matrices in HBM.  Requires the model axis to be 1 (the
    single-chip / pure-DP case, which includes the BASELINE bench)."""
    from ..ops.pallas_kernels import fused_lloyd_stats

    def shard_fn(x, w, centers, c_valid):
        # The kernel's operands must agree on their varying mesh axes
        # (x varies over data, centers over model): pcast each to varying
        # over whichever axes it doesn't already vary on.
        def vary_both(z):
            missing = tuple(
                a for a in (DATA_AXIS, MODEL_AXIS) if a not in jax.typeof(z).vma
            )
            return lax.pcast(z, missing, to="varying") if missing else z

        x, w, centers, c_valid = (
            vary_both(x), vary_both(w), vary_both(centers), vary_both(c_valid)
        )
        # block_rows=None → the kernel's VMEM-aware auto block size (the
        # estimator's chunk_rows targets the XLA scan path and overflows
        # scoped VMEM if forced on the kernel).
        sums, counts, cost = fused_lloyd_stats(x, w, centers, c_valid)
        return _finalize_lloyd(sums, counts, cost, centers, c_valid, cosine)

    # check_vma=False: the pallas_call blocks shard_map's static
    # replication inference (interpret mode discards the vma annotations);
    # the psum/pmax calls above establish the replication the out_specs
    # promise, exactly as in the checked scan path.
    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2), _PT.spec("batch/w", 1),
                      _PT.spec("state/centers", 2), _PT.spec("state/c_valid", 1)),
            out_specs=(_PT.spec("stats/sums", 2), _PT.spec("stats/counts", 1),
                       _PT.spec("scalar/cost"), _PT.spec("scalar/move")),
            check_vma=False,
        )
    )


@lru_cache(maxsize=64)
def _make_train_loop(
    mesh: Mesh,
    n_loc: int,
    k_pad: int,
    d: int,
    chunk_rows: int,
    cosine: bool,
    max_iter: int,
    tol_sq: float,
    precision: str = "highest",
    fuse_stats: bool = False,
):
    """The whole Lloyd loop as ONE device computation: ``lax.while_loop``
    around the shard-mapped step, plus a final stats pass on the converged
    centers.  A Python-side loop syncs the host on ``move`` every
    iteration — one blocking round trip per step, which dominates
    wall-clock on remote-attached chips; this version syncs once per fit.
    Used whenever no per-iteration host hook (checkpoint/on_iteration) is
    installed."""
    step = _make_train_step(
        mesh, n_loc, k_pad, d, chunk_rows, cosine, precision, fuse_stats
    )
    # the returned cost/sizes are always computed exactly: reduced-precision
    # assignment matmuls are a throughput trade for the ITERATIONS, but the
    # reported objective must not inherit bf16 cancellation error (the
    # x²−2xc+c² form cancels catastrophically for tight clusters)
    final_step = (
        step
        if precision == "highest"
        else _make_train_step(mesh, n_loc, k_pad, d, chunk_rows, cosine, "highest")
    )

    def loop(x, w, centers, c_valid):
        def cond(carry):
            it, _, move = carry
            return (it < max_iter) & (move > tol_sq)

        def body(carry):
            it, cen, _ = carry
            new_cen, _, _, move = step(x, w, cen, c_valid)
            return it + 1, new_cen, move

        it, cen, _ = lax.while_loop(
            cond, body, (jnp.int32(0), centers, jnp.float32(jnp.inf))
        )
        # final assignment pass: cost/sizes describe the RETURNED centers
        _, counts, cost, _ = final_step(x, w, cen, c_valid)
        return cen, counts, cost, it

    return jax.jit(loop)


def _kmeans_pp_init(sample: np.ndarray, k: int, seed: int) -> np.ndarray:
    """Greedy k-means++ on a host-side sample: at each step draw
    ``2 + ⌊log k⌋`` D²-weighted candidates and keep the one minimizing the
    resulting potential (the variant sklearn uses; materially better local
    optima than single-draw ++ when clusters are close)."""
    rng = np.random.default_rng(seed)
    n = sample.shape[0]
    if n == 0:
        raise ValueError("cannot initialize k-means on an empty dataset")
    n_trials = 2 + int(np.log(max(k, 2)))
    centers = np.empty((k, sample.shape[1]), dtype=np.float64)
    idx = int(rng.integers(n))
    centers[0] = sample[idx]
    d2 = np.sum((sample - centers[0]) ** 2, axis=1)
    for i in range(1, k):
        total = d2.sum()
        if total <= 0:
            centers[i:] = sample[rng.integers(n, size=k - i)]
            break
        # replace=False requires at least `size` nonzero-probability entries
        # (duplicate-heavy data can leave just one distinct far point)
        cand = rng.choice(
            n,
            size=min(n_trials, n, int(np.count_nonzero(d2))),
            p=d2 / total,
            replace=False,
        )
        # candidate-wise new potentials: (t, n) min against current d2
        cand_d2 = np.minimum(
            d2[None, :],
            ((sample[None, :, :] - sample[cand][:, None, :]) ** 2).sum(axis=2),
        )
        best = int(np.argmin(cand_d2.sum(axis=1)))
        centers[i] = sample[cand[best]]
        d2 = cand_d2[best]
    return centers


def _host_sqdist(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(n, d), (k, d) → (n, k) squared distances — host-side counterpart of
    ops.distance.pairwise_sqdist, shared by every host init path."""
    return (
        (a * a).sum(axis=1)[:, None]
        - 2.0 * a @ b.T
        + (b * b).sum(axis=1)[None, :]
    )


def _lloyd_refine(
    sample: np.ndarray, centers: np.ndarray, iters: int = 10, return_assign: bool = False
):
    """A few host-side Lloyd iterations to polish an init (numpy; used for
    initialization only — the sample is bounded)."""
    centers = centers.copy()
    assign = np.zeros(sample.shape[0], dtype=np.int64)
    for _ in range(iters):
        assign = np.argmin(_host_sqdist(sample, centers), axis=1)
        for j in range(centers.shape[0]):
            m = assign == j
            if m.any():
                centers[j] = sample[m].mean(axis=0)
    if return_assign:
        return centers, np.argmin(_host_sqdist(sample, centers), axis=1)
    return centers


@jax.jit
def _predict_fn(x, centers):
    from ..ops.distance import assign_clusters

    return assign_clusters(x, centers)[0]


@register_model("KMeansModel")
@dataclass
class KMeansModel(ClusteringModel):
    cluster_centers: np.ndarray          # (k, d)
    distance_measure: str = "euclidean"
    training_cost: float = 0.0           # final inertia (Spark summary.trainingCost)
    n_iter: int = 0
    cluster_sizes: np.ndarray | None = None

    @property
    def k(self) -> int:
        return self.cluster_centers.shape[0]

    @property
    def summary(self):
        """Spark's ``KMeansModel.summary`` surface (clusterSizes /
        trainingCost / numIter) — available even after load, since the
        stats persist with the model."""
        from .summary import ClusteringSummary

        return ClusteringSummary(
            k=self.k,
            num_iter=self.n_iter,
            cluster_sizes=(
                np.asarray(self.cluster_sizes)
                if self.cluster_sizes is not None
                else None
            ),
            training_cost=float(self.training_cost),
        )

    def _prep(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.float32)
        return normalize_rows(x) if self.distance_measure == "cosine" else x

    def predict(self, x: jax.Array, use_pallas: bool = False) -> jax.Array:
        check_features(x, self.cluster_centers.shape[1], type(self).__name__)
        centers = jnp.asarray(self.cluster_centers, jnp.float32)
        if use_pallas:
            from ..ops.pallas_kernels import fused_assign

            return fused_assign(self._prep(x), centers)[0]
        xp = self._prep(x)
        if xp.shape[0] * self.k > (1 << 24):
            # big inputs: chunked path — no (n, k) distance matrix in HBM,
            # shard-local under shard_map when x is mesh-sharded
            from ..ops.distance import assign_clusters_chunked

            return assign_clusters_chunked(xp, centers)
        return _predict_fn(xp, centers)

    def compute_cost(self, data, mesh=None) -> float:
        """Sum of squared distances to nearest center (Spark computeCost)."""
        ds = as_device_dataset(data, mesh=mesh)
        x = self._prep(ds.x)
        centers = jnp.asarray(self.cluster_centers, jnp.float32)
        d2 = pairwise_sqdist(x, centers)
        return float(jnp.sum(jnp.min(d2, axis=1) * ds.w))

    def _artifacts(self):
        return (
            "KMeansModel",
            {
                "distance_measure": self.distance_measure,
                "training_cost": self.training_cost,
                "n_iter": self.n_iter,
            },
            {
                "cluster_centers": np.asarray(self.cluster_centers),
                "cluster_sizes": (
                    np.asarray(self.cluster_sizes)
                    if self.cluster_sizes is not None
                    else np.zeros((self.k,))
                ),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            cluster_centers=arrays["cluster_centers"],
            distance_measure=params.get("distance_measure", "euclidean"),
            training_cost=float(params.get("training_cost", 0.0)),
            n_iter=int(params.get("n_iter", 0)),
            cluster_sizes=arrays.get("cluster_sizes"),
        )


@dataclass(frozen=True)
class KMeans(Estimator):
    k: int = 8
    max_iter: int = 20            # Spark default
    tol: float = 1e-4             # Spark default
    seed: int = 0
    init_mode: str = "k-means++"  # or "random"
    distance_measure: str = "euclidean"  # or "cosine"
    # Warm start (lifecycle/ continuous learning): begin Lloyd from these
    # (k, d) centers — a drift-triggered retrain initialized from the
    # serving artifact's centers skips init entirely and converges in the
    # few iterations the distribution actually moved, instead of paying
    # k-means++ plus the full trajectory (the avoidable cold start the
    # Spark-ML perf study charges to refits, arxiv 1612.01437).  The
    # checkpoint signature hashes the warm centers, so resuming against a
    # different warm start raises like any other config mismatch.
    warm_start_centers: np.ndarray | None = None
    # 32768 measured fastest on v5e across a 8k-256k sweep (k=256, d=8)
    chunk_rows: int = 32768
    init_sample_size: int = 65536
    # Assignment-matmul precision (ops/distance.MATMUL_PRECISIONS).  On TPU
    # "highest" emulates f32 with ~6 bf16 MXU passes; "bf16" truncates the
    # operands and accumulates f32 — ONE pass, the native systolic rate.
    # Default stays exact; the bench A/Bs "bf16" against silhouette parity.
    matmul_precision: str = "highest"
    # bf16-mode-only accumulation restructure (x²-free argmin basis +
    # one bf16 one-hot matmul for sums AND counts — see
    # _lloyd_shard_stats).  The sums matmul costs the same 2·k·d
    # FLOPs/row as the distance matmul, so leaving it at f32 caps the
    # bf16 mode's win near 2×; the bench A/Bs this flag on-chip under
    # the same silhouette-parity gate before the headline adopts it.
    fused_stats: bool = False
    # Pallas fused Lloyd kernel (ops/pallas_kernels.py), opt-in; requires
    # model axis 1.  None/False = the XLA scan path, which measures faster
    # at this workload's shapes (kernel docstring has the numbers).
    use_pallas: bool | None = None
    # Mid-training checkpointing (io/fit_checkpoint.py): every
    # checkpoint_every iterations the centroid state is committed so a
    # preempted fit resumes from the last commit instead of restarting.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 5
    weight_col: str | None = None  # Spark's weightCol (3.0+)

    def _init_from_sample(self, valid: np.ndarray) -> np.ndarray:
        """Shared init tail: (sample of valid rows) → (k, d) start centers."""
        if valid.shape[0] == 0:
            raise ValueError("k-means fit on an empty dataset")
        rng = np.random.default_rng(self.seed)
        if self.distance_measure == "cosine":
            norms = np.sqrt(np.maximum((valid * valid).sum(axis=1), 1e-12))
            valid = valid / norms[:, None]
        if self.init_mode == "random":
            pick = rng.choice(valid.shape[0], size=min(self.k, valid.shape[0]), replace=False)
            centers = valid[pick]
            if centers.shape[0] < self.k:  # fewer distinct rows than k
                extra = valid[rng.integers(valid.shape[0], size=self.k - centers.shape[0])]
                centers = np.concatenate([centers, extra])
            return centers
        return _kmeans_pp_init(valid, self.k, self.seed)

    def _warm_centers(self, d: int) -> np.ndarray | None:
        """Validated warm-start centers (cosine fits get unit rows, the
        center space the update step maintains), or None without one."""
        if self.warm_start_centers is None:
            return None
        c = np.asarray(self.warm_start_centers, dtype=np.float32)
        if c.shape != (self.k, d):
            raise ValueError(
                f"warm_start_centers must be ({self.k}, {d}); got "
                f"{tuple(c.shape)}"
            )
        if self.distance_measure == "cosine":
            norms = np.sqrt(np.maximum((c * c).sum(axis=1), 1e-12))
            c = c / norms[:, None]
        return c

    def _warm_fingerprint(self) -> str | None:
        """Warm-start identity for the checkpoint signature."""
        if self.warm_start_centers is None:
            return None
        from ..io.fit_checkpoint import array_fingerprint

        return array_fingerprint(
            np.asarray(self.warm_start_centers, dtype=np.float32)
        )

    def _init_centers(self, ds: DeviceDataset, mesh: Mesh) -> np.ndarray:
        # Host-side init on a bounded sample of valid rows (only the sample
        # crosses the device→host boundary).
        from ..parallel.sharding import sample_valid_rows

        return self._init_from_sample(
            sample_valid_rows(ds, self.init_sample_size, self.seed)
        )

    def _fit_outofcore(self, hd, mesh: Mesh, on_iteration=None) -> KMeansModel:
        """Rows ≫ HBM: stream ``max_device_rows`` blocks through the mesh
        per Lloyd iteration, accumulating the SAME psum'd sufficient
        statistics as the resident step, then apply one centroid update —
        device memory stays bounded by the block size while results match
        the resident path (bit-equal when the sums are exact, e.g.
        integer-valued features; see tests/test_outofcore.py).

        ``checkpoint_dir`` composes with this path (VERDICT r3 next #5):
        block streaming happens INSIDE an iteration, so iteration-boundary
        commits need no extra state — a preempted long out-of-core fit
        (exactly the fits that run longest) resumes from the last commit.
        """
        cosine = self.distance_measure == "cosine"
        d = hd.n_features
        m = mesh.shape[MODEL_AXIS]
        k_pad = padded_slots(self.k, m)

        ckpt = None
        resumed = None
        if self.checkpoint_dir:
            from ..io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "KMeans", "storage": "outofcore",
                "k": self.k, "d": d, "k_pad": k_pad,
                "data": data_fingerprint(hd.x, hd.w),
                "n": hd.n, "seed": self.seed,
                "init_mode": self.init_mode,
                "warm": self._warm_fingerprint(),
                "distance_measure": self.distance_measure, "tol": self.tol,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()

        start_it = 1
        if resumed is not None:
            step0, arrays, _ = resumed
            cen = arrays["centers"].astype(np.float32)
            if cen.shape != (k_pad, d):
                raise ValueError(
                    f"checkpointed centers shape {cen.shape} does not match "
                    f"this mesh's padded layout {(k_pad, d)}"
                )
            start_it = step0 + 1
        else:
            centers0 = self._warm_centers(d)
            if centers0 is None:
                centers0 = self._init_from_sample(
                    hd.sample_rows(self.init_sample_size, self.seed)
                )
            cen = pad_slots(centers0, k_pad)
        c_valid = slot_mask(self.k, k_pad)
        centers = _PT.put("state/centers", cen, mesh)
        c_valid_dev = _PT.put("state/c_valid", c_valid, mesh)

        _, b = hd.block_shape(mesh)
        n_loc = b // mesh.shape[DATA_AXIS]
        step = _make_stats_step(
            mesh, n_loc, k_pad, d, self.chunk_rows, self.matmul_precision,
            self.fused_stats,
        )
        final_stats = (
            step
            if self.matmul_precision == "highest"
            else _make_stats_step(mesh, n_loc, k_pad, d, self.chunk_rows)
        )

        def prep(blk):
            if not cosine:
                return blk.x
            # same rule as the resident path: unit rows, pad rows zeroed
            return _cosine_prep(blk.x, blk.w)

        def epoch(cen_dev, stats_fn=None):
            stats_fn = stats_fn or step
            tot = None
            for blk in hd.blocks(mesh):
                s = stats_fn(prep(blk), blk.w, cen_dev, c_valid_dev)
                tot = s if tot is None else _add_stats(tot, s)
            return tot

        it = start_it - 1
        for it in range(start_it, self.max_iter + 1):
            sums, counts, cost = epoch(centers)
            centers, move = _centroid_update(
                sums, counts, centers, c_valid_dev, cosine
            )
            if ckpt is not None and it % max(self.checkpoint_every, 1) == 0:
                ckpt.save(it, {"centers": np.asarray(jax.device_get(centers))})
            if on_iteration is not None:
                on_iteration(it, float(cost), float(move))
            if float(move) <= self.tol * self.tol:
                break
        # final pass so cost/sizes describe the RETURNED centers (Spark's
        # summary.trainingCost semantics, same as the resident path);
        # exact precision regardless of matmul_precision (see
        # _make_train_loop's final_step note)
        _, counts, cost = epoch(centers, final_stats)
        return KMeansModel(
            cluster_centers=np.asarray(jax.device_get(centers))[: self.k],
            distance_measure=self.distance_measure,
            training_cost=float(cost),
            n_iter=it,
            cluster_sizes=np.asarray(jax.device_get(counts))[: self.k],
        )

    # ---------------------------------------------------- partials protocol
    # Federated rounds reuse the out-of-core machinery verbatim: each silo
    # runs _make_stats_step on its private rows, the coordinator's merged
    # fold reproduces the psum/scan summation (zero-init, ascending), and
    # _centroid_update + a host-f32 mirror of the while_loop's convergence
    # test replay the resident fast path bit-for-bit when silo boundaries
    # sit on scan-chunk boundaries.
    partials_family = "kmeans"

    def partials_max_rounds(self) -> int:
        return self.max_iter

    def partials_final_collect(self) -> bool:
        # cost/sizes must describe the RETURNED centers (Spark's
        # summary.trainingCost) at exact precision — one closing collect
        return True

    def init_partials_state(self, n_features: int, mesh=None):
        from ..federated.partials import FitState

        c0 = self._warm_centers(n_features)
        if c0 is None:
            return None  # coordinator runs the candidate init round
        return FitState(
            family=self.partials_family, version=0,
            params={"centers": c0.astype(np.float32)}, meta={},
        )

    def local_init_stats(self, data, label_col: str | None = None, mesh=None):
        """One silo's init contribution: its local k-means++ candidates
        (each a weighted summary of the silo's geometry — candidate
        CENTERS cross the wire, never rows)."""
        from ..federated.partials import Partials
        from ..parallel.sharding import sample_valid_rows

        mesh = mesh or default_mesh()
        ds = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        sample = sample_valid_rows(ds, self.init_sample_size, self.seed)
        cand = self._init_from_sample(np.asarray(sample, np.float64))
        return Partials(
            family="kmeans.init",
            stats={"candidates": np.asarray(cand, np.float64)},
            n_rows=float(sample.shape[0]),
        )

    def init_state_from_merged(self, merged):
        """Round-0 centers from the concatenated per-silo candidates:
        k-means++ re-seeds over the candidate pool (ascending silo
        order), then a few host Lloyd polish passes — the distributed
        analogue of the pooled sample init."""
        from ..federated.partials import FitState

        cand = np.asarray(merged.stats["candidates"], np.float64)
        centers = _kmeans_pp_init(cand, self.k, self.seed)
        centers = _lloyd_refine(cand, centers, iters=10)
        if self.distance_measure == "cosine":
            norms = np.sqrt(np.maximum((centers * centers).sum(axis=1), 1e-12))
            centers = centers / norms[:, None]
        return FitState(
            family=self.partials_family, version=0,
            params={"centers": centers.astype(np.float32)}, meta={},
        )

    def partial_fit_stats(
        self, data, label_col: str | None = None, mesh=None,
        state=None, final: bool = False,
    ):
        from ..federated.partials import Partials

        if state is None:
            raise ValueError("kmeans partials need the broadcast FitState")
        mesh = mesh or default_mesh()
        ds = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        if self.distance_measure == "cosine":
            x = _cosine_prep(ds.x, ds.w)
        else:
            x = ds.x.astype(jnp.float32)
        m = mesh.shape[MODEL_AXIS]
        k_pad = padded_slots(self.k, m)
        d = x.shape[1]
        cen = pad_slots(
            np.asarray(state.params["centers"], np.float32), k_pad
        )
        centers = _PT.put("state/centers", cen, mesh)
        c_valid = _PT.put("state/c_valid", slot_mask(self.k, k_pad), mesh)
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        if final or self.matmul_precision == "highest":
            # exact precision for the closing stats pass (same rule as
            # _make_train_loop's final_step)
            step = _make_stats_step(mesh, n_loc, k_pad, d, self.chunk_rows)
        else:
            step = _make_stats_step(
                mesh, n_loc, k_pad, d, self.chunk_rows,
                self.matmul_precision, self.fused_stats,
            )
        sums, counts, cost = step(x, ds.w, centers, c_valid)
        # pad slots collect nothing (masked to _BIG) — slice them off so
        # partials are mesh-layout-independent on the wire
        counts_h = np.asarray(jax.device_get(counts))[: self.k]
        return Partials(
            family=self.partials_family,
            stats={
                "sums": np.asarray(jax.device_get(sums))[: self.k],
                "counts": counts_h,
                "cost": np.asarray(jax.device_get(cost)),
            },
            n_rows=float(counts_h.sum()),
            state_version=state.version,
        )

    def apply_partials(self, state, merged):
        from ..federated.partials import FitState

        centers = jnp.asarray(state.params["centers"], jnp.float32)
        c_valid = jnp.ones((centers.shape[0],), jnp.float32)
        new_centers, move = _centroid_update(
            jnp.asarray(merged.stats["sums"]),
            jnp.asarray(merged.stats["counts"]),
            centers, c_valid, self.distance_measure == "cosine",
        )
        version = state.version + 1
        # host-f32 mirror of the device while_loop's `move > tol_sq` exit
        # — same comparison, same f32 operands, same iteration counts
        done = not bool(
            np.float32(jax.device_get(move))
            > np.float32(float(self.tol * self.tol))
        )
        done = done or version >= self.max_iter
        return FitState(
            family=self.partials_family, version=version,
            params={"centers": np.asarray(jax.device_get(new_centers))},
            meta={"cost": float(np.asarray(merged.stats["cost"]))},
        ), done

    def fit_from_partials(self, merged, state=None) -> KMeansModel:
        """Final model from the closing exact-precision collect (``merged``)
        at the converged ``state`` centers."""
        if state is None:
            raise ValueError(
                "kmeans fit_from_partials needs the converged FitState"
            )
        return KMeansModel(
            cluster_centers=np.asarray(
                state.params["centers"], np.float32
            )[: self.k],
            distance_measure=self.distance_measure,
            training_cost=float(np.asarray(merged.stats["cost"])),
            n_iter=state.version,
            cluster_sizes=np.asarray(merged.stats["counts"])[: self.k],
        )

    def fit(
        self, data, label_col: str | None = None, mesh=None, on_iteration=None
    ) -> KMeansModel:
        """``on_iteration(it, cost, move)`` (optional) fires after every
        Lloyd step — progress reporting, early aborts, and the fault-
        injection hooks the checkpoint tests use.

        A :class:`~..parallel.outofcore.HostDataset` input takes the
        out-of-core path: rows stream through the device in
        ``max_device_rows`` blocks (Spark's disk-backed-RDD analogue,
        SURVEY.md §7 hard part 3)."""
        from ..ops.distance import validate_matmul_precision
        from ..parallel.outofcore import HostDataset

        validate_matmul_precision(self.matmul_precision)
        if self.fused_stats and self.matmul_precision != "bf16":
            raise ValueError(
                "fused_stats=True requires matmul_precision='bf16' (it is "
                "the bf16-rate accumulation mode; the exact path keeps f32 "
                "sums)"
            )
        if self.fused_stats and self.use_pallas:
            raise ValueError(
                "fused_stats and use_pallas are mutually exclusive — the "
                "Pallas kernel owns the whole Lloyd step"
            )
        mesh = mesh or default_mesh()
        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh, on_iteration)
        ds = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        x = ds.x.astype(jnp.float32)
        if self.distance_measure == "cosine":
            x = normalize_rows(x) * (ds.w[:, None] > 0)  # 0/1 mask, not the
            # weight value: fractional sample weights must not rescale the
            # unit vectors (they enter via the weighted stats instead)

        m = mesh.shape[MODEL_AXIS]
        k_pad = padded_slots(self.k, m)
        d = x.shape[1]

        ckpt = None
        resumed = None
        if self.checkpoint_dir:
            from ..io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "KMeans", "k": self.k, "d": d,
                "k_pad": k_pad,  # depends on the mesh's model axis
                "data": data_fingerprint(x, ds.w),
                "n_padded": ds.n_padded, "seed": self.seed,
                "init_mode": self.init_mode,
                "warm": self._warm_fingerprint(),
                "distance_measure": self.distance_measure, "tol": self.tol,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()

        start_it = 1
        if resumed is not None:
            step0, arrays, _ = resumed
            cen = arrays["centers"].astype(np.float32)
            if cen.shape != (k_pad, d):
                raise ValueError(
                    f"checkpointed centers shape {cen.shape} does not match "
                    f"this mesh's padded layout {(k_pad, d)}"
                )
            start_it = step0 + 1
        else:
            centers0 = self._warm_centers(d)
            if centers0 is None:
                centers0 = self._init_centers(
                    DeviceDataset(x, ds.y, ds.w), mesh
                )
            cen = pad_slots(centers0, k_pad)
        c_valid = slot_mask(self.k, k_pad)
        centers = _PT.put("state/centers", cen, mesh)
        c_valid_dev = _PT.put("state/c_valid", c_valid, mesh)

        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        cosine = self.distance_measure == "cosine"
        if self.use_pallas is not None:
            fused = self.use_pallas
            if fused and m != 1:
                raise ValueError(
                    "use_pallas=True requires a model axis of 1 (the fused "
                    f"kernel owns the whole centroid set); got model={m}"
                )
        else:
            # auto = XLA scan path: measured faster than the Pallas kernel
            # at this workload's shapes (see ops/pallas_kernels.py docstring
            # for the numbers); the kernel stays opt-in.
            fused = False
        if fused:
            step = _make_train_step_fused(mesh, k_pad, cosine)
        else:
            step = _make_train_step(
                mesh, n_loc, k_pad, d, self.chunk_rows, cosine,
                self.matmul_precision, self.fused_stats,
            )

        if ckpt is None and on_iteration is None and not fused:
            # Fast path: the whole Lloyd loop is one device computation
            # (single host sync per fit instead of one per iteration).
            loop = _make_train_loop(
                mesh, n_loc, k_pad, d, self.chunk_rows, cosine,
                self.max_iter - (start_it - 1), float(self.tol * self.tol),
                self.matmul_precision, self.fused_stats,
            )
            centers, counts, cost_dev, it_dev = loop(x, ds.w, centers, c_valid_dev)
            it = (start_it - 1) + int(it_dev)
        else:
            it = start_it - 1
            for it in range(start_it, self.max_iter + 1):
                centers, _, cost_it, move = step(x, ds.w, centers, c_valid_dev)
                if ckpt is not None and it % max(self.checkpoint_every, 1) == 0:
                    ckpt.save(it, {"centers": np.asarray(jax.device_get(centers))})
                if on_iteration is not None:
                    on_iteration(it, float(cost_it), float(move))
                if float(move) <= self.tol * self.tol:
                    break
            # One extra assignment pass so cost/sizes describe the RETURNED
            # centers, not the pre-update ones (Spark's summary.trainingCost
            # is the final model's cost) — always at exact precision (see
            # _make_train_loop's final_step note).
            if fused or self.matmul_precision == "highest":
                final_step = step
            else:
                final_step = _make_train_step(
                    mesh, n_loc, k_pad, d, self.chunk_rows, cosine, "highest"
                )
            _, counts, cost_dev, _ = final_step(x, ds.w, centers, c_valid_dev)
        final = np.asarray(jax.device_get(centers))[: self.k]
        sizes = np.asarray(jax.device_get(counts))[: self.k]
        return KMeansModel(
            cluster_centers=final,
            distance_measure=self.distance_measure,
            training_cost=float(cost_dev),
            n_iter=it,
            cluster_sizes=sizes,
        )
