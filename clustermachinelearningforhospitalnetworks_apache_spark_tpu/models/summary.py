"""Training summaries — ``model.summary`` parity with ``pyspark.ml``.

Spark attaches a TrainingSummary to every freshly fitted model
(``lr_model.summary.rootMeanSquaredError`` etc.; loaded models have
``hasSummary == False`` and raise).  Here summaries are **lazy**: fit
stores only references (model + the already-device-resident training
dataset); every metric is computed on first access with one jit'd
reduction over the mesh and cached — so fits pay nothing for summaries
they never read (the BASELINE benches stay pure), while a migrating Spark
user keeps the exact read-side surface.

Memory note: the summary keeps the training ``DeviceDataset`` alive (and
therefore resident in device memory) for the model's lifetime.  That's
free when the caller holds the dataset anyway; when retaining many fitted
models, call ``model.release_summary()`` (or drop the model) to unpin the
data — saving a model never persists the summary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property, partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def summary_unavailable(model_name: str):
    return RuntimeError(
        f"{model_name} has no training summary — summaries exist only on "
        "freshly fitted models (Spark parity: hasSummary is False after "
        "load_model)"
    )


@partial(jax.jit, static_argnames=("fit_intercept",))
def _xtwx_gram(x: jax.Array, w: jax.Array, fit_intercept: bool):
    """X'WX (intercept column appended only when the model fitted one) —
    the device reduction; the tiny (p, p) inverse runs on host in float64
    so collinearity can be DETECTED rather than silently producing
    garbage f32 standard errors."""
    if fit_intercept:
        ones = jnp.ones((x.shape[0], 1), x.dtype)
        x = jnp.concatenate([x, ones], axis=1)
    return (x * w[:, None]).T @ x


@dataclass
class LinearRegressionTrainingSummary:
    """``pyspark.ml.regression.LinearRegressionTrainingSummary`` surface."""

    _model: Any = field(repr=False)
    _ds: Any = field(repr=False)          # DeviceDataset the fit consumed
    _reg_param: float = 0.0
    _elastic_net_param: float = 0.0
    _fit_intercept: bool = True

    @cached_property
    def predictions(self):
        from .base import PredictionResult

        return PredictionResult(
            prediction=self._model.predict(self._ds.x),
            label=self._ds.y,
            weight=self._ds.w,
        )

    @cached_property
    def residuals(self) -> np.ndarray:
        """Per-row label − prediction, valid rows only (pad rows dropped —
        statistics computed on this array see exactly ``num_instances``
        entries, like Spark's residuals column)."""
        p = self.predictions
        res = np.asarray(jax.device_get(p.prediction - p.label)) * -1.0
        w = np.asarray(jax.device_get(p.weight))
        return res[w > 0]

    @cached_property
    def _reg_metrics(self) -> dict[str, float]:
        # ONE device pass for the sufficient statistics; every metric is a
        # host-side finish on the same sums dict
        from ..evaluation.regression import RegressionEvaluator, _reg_sums

        p = self.predictions
        sums = jax.device_get(_reg_sums(p.prediction, p.label, p.weight))
        return {
            m: float(RegressionEvaluator(m)._finish(sums))
            for m in ("rmse", "mse", "mae", "r2", "var")
        }

    @property
    def root_mean_squared_error(self) -> float:
        return self._reg_metrics["rmse"]

    @property
    def mean_squared_error(self) -> float:
        return self._reg_metrics["mse"]

    @property
    def mean_absolute_error(self) -> float:
        return self._reg_metrics["mae"]

    @property
    def r2(self) -> float:
        return self._reg_metrics["r2"]

    @property
    def explained_variance(self) -> float:
        return self._reg_metrics["var"]

    @property
    def r2adj(self) -> float:
        """Spark's ``r2adj``: 1 − (1−r²)(n−1)/(n−p−1) with p the feature
        count (intercept excluded, Spark's convention)."""
        n = self.num_instances
        p = self._model.coefficients.shape[0]
        denom = n - p - (1 if self._fit_intercept else 0)
        if denom <= 0:
            return float("nan")
        return 1.0 - (1.0 - self.r2) * (n - (1 if self._fit_intercept else 0)) / denom

    @cached_property
    def num_instances(self) -> int:
        """Count of (w>0) rows — Spark's numInstances is a ROW count, not
        the weight sum (they differ under fractional weightCol weights)."""
        return int(np.sum(np.asarray(jax.device_get(self._ds.w)) > 0))

    @cached_property
    def weight_sum(self) -> float:
        """Σw over valid rows (the quantity ``num_instances`` previously
        conflated; exposed separately for weighted-fit diagnostics)."""
        return float(np.asarray(jax.device_get(self._ds.count())))

    @property
    def degrees_of_freedom(self) -> int:
        p = self._model.coefficients.shape[0] + (1 if self._fit_intercept else 0)
        return max(self.num_instances - p, 0)

    # -- normal-solver-only inference statistics (Spark raises on the
    #    regularized path the same way) -------------------------------
    def _require_unregularized(self) -> None:
        if self._reg_param != 0.0:
            raise RuntimeError(
                "coefficient standard errors / t / p values are only "
                "available for an unregularized fit (reg_param=0), "
                "matching Spark's normal-solver restriction"
            )

    @cached_property
    def coefficient_standard_errors(self) -> np.ndarray:
        """Std errors for (coefficients..., intercept if fitted), Spark's
        ordering.  Raises on a (near-)collinear design — e.g. the
        dummy-variable trap of OneHotEncoder(drop_last=False) plus an
        intercept — instead of returning f32-inverse garbage (Spark's
        normal solver likewise errors on singular systems)."""
        self._require_unregularized()
        g = np.asarray(
            jax.device_get(
                _xtwx_gram(self._ds.x.astype(jnp.float32), self._ds.w,
                           self._fit_intercept)
            ),
            dtype=np.float64,
        )
        cond = np.linalg.cond(g)
        if not np.isfinite(cond) or cond > 1e7:  # f32-data Gram limit
            raise RuntimeError(
                "design matrix is (near-)collinear (Gram condition number "
                f"{cond:.2e}); standard errors are undefined — drop a "
                "redundant column (e.g. OneHotEncoder(drop_last=True))"
            )
        diag = np.diag(np.linalg.inv(g))
        dof = max(self.degrees_of_freedom, 1)
        # RSS = weighted mse × Σw (NOT × row count — they differ under
        # fractional weightCol weights); dof stays a row count
        sigma2 = self.mean_squared_error * self.weight_sum / dof
        return np.sqrt(np.maximum(diag * sigma2, 0.0))

    @cached_property
    def t_values(self) -> np.ndarray:
        self._require_unregularized()
        beta = np.asarray(self._model.coefficients, dtype=np.float64)
        if self._fit_intercept:
            beta = np.r_[beta, float(np.asarray(self._model.intercept))]
        return beta / self.coefficient_standard_errors

    @cached_property
    def p_values(self) -> np.ndarray:
        self._require_unregularized()
        try:
            from scipy import stats

            return 2.0 * stats.t.sf(np.abs(self.t_values), self.degrees_of_freedom)
        except ImportError:  # normal approximation fallback
            from math import erfc, sqrt

            return np.array(
                [erfc(abs(t) / sqrt(2.0)) for t in self.t_values]
            )


class _ConfusionMetricsMixin:
    """Confusion-matrix-derived metrics shared by the binary and
    multiclass logistic training summaries (Spark's
    ``LogisticRegressionSummary`` base surface).  Subclasses set
    ``_model``/``_ds`` dataclass fields and ``_num_classes``."""

    @property
    def _num_classes(self) -> int:
        return 2

    @cached_property
    def predictions(self):
        from .base import PredictionResult

        return PredictionResult(
            prediction=self._model.predict(self._ds.x),
            label=self._ds.y,
            weight=self._ds.w,
        )

    @cached_property
    def accuracy(self) -> float:
        from ..evaluation.classification import MulticlassClassificationEvaluator

        p = self.predictions
        return float(
            MulticlassClassificationEvaluator(
                "accuracy", num_classes=self._num_classes
            ).evaluate(p.prediction, p.label, p.weight)
        )

    @cached_property
    def _confusion(self) -> np.ndarray:
        from ..evaluation.classification import MulticlassClassificationEvaluator

        ev = MulticlassClassificationEvaluator(num_classes=self._num_classes)
        p = self.predictions
        return ev.confusion_matrix(p.prediction, p.label, p.weight)

    def _by_label(self, metric: str) -> np.ndarray:
        cm = self._confusion
        support = cm.sum(axis=1)
        pred_ct = cm.sum(axis=0)
        tp = np.diag(cm)
        with np.errstate(invalid="ignore", divide="ignore"):
            prec = np.where(pred_ct > 0, tp / pred_ct, 0.0)
            rec = np.where(support > 0, tp / support, 0.0)
            f1 = np.where(prec + rec > 0, 2 * prec * rec / (prec + rec), 0.0)
        return {"precision": prec, "recall": rec, "f1": f1}[metric]

    @property
    def precision_by_label(self) -> np.ndarray:
        return self._by_label("precision")

    @property
    def recall_by_label(self) -> np.ndarray:
        return self._by_label("recall")

    @property
    def f_measure_by_label(self) -> np.ndarray:
        return self._by_label("f1")

    # -- support-weighted aggregates (Spark's weighted* columns) — one
    #    copy of the math: delegate to MulticlassClassificationEvaluator
    #    on the cached predictions -------------------------------------
    def _weighted(self, metric: str) -> float:
        from ..evaluation.classification import MulticlassClassificationEvaluator

        return float(
            MulticlassClassificationEvaluator(
                metric, num_classes=self._num_classes
            ).evaluate(self.predictions)
        )

    @property
    def _support_frac(self) -> np.ndarray:
        support = self._confusion.sum(axis=1)
        return support / max(support.sum(), 1e-30)

    @property
    def weighted_precision(self) -> float:
        return self._weighted("weightedPrecision")

    @property
    def weighted_recall(self) -> float:
        return self._weighted("weightedRecall")

    @property
    def weighted_f_measure(self) -> float:
        return self._weighted("f1")

    @property
    def weighted_true_positive_rate(self) -> float:
        return self.weighted_recall  # Spark aliases TPR = recall

    @property
    def weighted_false_positive_rate(self) -> float:
        return float(self._support_frac @ self.false_positive_rate_by_label)

    @property
    def true_positive_rate_by_label(self) -> np.ndarray:
        return self._by_label("recall")  # Spark: TPR_l = recall_l

    @property
    def false_positive_rate_by_label(self) -> np.ndarray:
        cm = self._confusion
        support = cm.sum(axis=1)
        total = max(support.sum(), 1e-30)
        fp = cm.sum(axis=0) - np.diag(cm)
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(total - support > 0, fp / (total - support), 0.0)


@dataclass
class MulticlassLogisticRegressionTrainingSummary(_ConfusionMetricsMixin):
    """``pyspark.ml.classification.LogisticRegressionTrainingSummary``
    for the multinomial family: accuracy, per-label P/R/F/TPR/FPR, and
    the support-weighted aggregates (no ROC — Spark likewise reserves the
    curve surface for the binary summary)."""

    _model: Any = field(repr=False)
    _ds: Any = field(repr=False)

    @property
    def _num_classes(self) -> int:
        return self._model.num_classes

    @property
    def num_classes(self) -> int:
        return self._model.num_classes


@dataclass
class BinaryLogisticRegressionTrainingSummary(_ConfusionMetricsMixin):
    """``pyspark.ml.classification.BinaryLogisticRegressionSummary``:
    the confusion-derived base surface plus AUC and the threshold
    curves."""

    _model: Any = field(repr=False)
    _ds: Any = field(repr=False)

    @cached_property
    def _scores(self):
        return self._model.predict_proba(self._ds.x)

    @cached_property
    def area_under_roc(self) -> float:
        from ..evaluation.binary import BinaryClassificationEvaluator

        return float(
            BinaryClassificationEvaluator("areaUnderROC").evaluate(
                self._scores, self._ds.y, self._ds.w
            )
        )

    @cached_property
    def area_under_pr(self) -> float:
        from ..evaluation.binary import BinaryClassificationEvaluator

        return float(
            BinaryClassificationEvaluator("areaUnderPR").evaluate(
                self._scores, self._ds.y, self._ds.w
            )
        )

    # -- threshold curves (Spark's roc / pr / *ByThreshold DataFrames,
    #    returned as (m, 2) arrays of curve points) --------------------
    @cached_property
    def _curves(self) -> dict:
        from ..evaluation.binary import binary_curves

        return binary_curves(self._scores, self._ds.y, self._ds.w)

    @cached_property
    def roc(self) -> np.ndarray:
        """(m, 2) [FPR, TPR] points anchored at (0,0) and (1,1) —
        Spark's ``summary.roc`` DataFrame as an array."""
        c = self._curves
        fpr = c["fp"] / max(c["total_neg"], 1e-30)
        tpr = c["tp"] / max(c["total_pos"], 1e-30)
        return np.column_stack(
            [np.r_[0.0, fpr, 1.0], np.r_[0.0, tpr, 1.0]]
        )

    @cached_property
    def pr(self) -> np.ndarray:
        """(m, 2) [recall, precision] points, anchored at recall=0 with
        the highest-threshold block's precision (Spark's first point)."""
        c = self._curves
        recall = c["tp"] / max(c["total_pos"], 1e-30)
        with np.errstate(invalid="ignore", divide="ignore"):
            precision = c["tp"] / np.maximum(c["tp"] + c["fp"], 1e-30)
        return np.column_stack(
            [np.r_[0.0, recall], np.r_[precision[:1], precision]]
        )

    def _by_threshold(self, kind: str, beta: float = 1.0) -> np.ndarray:
        c = self._curves
        with np.errstate(invalid="ignore", divide="ignore"):
            precision = c["tp"] / np.maximum(c["tp"] + c["fp"], 1e-30)
            recall = c["tp"] / max(c["total_pos"], 1e-30)
            if kind == "precision":
                val = precision
            elif kind == "recall":
                val = recall
            else:
                b2 = beta * beta
                val = np.where(
                    precision + recall > 0,
                    (1 + b2) * precision * recall
                    / np.maximum(b2 * precision + recall, 1e-30),
                    0.0,
                )
        return np.column_stack([c["thresholds"], val])

    def precision_by_threshold(self) -> np.ndarray:
        """(m, 2) [threshold, precision] over distinct score thresholds."""
        return self._by_threshold("precision")

    def recall_by_threshold(self) -> np.ndarray:
        return self._by_threshold("recall")

    def f_measure_by_threshold(self, beta: float = 1.0) -> np.ndarray:
        return self._by_threshold("f", beta)

    @property
    def max_f_measure_threshold(self) -> float:
        """Threshold maximizing F1 — Spark exposes the curve and leaves
        the argmax to the user; this is the one-liner everyone writes."""
        curve = self.f_measure_by_threshold()
        return float(curve[np.argmax(curve[:, 1]), 0])


@dataclass(frozen=True)
class ClusteringSummary:
    """``pyspark.ml.clustering.*Summary`` surface (KMeans / Bisecting /
    GaussianMixture): sizes + objective, already computed by the fit."""

    k: int
    num_iter: int
    cluster_sizes: np.ndarray | None = None
    training_cost: float | None = None      # KMeans / Bisecting
    log_likelihood: float | None = None     # GaussianMixture
