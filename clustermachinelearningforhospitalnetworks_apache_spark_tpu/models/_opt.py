"""Shared jitted optimizer harnesses for the L-BFGS estimator families
(MultilayerPerceptronClassifier, AFTSurvivalRegression).

One copy of the ``optax.lbfgs`` loop so convergence semantics can't
silently diverge between families: runs as a ``lax.while_loop`` with the
Spark-style stop ``|loss_t − loss_{t−1}| ≤ tol`` (or ``max_iter``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lbfgs_minimize(loss_fn, params, max_iter: int, tol):
    """Minimize ``loss_fn`` over the ``params`` pytree with optax L-BFGS.

    → (params, final_loss, n_iter).  Traceable (call under jit); the stop
    condition is the relative loss plateau |Δloss| ≤ tol·max(|loss|, 1).
    """
    import optax

    opt = optax.lbfgs()
    state = opt.init(params)
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def cond(carry):
        _, _, prev, loss, it = carry
        delta = jnp.abs(prev - loss)
        return (it < max_iter) & (
            delta > tol * jnp.maximum(jnp.abs(loss), 1.0)
        )

    def body(carry):
        p, st, _, prev, it = carry
        loss, grad = value_and_grad(p, state=st)
        updates, st = opt.update(
            grad, st, p, value=loss, grad=grad, value_fn=loss_fn
        )
        p = optax.apply_updates(p, updates)
        # the zoom linesearch already evaluated the accepted point —
        # reuse its cached value instead of paying an extra forward pass
        new_loss = optax.tree_utils.tree_get(st, "value")
        return (p, st, loss, new_loss, it + 1)

    p, _, _, loss, it = lax.while_loop(
        cond,
        body,
        (params, state, jnp.float32(jnp.inf), loss_fn(params), jnp.int32(0)),
    )
    return p, loss, it
