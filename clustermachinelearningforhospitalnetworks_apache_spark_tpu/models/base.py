"""Estimator / Model protocol.

Mirrors the ``pyspark.ml`` Estimator→Model contract the reference leans on
(``.fit(train)`` then ``model.transform(test)``, ``mllearnforhospitalnetwork
.py:146-158,183-190``), reshaped for the TPU substrate: estimators consume a
row-sharded :class:`~..parallel.sharding.DeviceDataset` (or anything
coercible to one) and models predict on device, returning a
:class:`PredictionResult` whose arrays stay sharded until explicitly
collected — so fit→transform→evaluate never leaves the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..features.assembler import AssembledTable
from ..parallel.sharding import DeviceDataset, device_dataset, unpad


def as_device_dataset(
    data: Any, label_col: str | None = None, mesh=None, weight_col: str | None = None
) -> DeviceDataset:
    """Coerce (DeviceDataset | AssembledTable | (X, y[, w]) | X) to a
    sharded dataset.  ``weight_col`` (Spark's ``weightCol``) names a table
    column of non-negative sample weights; a 3-tuple passes them directly."""
    from ..parallel.federation import FederatedDataset

    if isinstance(data, DeviceDataset):
        return data  # weights (weight_col or explicit) are already baked in
    if isinstance(data, FederatedDataset):
        return data.data
    if isinstance(data, AssembledTable):
        return data.to_device(label_col=label_col, weight_col=weight_col, mesh=mesh)
    if weight_col is not None:
        # a named column can only be resolved against a table — silently
        # fitting unweighted would betray an explicitly configured weightCol
        raise ValueError(
            f"weight_col={weight_col!r} needs a table input to resolve the "
            f"column; got {type(data).__name__} — pass an AssembledTable, "
            "an (x, y, weights) tuple, or a pre-weighted DeviceDataset"
        )
    if isinstance(data, tuple) and len(data) == 3:
        return device_dataset(
            np.asarray(data[0]), np.asarray(data[1]), mesh=mesh,
            weights=np.asarray(data[2]),
        )
    if isinstance(data, tuple) and len(data) == 2:
        return device_dataset(np.asarray(data[0]), np.asarray(data[1]), mesh=mesh)
    return device_dataset(np.asarray(data), None, mesh=mesh)


@jax.tree_util.register_dataclass
@dataclass
class PredictionResult:
    """Sharded predictions + labels + validity weights (pad rows w=0)."""

    prediction: jax.Array
    label: jax.Array
    weight: jax.Array

    def to_numpy(self, n: int | None = None) -> tuple[np.ndarray, np.ndarray]:
        pred = np.asarray(jax.device_get(self.prediction))
        lab = np.asarray(jax.device_get(self.label))
        if n is None:
            valid = np.asarray(jax.device_get(self.weight)) > 0
            return pred[valid], lab[valid]
        return pred[:n], lab[:n]


class Estimator:
    """Base: subclasses implement ``fit(dataset) -> Model``."""

    def fit(self, data: Any, label_col: str | None = None, mesh=None):
        raise NotImplementedError


def check_features(x, expected: int, model_name: str) -> None:
    """Friendly feature-width validation at the model's front door — a
    mismatched matrix otherwise surfaces as a raw XLA dot-dimension
    TypeError deep inside jit."""
    got = x.shape[-1] if getattr(x, "ndim", 0) >= 2 else None
    if got is not None and got != expected:
        raise ValueError(
            f"{model_name} was trained on {expected} features but the input "
            f"has {got} (shape {tuple(x.shape)}); assemble the same feature "
            "columns used at fit time"
        )


class Model:
    """Base: subclasses implement ``predict(x) -> jax.Array`` on device."""

    def predict(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    # serving contract ---------------------------------------------------
    def serving_predict_fn(self):
        """Stable raw-array predict entry point for the ``serve/`` layer.

        Returns a PURE function ``(batch, d) array -> (batch,)
        predictions``: traceable under ``jax.jit``, deterministic in its
        parameters (closed over, never mutated), and row-local — row i of
        the output depends only on row i of the input, so the server may
        pad batches with junk rows and slice the real rows back out.
        Defaults to ``self.predict``; families whose predict takes extra
        arguments or runs host-side logic override this with a serving-
        safe closure."""
        return self.predict

    @property
    def num_features(self) -> int | None:
        """Feature width the model was trained on, when recoverable from
        its parameters — the serve registry uses it to size shape-bucket
        executables without a probe row.  ``None`` when undeterminable."""
        for attr, axis in (
            ("coefficients", -1),   # linear family; (d,) or (k, d)
            ("cluster_centers", 1),  # kmeans / bisecting
            ("means", 1),            # gmm
            ("theta", 1),            # naive bayes
            ("feature_importances", -1),  # tree ensembles (what
            # decision_tree.check_features sizes against)
        ):
            v = getattr(self, attr, None)
            if v is not None and getattr(v, "ndim", 0) >= 1:
                return int(np.asarray(v).shape[axis])
        return None

    def transform(self, data: Any, label_col: str | None = None, mesh=None) -> PredictionResult:
        ds = as_device_dataset(data, label_col=label_col, mesh=mesh)
        pred = self.predict(ds.x)
        return PredictionResult(prediction=pred, label=ds.y, weight=ds.w)

    def predict_numpy(self, x: np.ndarray) -> np.ndarray:
        ds = as_device_dataset(np.asarray(x))
        n = np.asarray(x).shape[0]
        return unpad(self.predict(ds.x), n)

    # persistence sugar -------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        from ..io.model_io import save_model

        name, meta, arrays = self._artifacts()
        save_model(path, name, meta, arrays, overwrite=overwrite)

    def write(self) -> "_Writer":
        """Spark-style ``model.write().overwrite().save(path)`` chain."""
        return _Writer(self)

    def _artifacts(self) -> tuple[str, dict, dict[str, np.ndarray]]:
        raise NotImplementedError


class ClusteringModel(Model):
    """Model base for the clustering family, adding Spark's DataFrame-style
    ``transform``: an :class:`AssembledTable` comes back as its source
    :class:`Table` with a ``prediction`` column appended (and, for
    probabilistic models, a ``probability`` column holding the assigned
    component's posterior — Spark's ``probability`` is the full K-vector,
    which a columnar table carries via :meth:`predict_proba` instead).
    This is the composition pattern the reference applies to supervised
    models (``model.transform(test_data)``, ``mllearnforhospitalnetwork
    .py:148,157``), extended to the clustering estimators so they plug
    into the same Table pipeline.

    Non-table inputs keep the base behavior (sharded
    :class:`PredictionResult`)."""

    def transform(self, data: Any, label_col: str | None = None, mesh=None):
        if isinstance(data, AssembledTable):
            n = len(data)
            ds = as_device_dataset(data.features, mesh=mesh)
            assigned = None
            if hasattr(self, "predict_assigned"):
                # fused chunked argmax+posterior — no (n, k) tensor in HBM,
                # only two length-n vectors cross to host
                pred_d, assigned = self.predict_assigned(ds.x)
            elif hasattr(self, "predict_proba"):
                p = self.predict_proba(ds.x)
                pred_d = jnp.argmax(p, axis=1)
                assigned = jnp.take_along_axis(p, pred_d[:, None], axis=1)[:, 0]
            else:
                pred_d = self.predict(ds.x)
            pred = np.asarray(unpad(pred_d, n)).astype(np.int32)
            out = data.table.with_column("prediction", pred, dtype="int")
            if assigned is not None:
                out = out.with_column(
                    "probability", np.asarray(unpad(assigned, n)), dtype="float"
                )
            return out
        return super().transform(data, label_col=label_col, mesh=mesh)


@dataclass
class _Writer:
    model: Model
    _overwrite: bool = False

    def overwrite(self) -> "_Writer":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        self.model.save(path, overwrite=self._overwrite)
