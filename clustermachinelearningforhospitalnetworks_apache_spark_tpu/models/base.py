"""Estimator / Model protocol.

Mirrors the ``pyspark.ml`` Estimator→Model contract the reference leans on
(``.fit(train)`` then ``model.transform(test)``, ``mllearnforhospitalnetwork
.py:146-158,183-190``), reshaped for the TPU substrate: estimators consume a
row-sharded :class:`~..parallel.sharding.DeviceDataset` (or anything
coercible to one) and models predict on device, returning a
:class:`PredictionResult` whose arrays stay sharded until explicitly
collected — so fit→transform→evaluate never leaves the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..features.assembler import AssembledTable
from ..parallel.sharding import DeviceDataset, device_dataset, unpad


def as_device_dataset(
    data: Any, label_col: str | None = None, mesh=None, weight_col: str | None = None
) -> DeviceDataset:
    """Coerce (DeviceDataset | AssembledTable | (X, y[, w]) | X) to a
    sharded dataset.  ``weight_col`` (Spark's ``weightCol``) names a table
    column of non-negative sample weights; a 3-tuple passes them directly."""
    from ..parallel.federation import FederatedDataset

    if isinstance(data, DeviceDataset):
        return data  # weights (weight_col or explicit) are already baked in
    if isinstance(data, FederatedDataset):
        return data.data
    if isinstance(data, AssembledTable):
        return data.to_device(label_col=label_col, weight_col=weight_col, mesh=mesh)
    if weight_col is not None:
        # a named column can only be resolved against a table — silently
        # fitting unweighted would betray an explicitly configured weightCol
        raise ValueError(
            f"weight_col={weight_col!r} needs a table input to resolve the "
            f"column; got {type(data).__name__} — pass an AssembledTable, "
            "an (x, y, weights) tuple, or a pre-weighted DeviceDataset"
        )
    if isinstance(data, tuple) and len(data) == 3:
        return device_dataset(
            np.asarray(data[0]), np.asarray(data[1]), mesh=mesh,
            weights=np.asarray(data[2]),
        )
    if isinstance(data, tuple) and len(data) == 2:
        return device_dataset(np.asarray(data[0]), np.asarray(data[1]), mesh=mesh)
    return device_dataset(np.asarray(data), None, mesh=mesh)


@jax.tree_util.register_dataclass
@dataclass
class PredictionResult:
    """Sharded predictions + labels + validity weights (pad rows w=0)."""

    prediction: jax.Array
    label: jax.Array
    weight: jax.Array

    def to_numpy(self, n: int | None = None) -> tuple[np.ndarray, np.ndarray]:
        pred = np.asarray(jax.device_get(self.prediction))
        lab = np.asarray(jax.device_get(self.label))
        if n is None:
            valid = np.asarray(jax.device_get(self.weight)) > 0
            return pred[valid], lab[valid]
        return pred[:n], lab[:n]


class Estimator:
    """Base: subclasses implement ``fit(dataset) -> Model``.

    Estimators that fit from mergeable sufficient statistics additionally
    implement the **partials protocol** (ISSUE 16): set
    :attr:`partials_family` and override :meth:`partial_fit_stats` /
    :meth:`fit_from_partials` (single-round families) plus the state
    hooks (iterative families).  The contract the federated coordinator
    holds them to: ``fit(pooled)`` and ``fit_from_partials(merge(
    per-silo partials))`` are **bit-identical** when silo boundaries
    coincide with the estimator's own scan-chunk boundaries, because
    ``federated.partials.merge_partials`` reproduces the chunk fold's
    zero-init ascending summation exactly.
    """

    #: partials-family name (``federated.partials`` registry) or ``None``
    #: when the estimator cannot fit from merged statistics.
    partials_family: str | None = None

    def fit(self, data: Any, label_col: str | None = None, mesh=None):
        raise NotImplementedError

    # ---------------------------------------------------- partials protocol
    def supports_partials(self) -> bool:
        return self.partials_family is not None

    def _no_partials(self):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the mergeable-"
            "partials protocol (partials_family="
            f"{self.partials_family!r})"
        )

    def init_partials_state(self, n_features: int, mesh=None):
        """Round-0 ``FitState`` when it needs no data, else ``None`` —
        the coordinator then runs a data-dependent init round
        (:meth:`local_init_stats` → :meth:`init_state_from_merged`)."""
        self._no_partials()

    def local_init_stats(self, data: Any, label_col: str | None = None, mesh=None):
        """One silo's init-round contribution (e.g. k-means++ candidate
        centers from the local sample) as a ``Partials``."""
        self._no_partials()

    def init_state_from_merged(self, merged):
        """Build the round-0 ``FitState`` from merged init partials."""
        self._no_partials()

    def partial_fit_stats(
        self, data: Any, label_col: str | None = None, mesh=None,
        state=None, final: bool = False,
    ):
        """One silo's sufficient statistics for the next update, computed
        against ``state`` (ignored by single-round families).  ``final``
        marks the exact-precision closing collect of families that
        require one (:meth:`partials_final_collect`)."""
        self._no_partials()

    def apply_partials(self, state, merged):
        """Fold merged statistics into ``state`` → ``(state', done)``.
        ``done`` mirrors the family's own device convergence test
        bit-for-bit (host float32 arithmetic)."""
        self._no_partials()

    def fit_from_partials(self, merged, state=None):
        """Build the final Model from merged statistics (and, for
        iterative families, the converged ``state``)."""
        self._no_partials()

    def partials_max_rounds(self) -> int:
        """Round budget: 1 for single-shot families, ``max_iter`` for
        iterative ones."""
        return 1

    def partials_final_collect(self) -> bool:
        """True when the family needs one extra exact-precision collect
        after convergence (k-means' final stats pass)."""
        return False


def check_features(x, expected: int, model_name: str) -> None:
    """Friendly feature-width validation at the model's front door — a
    mismatched matrix otherwise surfaces as a raw XLA dot-dimension
    TypeError deep inside jit."""
    got = x.shape[-1] if getattr(x, "ndim", 0) >= 2 else None
    if got is not None and got != expected:
        raise ValueError(
            f"{model_name} was trained on {expected} features but the input "
            f"has {got} (shape {tuple(x.shape)}); assemble the same feature "
            "columns used at fit time"
        )


class Model:
    """Base: subclasses implement ``predict(x) -> jax.Array`` on device."""

    def predict(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    # serving contract ---------------------------------------------------
    def serving_predict_fn(self):
        """Stable raw-array predict entry point for the ``serve/`` layer.

        Returns a PURE function ``(batch, d) array -> (batch,)
        predictions``: traceable under ``jax.jit``, deterministic in its
        parameters (closed over, never mutated), and row-local — row i of
        the output depends only on row i of the input, so the server may
        pad batches with junk rows and slice the real rows back out.
        Defaults to ``self.predict``; families whose predict takes extra
        arguments or runs host-side logic override this with a serving-
        safe closure."""
        return self.predict

    @property
    def num_features(self) -> int | None:
        """Feature width the model was trained on, when recoverable from
        its parameters — the serve registry uses it to size shape-bucket
        executables without a probe row.  ``None`` when undeterminable."""
        for attr, axis in (
            ("coefficients", -1),   # linear family; (d,) or (k, d)
            ("cluster_centers", 1),  # kmeans / bisecting
            ("means", 1),            # gmm
            ("theta", 1),            # naive bayes
            ("feature_importances", -1),  # tree ensembles (what
            # decision_tree.check_features sizes against)
        ):
            v = getattr(self, attr, None)
            if v is not None and getattr(v, "ndim", 0) >= 1:
                return int(np.asarray(v).shape[axis])
        return None

    def transform(self, data: Any, label_col: str | None = None, mesh=None) -> PredictionResult:
        ds = as_device_dataset(data, label_col=label_col, mesh=mesh)
        pred = self.predict(ds.x)
        return PredictionResult(prediction=pred, label=ds.y, weight=ds.w)

    def predict_numpy(self, x: np.ndarray) -> np.ndarray:
        ds = as_device_dataset(np.asarray(x))
        n = np.asarray(x).shape[0]
        return unpad(self.predict(ds.x), n)

    # persistence sugar -------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        from ..io.model_io import save_model

        name, meta, arrays = self._artifacts()
        save_model(path, name, meta, arrays, overwrite=overwrite)

    def write(self) -> "_Writer":
        """Spark-style ``model.write().overwrite().save(path)`` chain."""
        return _Writer(self)

    def _artifacts(self) -> tuple[str, dict, dict[str, np.ndarray]]:
        raise NotImplementedError


class ClusteringModel(Model):
    """Model base for the clustering family, adding Spark's DataFrame-style
    ``transform``: an :class:`AssembledTable` comes back as its source
    :class:`Table` with a ``prediction`` column appended (and, for
    probabilistic models, a ``probability`` column holding the assigned
    component's posterior — Spark's ``probability`` is the full K-vector,
    which a columnar table carries via :meth:`predict_proba` instead).
    This is the composition pattern the reference applies to supervised
    models (``model.transform(test_data)``, ``mllearnforhospitalnetwork
    .py:148,157``), extended to the clustering estimators so they plug
    into the same Table pipeline.

    Non-table inputs keep the base behavior (sharded
    :class:`PredictionResult`)."""

    def transform(self, data: Any, label_col: str | None = None, mesh=None):
        if isinstance(data, AssembledTable):
            n = len(data)
            ds = as_device_dataset(data.features, mesh=mesh)
            assigned = None
            if hasattr(self, "predict_assigned"):
                # fused chunked argmax+posterior — no (n, k) tensor in HBM,
                # only two length-n vectors cross to host
                pred_d, assigned = self.predict_assigned(ds.x)
            elif hasattr(self, "predict_proba"):
                p = self.predict_proba(ds.x)
                pred_d = jnp.argmax(p, axis=1)
                assigned = jnp.take_along_axis(p, pred_d[:, None], axis=1)[:, 0]
            else:
                pred_d = self.predict(ds.x)
            pred = np.asarray(unpad(pred_d, n)).astype(np.int32)
            out = data.table.with_column("prediction", pred, dtype="int")
            if assigned is not None:
                out = out.with_column(
                    "probability", np.asarray(unpad(assigned, n)), dtype="float"
                )
            return out
        return super().transform(data, label_col=label_col, mesh=mesh)


@dataclass
class _Writer:
    model: Model
    _overwrite: bool = False

    def overwrite(self) -> "_Writer":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        self.model.save(path, overwrite=self._overwrite)
