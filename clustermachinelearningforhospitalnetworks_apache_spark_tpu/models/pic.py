"""PowerIterationClustering (``pyspark.ml.clustering.PowerIterationClustering``).

Lin & Cohen's PIC: truncated power iteration of the row-normalized
affinity matrix W = D⁻¹A converges (before the trivial all-ones
eigenvector dominates) to a 1-D embedding in which clusters separate;
k-means on that embedding assigns the clusters.

Spark runs the iteration as pregel-style message passing over an edge
RDD; here the (symmetrized) affinity is a dense device matrix and each
iteration is one matvec on the MXU inside a ``lax.fori_loop`` — the
whole power iteration is a single jitted computation.  Dense (n, n) is
the honest trade for this estimator's scale (Spark's own docs position
PIC for up to ~10⁵ nodes; a dense f32 10⁵² matrix is HBM-feasible on a
v5e only to ~3·10⁴ — raise beyond that rather than silently thrash).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .base import Estimator

#: dense-affinity node budget (f32 n² must fit comfortably in HBM)
_MAX_NODES = 40_000


@partial(jax.jit, static_argnames=("max_iter",))
def _power_iterate(w_norm, v0, max_iter: int):
    def body(_, v):
        v = w_norm @ v
        # L1 normalization (Lin & Cohen) keeps the iterate from vanishing
        return v / jnp.maximum(jnp.sum(jnp.abs(v)), 1e-30)

    return lax.fori_loop(0, max_iter, body, v0)


def _build_affinity(src, dst, w, n: int) -> np.ndarray:
    """Symmetrized dense (n, n) affinity from edge triplets.

    Spark requires symmetric affinities; either orientation is accepted
    and duplicates fold additively.  Self-loops (src == dst) are folded
    exactly once — symmetrization must not double the diagonal.
    """
    a = np.zeros((n, n), np.float32)
    np.add.at(a, (src, dst), w)
    off_diag = src != dst
    np.add.at(a, (dst[off_diag], src[off_diag]), w[off_diag])
    return a


@dataclass(frozen=True)
class PowerIterationClustering(Estimator):
    """Spark defaults: k 2, maxIter 20, initMode "random" (or "degree").
    ``assign_clusters`` consumes (src, dst, weight) affinity triplets and
    returns per-node cluster assignments — Spark's API shape (PIC is a
    transformer-less estimator there too)."""

    k: int = 2
    max_iter: int = 20
    init_mode: str = "random"
    seed: int = 0

    def assign_clusters(self, src, dst, weight=None, mesh=None) -> np.ndarray:
        """(n,) cluster id per node (node ids = 0..max id)."""
        if self.k < 2:
            raise ValueError(f"k must be >= 2, got {self.k}")
        if self.init_mode not in ("random", "degree"):
            raise ValueError(
                f"init_mode must be random|degree, got {self.init_mode!r}"
            )
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if src.shape != dst.shape or src.ndim != 1:
            raise ValueError("src/dst must be equal-length 1-D id arrays")
        if len(src) == 0:
            raise ValueError("PowerIterationClustering on an empty affinity")
        if src.min() < 0 or dst.min() < 0:
            raise ValueError("node ids must be non-negative")
        w = (
            np.ones(len(src), np.float32)
            if weight is None
            else np.asarray(weight, np.float32)
        )
        if (w < 0).any():
            raise ValueError("affinity weights must be non-negative")
        n = int(max(src.max(), dst.max())) + 1
        if n > _MAX_NODES:
            raise ValueError(
                f"{n} nodes exceeds the dense-affinity budget "
                f"({_MAX_NODES}); PIC here materializes (n, n) in HBM"
            )
        a = _build_affinity(src, dst, w, n)
        deg = a.sum(axis=1)
        if (deg == 0).any():
            isolated = int(np.flatnonzero(deg == 0)[0])
            raise ValueError(
                f"node {isolated} has no edges; every node needs at least "
                "one affinity"
            )
        w_norm = jnp.asarray(a / deg[:, None])

        rng = np.random.default_rng(self.seed)
        if self.init_mode == "degree":
            v0 = deg / deg.sum()
        else:
            v0 = rng.uniform(0, 1, size=n)
            v0 = v0 / np.abs(v0).sum()
        v = np.asarray(
            jax.device_get(
                _power_iterate(w_norm, jnp.asarray(v0, jnp.float32), self.max_iter)
            ),
            np.float64,
        )

        # k-means on the 1-D embedding (Lin & Cohen step 3)
        from .kmeans import KMeans

        km = KMeans(k=self.k, seed=self.seed, max_iter=40).fit(
            v[:, None].astype(np.float32), mesh=mesh
        )
        return np.asarray(km.predict_numpy(v[:, None].astype(np.float32))).astype(
            np.int64
        )
