"""StreamingLinearRegression / StreamingLogisticRegression — incremental
supervised learners over micro-batches.

Capability parity with ``pyspark.mllib.regression
.StreamingLinearRegressionWithSGD`` / ``...classification
.StreamingLogisticRegressionWithSGD`` — and the WORKING version of the
reference's dead incremental-training hook, whose comment names
LogisticRegression as the per-batch model
(``mllearnforhospitalnetwork.py:87-106``, SURVEY.md C6/D2).

Spark streams SGD steps per batch.  On an accelerator the honest
incremental algorithm is better than SGD in both cost and exactness:

- **Linear**: decayed recursive least squares.  Per batch, one jitted
  pass builds the batch Gram/moment (two MXU matmuls), the running
  statistics decay by ``decay_factor`` and accumulate, and the (d+1)²
  solve re-runs — for decay 1.0 the model after N batches is EXACTLY the
  batch WLS fit of all rows seen (tested bit-tight), for decay < 1 it is
  exponentially-forgetting ridge, constant memory either way.
- **Logistic**: decayed IRLS statistics around the current estimate —
  each batch contributes its Newton gradient/Hessian at θₜ, history
  decays, one damped solve updates θ.  A drifting stream tracks; a
  stationary stream converges to the batch Newton fit.

Both plug into the micro-batch driver (``streaming/microbatch.py``) as
``foreachBatch`` consumers, like StreamingKMeans.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import default_mesh
from ..parallel.sharding import (
    DeviceDataset,
    batch_rows,
    mesh_of_dataset,
    microbatch_mesh,
    place_replicated,
)
from .base import as_device_dataset
from .linear_regression import LinearRegressionModel
from .logistic_regression import LogisticRegressionModel


@jax.jit
def _lin_batch_stats(x, y, w):
    """Batch (XᵀWX, XᵀWy, Σw) with an intercept column appended."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    xw = xa * w[:, None]
    return xw.T @ xa, xw.T @ y, jnp.sum(w)


@lru_cache(maxsize=16)
def _make_lin_update(decay: float):
    """One jitted, state-donating dispatch per micro-batch: batch stats +
    decayed accumulate fused, so the per-batch work is a single device
    call with the (d+1)² running statistics updated in place (same math
    as ``a*gram + g`` eagerly: elementwise, no reduction reorder — the
    decay-1.0 ≡ batch-WLS bit-tightness is preserved)."""

    def step(x, y, w, gram, mom, wsum):
        g, m, ws = _lin_batch_stats(x, y, w)
        a = jnp.float32(decay)
        return a * gram + g, a * mom + m, a * wsum + ws

    return jax.jit(step, donate_argnums=(3, 4, 5))


@jax.jit
def _logit_batch_stats(x, y, w, theta):
    """Batch Newton (gradient, Hessian) at θ — same per-row math as the
    batch IRLS fit (models/logistic_regression.py)."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    z = xa @ theta
    p = jax.nn.sigmoid(z)
    grad = xa.T @ (w * (p - y))
    r = jnp.maximum(w * p * (1.0 - p), 1e-10 * w)
    hess = (xa * r[:, None]).T @ xa
    return grad, hess


@dataclass
class StreamingLinearRegression:
    """``update(batch)`` per micro-batch; ``latest_model`` is always a
    plain :class:`LinearRegressionModel`.  decay_factor 1.0 (default)
    reproduces the exact all-data WLS fit; < 1 forgets exponentially."""

    decay_factor: float = 1.0
    reg_param: float = 0.0
    label_col: str = "length_of_stay"

    _gram: object = field(default=None, repr=False)
    _mom: object = field(default=None, repr=False)
    _wsum: object = field(default=0.0, repr=False)
    _n_batches: int = field(default=0, repr=False)
    _state_mesh: object = field(default=None, repr=False)

    def __post_init__(self):
        if not 0.0 <= self.decay_factor <= 1.0:
            raise ValueError(
                f"decay_factor must be in [0, 1], got {self.decay_factor}"
            )

    @property
    def n_batches(self) -> int:
        return self._n_batches

    def update(self, batch, mesh=None) -> "StreamingLinearRegression":
        mesh = mesh or default_mesh()
        if not isinstance(batch, DeviceDataset):
            mesh = microbatch_mesh(batch_rows(batch), mesh)
        ds = as_device_dataset(batch, self.label_col, mesh=mesh)
        if self._gram is None:
            d = ds.n_features + 1
            zero = jnp.zeros((d, d), jnp.float32)
            # zero-initialized state makes the first batch exact
            # (a·0 + g ≡ g bitwise), so one fused step covers every batch
            self._gram, self._mom, self._wsum = (
                zero, jnp.zeros((d,), jnp.float32), jnp.float32(0.0)
            )
        self._place_state(ds)
        step = _make_lin_update(float(self.decay_factor))
        self._gram, self._mom, self._wsum = step(
            ds.x, ds.y, ds.w, self._gram, self._mom, self._wsum
        )
        self._n_batches += 1
        return self

    def _place_state(self, ds) -> None:
        mesh = mesh_of_dataset(ds)
        if mesh is None or self._state_mesh == mesh:
            return
        self._gram, self._mom, self._wsum = place_replicated(
            mesh, (self._gram, self._mom, self._wsum)
        )
        self._state_mesh = mesh

    def absorb_partials(self, merged) -> "StreamingLinearRegression":
        """Fold a merged federated ``linear`` :class:`~..federated
        .partials.Partials` into the decayed RLS state as ONE micro-batch
        — the cross-silo form of :meth:`update`.  The merged Gram/moment
        ARE the batch statistics ``_lin_batch_stats`` would have produced
        on the concatenated silo rows (intercept-augmented, psum'd), so
        the decayed accumulate is the identical ``a·state + g``
        elementwise update and decay-1.0 keeps the all-rows-seen WLS
        exactness across network rounds (bit-tight when the silo sums
        are exact — the federated linear contract)."""
        if merged.family != "linear":
            raise ValueError(
                f"absorb_partials folds 'linear' partials, got "
                f"{merged.family!r}"
            )
        g = jnp.asarray(merged.stats["gram"], jnp.float32)
        m = jnp.asarray(merged.stats["mom"], jnp.float32)
        ws = jnp.float32(np.asarray(merged.stats["sw"]))
        if g.shape[0] != m.shape[0]:
            raise ValueError("merged gram/mom shapes disagree")
        if self._gram is None:
            d = g.shape[0]
            self._gram = jnp.zeros((d, d), jnp.float32)
            self._mom = jnp.zeros((d,), jnp.float32)
            self._wsum = jnp.float32(0.0)
        a = jnp.float32(self.decay_factor)
        # eager a·state + g matches the fused jit step bitwise
        # (elementwise, no reduction reorder — see _make_lin_update)
        self._gram = a * self._gram + g
        self._mom = a * self._mom + m
        self._wsum = a * self._wsum + ws
        self._n_batches += 1
        return self

    @property
    def latest_model(self) -> LinearRegressionModel:
        if self._gram is None:
            raise RuntimeError("no batches seen yet — call update() first")
        d = self._gram.shape[0]
        ridge = self.reg_param * max(float(jax.device_get(self._wsum)), 1.0)
        reg = jnp.zeros((d,), jnp.float32).at[:-1].set(ridge) + 1e-6
        # host arrays: the snapshot model must be usable on ANY mesh, not
        # pinned to whichever device the stream state happens to live on
        theta = np.asarray(
            jax.device_get(jnp.linalg.solve(self._gram + jnp.diag(reg), self._mom))
        )
        return LinearRegressionModel(coefficients=theta[:-1], intercept=theta[-1])


@dataclass
class StreamingLogisticRegression:
    """``update(batch)`` per micro-batch — the estimator the reference's
    dead hook intended.  Each batch adds its Newton statistics at the
    CURRENT θ to exponentially-decayed history and takes one damped
    Newton step; ``newton_steps_per_batch`` > 1 re-linearizes within the
    batch for faster early convergence."""

    decay_factor: float = 1.0
    reg_param: float = 0.0
    newton_steps_per_batch: int = 1
    label_col: str = "LOS_binary"
    threshold: float = 0.5

    _theta: object = field(default=None, repr=False)
    _grad_hist: object = field(default=None, repr=False)
    _hess_hist: object = field(default=None, repr=False)
    _wsum: float = field(default=0.0, repr=False)
    _n_batches: int = field(default=0, repr=False)
    _state_mesh: object = field(default=None, repr=False)

    def __post_init__(self):
        if not 0.0 <= self.decay_factor <= 1.0:
            raise ValueError(
                f"decay_factor must be in [0, 1], got {self.decay_factor}"
            )
        if self.newton_steps_per_batch < 1:
            raise ValueError("newton_steps_per_batch must be >= 1")

    @property
    def n_batches(self) -> int:
        return self._n_batches

    def update(self, batch, mesh=None) -> "StreamingLogisticRegression":
        mesh = mesh or default_mesh()
        if not isinstance(batch, DeviceDataset):
            mesh = microbatch_mesh(batch_rows(batch), mesh)
        ds = as_device_dataset(batch, self.label_col, mesh=mesh)
        d = ds.n_features + 1
        if self._theta is None:
            self._theta = jnp.zeros((d,), jnp.float32)
        self._place_state(ds)
        a = jnp.float32(self.decay_factor)
        w_batch = float(jax.device_get(jnp.sum(ds.w)))
        for _ in range(self.newton_steps_per_batch):
            g, h = _logit_batch_stats(ds.x, ds.y, ds.w, self._theta)
            # decayed history holds PAST batches' contributions at their
            # linearization points; the current batch re-linearizes
            if self._grad_hist is None:
                grad_tot, hess_tot = g, h
            else:
                grad_tot = a * self._grad_hist + g
                hess_tot = a * self._hess_hist + h
            ridge = self.reg_param * max(
                self.decay_factor * self._wsum + w_batch, 1.0
            )
            reg = jnp.zeros((d,), jnp.float32).at[:-1].set(ridge)
            grad_tot = grad_tot + reg * self._theta
            hess_r = hess_tot + jnp.diag(reg)
            jitter = 1e-6 * jnp.trace(hess_r) / d + 1e-8
            delta = jnp.linalg.solve(
                hess_r + jitter * jnp.eye(d, dtype=jnp.float32), grad_tot
            )
            dmax = jnp.max(jnp.abs(delta))
            delta = delta * jnp.minimum(1.0, 20.0 / (dmax + 1e-30))
            self._theta = self._theta - delta
        # history absorbs this batch's final-linearization stats
        g, h = _logit_batch_stats(ds.x, ds.y, ds.w, self._theta)
        if self._grad_hist is None:
            self._grad_hist, self._hess_hist = g, h
        else:
            self._grad_hist = a * self._grad_hist + g
            self._hess_hist = a * self._hess_hist + h
        self._wsum = self.decay_factor * self._wsum + w_batch
        self._n_batches += 1
        return self

    def _place_state(self, ds) -> None:
        """Keep θ and the decayed Newton statistics committed to the
        batch's mesh, so adaptive single-device/mesh placement switches
        never mix incompatibly-committed jit inputs."""
        mesh = mesh_of_dataset(ds)
        if mesh is None or self._state_mesh == mesh:
            return
        self._theta, self._grad_hist, self._hess_hist = place_replicated(
            mesh, (self._theta, self._grad_hist, self._hess_hist)
        )
        self._state_mesh = mesh

    @property
    def latest_model(self) -> LogisticRegressionModel:
        if self._theta is None:
            raise RuntimeError("no batches seen yet — call update() first")
        theta = np.asarray(jax.device_get(self._theta))  # any-mesh snapshot
        return LogisticRegressionModel(
            coefficients=theta[:-1],
            intercept=theta[-1],
            threshold=self.threshold,
            n_iter=self._n_batches,
        )
