"""GaussianMixture — EM with full covariances (BASELINE config 3: k=32).

Capability parity: ``pyspark.ml.clustering.GaussianMixture`` (fit/transform,
``weights``, ``gaussians`` (mean+cov), ``summary.logLikelihood``; defaults
maxIter=100, tol=0.01, full covariance).  Spark distributes the E-step and
accumulates the M-step sufficient statistics (Σr, Σr·x, Σr·xxᵀ) per
partition with ``treeAggregate``.

The TPU-native fit is ONE jitted shard_map program: a ``lax.while_loop``
over EM iterations, each iteration a row-chunked ``lax.scan`` over the data
shard that accumulates exactly the Spark sufficient statistics — (nk,
Σr·x, Σr·xxᵀ, log-likelihood) — and ``psum``s them over the mesh's data
axis.  The (n, k) responsibility matrix exists only one chunk at a time in
VMEM-sized transients (the BASELINE 10M-row table would need an n·k HBM
tensor otherwise), the moment contraction is an MXU matmul of the (chunk,
k) responsibilities against the (chunk, d·d) row outer products, and the
(k, d, d) refit runs replicated on every device.  One host sync per fit.

Rows are recentered around the init-sample mean inside the scan (fused
into the chunk read): the covariance refit ``Σr·xxᵀ/nk − μμᵀ`` cancels
catastrophically in f32 when the data mean dwarfs the spread.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.special import logsumexp
from jax.sharding import Mesh

from ..io.model_io import register_model
from ..ops.distance import matmul_p, validate_matmul_precision
from ..parallel.mesh import DATA_AXIS, default_mesh
from ..parallel.partitioner import family as _partitioner_family

#: declarative EM layouts — rules in parallel/partitioner.py
_PT = _partitioner_family("gmm")
from ..parallel.outofcore import add_stats as _gmm_add_stats
from ..parallel.sharding import DeviceDataset
from .base import ClusteringModel, Estimator, Model, as_device_dataset, check_features
from ..parallel.sharding import chunk_layout, chunked_pad
from .kmeans import _kmeans_pp_init, _lloyd_refine


def _chol_log_pdf(x, mean, chol):
    """Row-wise log N(x; mean, L·Lᵀ) given the Cholesky factor L (d,d)."""
    d = x.shape[-1]
    diff = x - mean[None, :]
    sol = jax.scipy.linalg.solve_triangular(chol, diff.T, lower=True).T
    maha = jnp.sum(sol * sol, axis=-1)
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
    return -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + maha)


def _pdf_factors(means, chols):
    """→ (W (d, k·d), offset (k, d), const (k,)) for the matmul E-step.

    With L⁻¹ the inverse Cholesky factor, maha_k(x) = ‖x·L_k⁻ᵀ −
    mean_k·L_k⁻ᵀ‖²; stacking L⁻ᵀ over components turns the per-component
    triangular solves of :func:`_chol_log_pdf` (VPU work, k·d² per row)
    into ONE (chunk, d) @ (d, k·d) MXU matmul per row chunk.  The k
    (d, d) inversions run once per EM iteration, outside the row scan."""
    k, d = means.shape
    eye = jnp.eye(d, dtype=jnp.float32)
    linv = jax.vmap(
        lambda L: jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    )(chols)                                      # (k, d, d) = L⁻¹
    linvT = jnp.transpose(linv, (0, 2, 1))        # [k, i, j] = L⁻ᵀ entries
    w_fac = jnp.transpose(linvT, (1, 0, 2)).reshape(d, k * d)
    offset = jnp.einsum("kd,kde->ke", means, linvT)
    logdet = 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(chols, axis1=1, axis2=2)), axis=1
    )
    const = -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet)
    return w_fac, offset, const


def _batched_log_pdf(xb, w_fac, offset, const, precision="highest"):
    """(chunk, k) log-densities via the precomputed :func:`_pdf_factors`
    — identical values to the ``vmap(_chol_log_pdf)`` form (modulo matmul
    rounding), but the hot op is an MXU matmul instead of per-component
    triangular solves."""
    k, d = offset.shape
    xw = matmul_p(xb, w_fac, precision).reshape(-1, k, d)
    y = xw - offset[None]
    maha = jnp.sum(y * y, axis=-1)
    return const[None, :] - 0.5 * maha


@partial(jax.jit, static_argnames=())
def _e_step(x, w, log_weights, means, chols):
    """Full-table responsibilities — model-side scoring only (``score``,
    ``predict_proba``); the fit path never materializes (n, k)."""
    log_pdf = jax.vmap(lambda m, L: _chol_log_pdf(x, m, L))(means, chols).T
    log_resp_un = log_pdf + log_weights[None, :]
    log_norm = logsumexp(log_resp_un, axis=1)
    resp = jnp.exp(log_resp_un - log_norm[:, None]) * w[:, None]
    log_likelihood = jnp.sum(log_norm * w)
    return resp, log_likelihood


def _em_pass_builder(k: int, d: int, precision: str = "highest"):
    """Chunk-scan E-step sufficient statistics (nk, Σr·x, Σr·xxᵀ, ll),
    psum'd over the data axis — shared by the fused resident EM loop and
    the out-of-core block-stats step.

    ``precision`` drives the log-pdf computation AND the moment
    contractions.  The default "highest" keeps the triangular-solve
    log-pdf (diff-first: forming x − mean_k before the L⁻¹ transform is
    stable when component separations dwarf within-component scale) with
    exact-f32 matmul emulation.  The throughput modes ("high"/"default"/
    "bf16") switch the log-pdf to the :func:`_pdf_factors` matmul form —
    one (chunk, d) @ (d, k·d) MXU contraction per chunk instead of
    per-component VPU solves — which subtracts in the transformed basis
    and therefore trades that extreme-offset stability guard for MXU
    rate, on top of the reduced matmul precision the caller already
    opted into (the global-mean recentering shift still absorbs a
    common offset)."""
    use_factors = precision != "highest"

    def em_pass(x_c, w_c, shift, logw, means, chols):
        if use_factors:
            # Per-iteration factor precompute (k triangular inversions) —
            # outside the row scan, so the per-chunk hot op is one matmul.
            w_fac, offset, const = _pdf_factors(means, chols)

        def body(carry, inputs):
            nk, sums, outer, ll = carry
            xb, wb = inputs
            xb = xb - shift[None, :]
            if use_factors:
                log_pdf = _batched_log_pdf(xb, w_fac, offset, const, precision)
            else:
                log_pdf = jax.vmap(
                    lambda m, L: _chol_log_pdf(xb, m, L)
                )(means, chols).T
            log_resp_un = log_pdf + logw[None, :]
            log_norm = logsumexp(log_resp_un, axis=1)
            resp = jnp.exp(log_resp_un - log_norm[:, None]) * wb[:, None]  # (c, k)
            nk = nk + jnp.sum(resp, axis=0)
            sums = sums + matmul_p(resp.T, xb, precision)
            # (chunk, d·d) row outer products against (chunk, k) resp —
            # an MXU matmul instead of an (n, k, d, d)-shaped einsum.
            xx = (xb[:, :, None] * xb[:, None, :]).reshape(-1, d * d)
            outer = outer + matmul_p(resp.T, xx, precision).reshape(k, d, d)
            ll = ll + jnp.sum(log_norm * wb)
            return (nk, sums, outer, ll), None

        init = jax.tree.map(
            lambda z: lax.pcast(z, DATA_AXIS, to="varying"),
            (
                jnp.zeros((k,), jnp.float32),
                jnp.zeros((k, d), jnp.float32),
                jnp.zeros((k, d, d), jnp.float32),
                jnp.zeros((), jnp.float32),
            ),
        )
        (nk, sums, outer, ll), _ = lax.scan(body, init, (x_c, w_c))
        return (
            lax.psum(nk, DATA_AXIS),
            lax.psum(sums, DATA_AXIS),
            lax.psum(outer, DATA_AXIS),
            lax.psum(ll, DATA_AXIS),
        )

    return em_pass


def _m_step_rule(nk, sums, outer, reg_covar):
    """The one copy of the M-step refit (means/covs/weights from
    accumulated sufficient statistics) — shared by the fused resident loop
    body and the out-of-core :func:`_gmm_m_step`."""
    d = sums.shape[1]
    eye = jnp.eye(d, dtype=jnp.float32)
    nk = jnp.maximum(nk, 1e-6)
    means = sums / nk[:, None]
    covs = outer / nk[:, None, None] - jnp.einsum("kd,ke->kde", means, means)
    covs = covs + reg_covar * eye[None]
    weights = nk / jnp.sum(nk)
    return means, covs, weights


@lru_cache(maxsize=32)
def _make_em_loop(
    mesh: Mesh, n_loc: int, k: int, d: int, chunk_rows: int, max_iter: int,
    precision: str = "highest",
):
    """The whole EM fit as one jitted shard_map computation.

    max_iter=1 doubles as the single-step builder for the host-hook path
    (checkpointing / on_iteration callbacks need the host every step).
    Convergence: |ll_t − ll_{t−1}| < tol, Spark semantics on the TOTAL
    log-likelihood.
    """
    n_chunks, chunk = chunk_layout(n_loc, chunk_rows)
    em_pass = _em_pass_builder(k, d, precision)

    def shard_fn(x, w, shift, means, covs, weights, reg_covar, tol):
        x_c, w_c = chunked_pad(x, w, n_chunks, chunk)
        eye = jnp.eye(d, dtype=jnp.float32)

        def cond(carry):
            it, _, _, _, prev_ll, ll = carry
            return (it < max_iter) & (jnp.abs(ll - prev_ll) >= tol)

        def body(carry):
            it, means, covs, weights, _, old_ll = carry
            chols = jnp.linalg.cholesky(covs + reg_covar * eye[None])
            nk, sums, outer, ll = em_pass(
                x_c, w_c, shift, jnp.log(weights), means, chols
            )
            means, covs, weights = _m_step_rule(nk, sums, outer, reg_covar)
            return it + 1, means, covs, weights, old_ll, ll

        init = (
            jnp.int32(0), means, covs, weights,
            jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
        )
        it, means, covs, weights, _, ll = lax.while_loop(cond, body, init)
        return means, covs, weights, ll, it

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                _PT.spec("batch/x", 2),
                _PT.spec("batch/w", 1),
            ) + (_PT.spec("const/params"),) * 6,
            out_specs=(_PT.spec("const/params"),) * 5,
        )
    )


def _init_params(valid: np.ndarray, k: int, d: int, seed: int, reg_covar: float):
    """EM init from a SHIFTED host sample → (means, covs, weights).

    k-means++ seeding + short Lloyd refinement (sklearn's
    init_params="kmeans" equivalent) — raw ++ points alone leave EM in
    visibly worse local optima on close blob pairs.  Per-cluster diagonal
    covariance + cluster-share weights from the init assignment (a global
    variance would span the blob spread and make the first E-step
    responsibilities near-uniform, collapsing means)."""
    means64, assign0 = _lloyd_refine(
        valid, _kmeans_pp_init(valid, k, seed), iters=10, return_assign=True
    )
    means = means64.astype(np.float32)
    covs = np.empty((k, d, d), dtype=np.float32)
    weights = np.empty((k,), dtype=np.float32)
    global_var = np.maximum(valid.var(axis=0), reg_covar)
    for j in range(k):
        mask = assign0 == j
        weights[j] = max(mask.mean(), 1e-6)
        if mask.sum() >= 2:
            covs[j] = np.diag(np.maximum(valid[mask].var(axis=0), reg_covar))
        else:
            covs[j] = np.diag(global_var)
    return means, covs, weights / weights.sum()


@lru_cache(maxsize=32)
def _make_em_stats_step(
    mesh: Mesh, n_loc: int, k: int, d: int, chunk_rows: int,
    precision: str = "highest",
):
    """Per-BLOCK E-step sufficient statistics (nk, Σr·x, Σr·xxᵀ, ll) —
    the out-of-core driver accumulates these across host row blocks, then
    applies one :func:`_gmm_m_step` per EM iteration."""
    n_chunks, chunk = chunk_layout(n_loc, chunk_rows)
    em_pass = _em_pass_builder(k, d, precision)

    def shard_fn(x, w, shift, logw, means, chols):
        x_c, w_c = chunked_pad(x, w, n_chunks, chunk)
        return em_pass(x_c, w_c, shift, logw, means, chols)

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2), _PT.spec("batch/w", 1))
            + (_PT.spec("const/params"),) * 4,
            out_specs=(_PT.spec("const/params"),) * 4,
        )
    )


@jax.jit
def _gmm_m_step(nk, sums, outer, reg_covar):
    """M-step refit from fully-accumulated out-of-core statistics — the
    same :func:`_m_step_rule` the fused resident loop applies."""
    return _m_step_rule(nk, sums, outer, reg_covar)


@jax.jit
def _gmm_chols(covs, reg_covar):
    d = covs.shape[-1]
    return jnp.linalg.cholesky(covs + reg_covar * jnp.eye(d, dtype=jnp.float32)[None])


def _predict_assigned_local(xs, logw, means, chols, *, chunk):
    """Shard-local fused argmax+posterior over row chunks."""
    n = xs.shape[0]
    c = min(chunk, max(n, 1))
    pad = (-n) % c
    if pad:
        xs = jnp.pad(xs, ((0, pad), (0, 0)))

    def one(xc):
        log_pdf = jax.vmap(lambda m, L: _chol_log_pdf(xc, m, L))(means, chols).T
        log_resp = log_pdf + logw[None, :]
        pred = jnp.argmax(log_resp, axis=1)
        assigned = jnp.exp(jnp.max(log_resp, axis=1) - logsumexp(log_resp, axis=1))
        return pred.astype(jnp.int32), assigned

    preds, probs = lax.map(one, xs.reshape(-1, c, xs.shape[1]))
    return preds.reshape(-1)[:n], probs.reshape(-1)[:n]


@lru_cache(maxsize=32)
def _make_predict_assigned(mesh: Mesh | None, chunk: int):
    """Cached compiled wrapper (jit caches on the function object, so a
    per-call closure would retrace and recompile every call)."""
    local = partial(_predict_assigned_local, chunk=chunk)
    if mesh is None:
        return jax.jit(local)
    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2),)
            + (_PT.spec("const/params"),) * 3,
            out_specs=(_PT.spec("rows/assign", 1), _PT.spec("rows/logprob", 1)),
        )
    )


@register_model("GaussianMixtureModel")
@dataclass
class GaussianMixtureModel(ClusteringModel):
    weights: np.ndarray      # (k,)
    means: np.ndarray        # (k, d)
    covariances: np.ndarray  # (k, d, d)
    log_likelihood: float = 0.0      # TOTAL (Spark summary.logLikelihood)
    avg_log_likelihood: float = 0.0  # per-row mean (sklearn .score parity)
    n_iter: int = 0

    @property
    def k(self) -> int:
        return self.means.shape[0]

    @property
    def summary(self):
        """Spark's ``GaussianMixtureModel.summary`` surface (logLikelihood
        / numIter); hard-assignment sizes aren't stored, so
        ``cluster_sizes`` is None — use ``predict`` + a bincount for them."""
        from .summary import ClusteringSummary

        return ClusteringSummary(
            k=self.k,
            num_iter=self.n_iter,
            log_likelihood=float(self.log_likelihood),
        )

    def _device_params(self):
        means = jnp.asarray(self.means, jnp.float32)
        chols = jnp.linalg.cholesky(jnp.asarray(self.covariances, jnp.float32))
        logw = jnp.log(jnp.asarray(self.weights, jnp.float32))
        return logw, means, chols

    def predict_proba(self, x: jax.Array) -> jax.Array:
        check_features(x, self.means.shape[1], "GaussianMixtureModel")
        logw, means, chols = self._device_params()
        x = x.astype(jnp.float32)
        log_pdf = jax.vmap(lambda m, L: _chol_log_pdf(x, m, L))(means, chols).T
        log_resp = log_pdf + logw[None, :]
        return jnp.exp(log_resp - logsumexp(log_resp, axis=1)[:, None])

    def predict(self, x: jax.Array) -> jax.Array:
        if x.shape[0] * self.k > (1 << 24):
            return self.predict_assigned(x)[0]
        return jnp.argmax(self.predict_proba(x), axis=1).astype(jnp.int32)

    def predict_assigned(
        self, x: jax.Array, chunk: int = 65536
    ) -> tuple[jax.Array, jax.Array]:
        """→ (component (n,) int32, assigned-component posterior (n,)).

        The fused, chunked form of ``argmax(predict_proba)`` — per chunk
        only a (chunk, k) responsibility tile exists, so no (n, k) tensor
        lands in HBM at BASELINE scale (the same rule as the KMeans
        chunked assign and the training E-step's row scan).  Mesh-sharded
        inputs run shard-locally under ``shard_map``.
        """
        from jax.sharding import Mesh

        check_features(x, self.means.shape[1], "GaussianMixtureModel")
        logw, means, chols = self._device_params()
        mesh = getattr(getattr(x, "sharding", None), "mesh", None)
        mesh = mesh if isinstance(mesh, Mesh) else None
        fn = _make_predict_assigned(mesh, chunk)
        xf = x.astype(jnp.float32)
        if mesh is not None:
            return fn(
                xf,
                _PT.put("const/logw", logw, mesh),
                _PT.put("const/means", means, mesh),
                _PT.put("const/chols", chols, mesh),
            )
        return fn(xf, logw, means, chols)

    def score(self, data, mesh=None) -> float:
        """Mean per-row log-likelihood."""
        ds = as_device_dataset(data, mesh=mesh)
        logw, means, chols = self._device_params()
        _, ll = _e_step(ds.x.astype(jnp.float32), ds.w, logw, means, chols)
        return float(ll / jnp.maximum(jnp.sum(ds.w), 1.0))

    def _artifacts(self):
        return (
            "GaussianMixtureModel",
            {
                "log_likelihood": self.log_likelihood,
                "avg_log_likelihood": self.avg_log_likelihood,
                "n_iter": self.n_iter,
            },
            {
                "weights": np.asarray(self.weights),
                "means": np.asarray(self.means),
                "covariances": np.asarray(self.covariances),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            weights=arrays["weights"],
            means=arrays["means"],
            covariances=arrays["covariances"],
            log_likelihood=float(params.get("log_likelihood", 0.0)),
            avg_log_likelihood=float(params.get("avg_log_likelihood", 0.0)),
            n_iter=int(params.get("n_iter", 0)),
        )


@dataclass(frozen=True)
class GaussianMixture(Estimator):
    k: int = 2
    max_iter: int = 100        # Spark default
    tol: float = 0.01          # Spark default (log-likelihood delta)
    seed: int = 0
    reg_covar: float = 1e-6
    init_sample_size: int = 65536
    # Row-chunk size for the E/M scan; the per-chunk transients (resp
    # (chunk, k), row outer products (chunk, d²)) stay VMEM-friendly.
    chunk_rows: int = 65536
    # Mid-training checkpointing (io/fit_checkpoint.py): commit EM state
    # (means, covariances, weights, log-likelihood) every N iterations so a
    # preempted fit resumes from the last commit.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 5
    weight_col: str | None = None  # Spark's weightCol (3.0+)
    # Warm start (lifecycle + federated rounds): begin EM from these
    # (weights (k,), means (k, d), covariances (k, d, d)) instead of the
    # sample init — same role as KMeans.warm_start_centers.  A warm fit
    # runs UNSHIFTED (shift = 0): the supplied means live in raw feature
    # coordinates and re-deriving a sample shift would make the fit's
    # arithmetic depend on the sampler, breaking the federated parity
    # contract.  The checkpoint signature fingerprints the warm params.
    warm_start_params: tuple | None = None
    # Matmul mode for the E-step log-pdf + moment contractions — same
    # naming as KMeans.matmul_precision.  Default "highest" keeps the
    # exact-f32, solve-form E-step (round-4 behavior, bit-comparable).
    # The throughput modes use the matmul-factor E-step; note that under
    # them the convergence log-likelihood is itself computed at reduced
    # matmul precision, so a tol much below the mode's rounding noise
    # (~1e-2 relative for "bf16") stops on noise, not EM progress.
    matmul_precision: str = "highest"

    def _warm_params(self, d: int):
        """Validated warm-start (weights, means, covs) as f32, or None."""
        if self.warm_start_params is None:
            return None
        w, m, c = self.warm_start_params
        w = np.asarray(w, np.float32)
        m = np.asarray(m, np.float32)
        c = np.asarray(c, np.float32)
        if w.shape != (self.k,) or m.shape != (self.k, d) or \
                c.shape != (self.k, d, d):
            raise ValueError(
                "warm_start_params must be (weights (k,), means (k, d), "
                f"covariances (k, d, d)) for k={self.k}, d={d}; got "
                f"{w.shape}, {m.shape}, {c.shape}"
            )
        return w, m, c

    def _warm_fingerprint(self) -> str | None:
        """Warm-start identity for the checkpoint signature."""
        if self.warm_start_params is None:
            return None
        from ..io.fit_checkpoint import array_fingerprint

        w, m, c = self.warm_start_params
        return "|".join(
            array_fingerprint(np.asarray(a, dtype=np.float32))
            for a in (w, m, c)
        )

    def fit(
        self, data, label_col: str | None = None, mesh=None, on_iteration=None
    ) -> GaussianMixtureModel:
        """``on_iteration(it, log_likelihood)`` (optional) fires after every
        EM step — progress reporting and fault-injection hooks.

        A :class:`~..parallel.outofcore.HostDataset` input takes the
        out-of-core path: rows stream through the device in
        ``max_device_rows`` blocks per EM iteration."""
        from ..parallel.outofcore import HostDataset

        validate_matmul_precision(self.matmul_precision)
        mesh = mesh or default_mesh()
        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh, on_iteration)
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        x = ds.x.astype(jnp.float32)
        w = ds.w
        d = x.shape[1]
        # One weight fetch serves the row count AND the init sampler (on a
        # remote-attached chip every extra host sync costs tens of ms).
        w_host = np.asarray(jax.device_get(w))
        n = float(w_host.sum())
        if n == 0:
            raise ValueError("GaussianMixture fit on an empty dataset")

        ckpt = None
        resumed = None
        if self.checkpoint_dir:
            from ..io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "GaussianMixture", "k": self.k, "d": d,
                "data": data_fingerprint(x, w),
                "n_padded": ds.n_padded, "seed": self.seed,
                "warm": self._warm_fingerprint(),
                "reg_covar": self.reg_covar, "tol": self.tol,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()

        warm = self._warm_params(d)
        if warm is None:
            # Init on a bounded host sample (only the sample leaves the
            # device); the sample also supplies the recentering shift that
            # keeps the f32 covariance refit stable on unstandardized data.
            from ..parallel.sharding import sample_valid_rows

            valid = sample_valid_rows(
                DeviceDataset(x, ds.y, w), self.init_sample_size, self.seed,
                w_host=w_host,
            )
            shift = valid.mean(axis=0).astype(np.float32) if valid.shape[0] else np.zeros(
                (d,), np.float32
            )
        else:
            # warm fits run unshifted (see warm_start_params note)
            valid = None
            shift = np.zeros((d,), np.float32)

        start_it = 1
        prev_ll = -np.inf
        if resumed is not None:
            step0, arrays, extra = resumed
            # Checkpoints store UNSHIFTED means; re-apply this fit's shift.
            means = arrays["means"].astype(np.float32) - shift
            covs = arrays["covariances"].astype(np.float32)
            weights = arrays["weights"].astype(np.float32)
            prev_ll = float(extra.get("prev_ll", -np.inf))
            start_it = step0 + 1
        elif warm is not None:
            weights, means, covs = warm
        else:
            # Init runs in SHIFTED coordinates, like the EM loop itself.
            means, covs, weights = _init_params(
                valid - shift, self.k, d, self.seed, self.reg_covar
            )

        means_d = jnp.asarray(means)
        covs_d = jnp.asarray(covs)
        weights_d = jnp.asarray(weights)
        shift_d = jnp.asarray(shift)
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]

        # A resume that lands past max_iter skips the loop entirely — seed
        # ll from the checkpoint so the returned model reports the real
        # likelihood, not 0.0.
        ll = prev_ll if np.isfinite(prev_ll) else 0.0
        it = start_it - 1
        if ckpt is None and on_iteration is None and start_it <= self.max_iter:
            # Fast path: the whole EM fit is one device computation
            # (single host sync instead of one per iteration).
            loop = _make_em_loop(
                mesh, n_loc, self.k, d, self.chunk_rows,
                self.max_iter - (start_it - 1), self.matmul_precision,
            )
            means_d, covs_d, weights_d, ll_dev, it_dev = loop(
                x, w, shift_d, means_d, covs_d, weights_d,
                jnp.float32(self.reg_covar), jnp.float32(self.tol),
            )
            ll = float(ll_dev)
            it = (start_it - 1) + int(it_dev)
        else:
            # Host-hook path: one EM iteration per device call (the
            # max_iter=1 loop never re-enters its while body).
            step = _make_em_loop(
                mesh, n_loc, self.k, d, self.chunk_rows, 1,
                self.matmul_precision,
            )
            for it in range(start_it, self.max_iter + 1):
                means_d, covs_d, weights_d, ll_dev, _ = step(
                    x, w, shift_d, means_d, covs_d, weights_d,
                    jnp.float32(self.reg_covar), jnp.float32(-jnp.inf),
                )
                ll = float(ll_dev)  # TOTAL log-likelihood — Spark tol here
                if ckpt is not None and it % max(self.checkpoint_every, 1) == 0:
                    ckpt.save(
                        it,
                        {
                            # stored UNSHIFTED so any later fit (whose
                            # sample shift may differ) resumes correctly
                            "means": np.asarray(jax.device_get(means_d)) + shift,
                            "covariances": np.asarray(jax.device_get(covs_d)),
                            "weights": np.asarray(jax.device_get(weights_d)),
                        },
                        extra={"prev_ll": ll},
                    )
                if on_iteration is not None:
                    on_iteration(it, ll)
                if abs(ll - prev_ll) < self.tol:
                    prev_ll = ll
                    break
                prev_ll = ll

        return GaussianMixtureModel(
            weights=np.asarray(jax.device_get(weights_d)),
            means=np.asarray(jax.device_get(means_d)) + shift,
            covariances=np.asarray(jax.device_get(covs_d)),
            log_likelihood=ll,
            avg_log_likelihood=ll / max(n, 1.0),
            n_iter=it,
        )

    # ---------------------------------------------------- partials protocol
    # Federated EM: silos run _make_em_stats_step (the out-of-core block
    # kernel) on their private rows against the broadcast parameters, the
    # coordinator's zero-init ascending fold reproduces the scan/psum
    # summation, and _gmm_m_step + a host-f32 mirror of the while_loop's
    # |ll − prev_ll| test replay the resident fast path bit-for-bit.
    # Everything runs unshifted (the warm_start_params convention).
    partials_family = "gmm"

    def partials_max_rounds(self) -> int:
        return self.max_iter

    def init_partials_state(self, n_features: int, mesh=None):
        from ..federated.partials import FitState

        warm = self._warm_params(n_features)
        if warm is None:
            return None  # coordinator runs the candidate init round
        weights, means, covs = warm
        return FitState(
            family=self.partials_family, version=0,
            params={"weights": weights, "means": means, "covariances": covs},
            # the device loop's convergence carry starts at +inf (the
            # first cond compares ll₁ against it) — the host mirror must
            # match to reproduce iteration counts
            meta={"prev_ll": float("inf"), "ll": 0.0, "n": 0.0},
        )

    def local_init_stats(self, data, label_col: str | None = None, mesh=None):
        """One silo's init contribution: local k-means++ candidates of its
        sample (candidate centers cross the wire, never rows)."""
        from ..federated.partials import Partials
        from ..parallel.sharding import sample_valid_rows

        mesh = mesh or default_mesh()
        ds = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        sample = np.asarray(
            sample_valid_rows(ds, self.init_sample_size, self.seed),
            np.float64,
        )
        n_cand = min(max(4 * self.k, 2 * self.k + 8), sample.shape[0])
        cand = _kmeans_pp_init(sample, n_cand, self.seed)
        return Partials(
            family="gmm.init",
            stats={"candidates": np.asarray(cand, np.float64)},
            n_rows=float(sample.shape[0]),
        )

    def init_state_from_merged(self, merged):
        """Round-0 EM parameters from the concatenated per-silo candidates
        (same `_init_params` recipe as the pooled sample init, run on the
        candidate pool, unshifted)."""
        from ..federated.partials import FitState

        cand = np.asarray(merged.stats["candidates"], np.float64)
        d = cand.shape[1]
        means, covs, weights = _init_params(
            cand, self.k, d, self.seed, self.reg_covar
        )
        return FitState(
            family=self.partials_family, version=0,
            params={
                "weights": np.asarray(weights, np.float32),
                "means": np.asarray(means, np.float32),
                "covariances": np.asarray(covs, np.float32),
            },
            meta={"prev_ll": float("inf"), "ll": 0.0, "n": 0.0},
        )

    def partial_fit_stats(
        self, data, label_col: str | None = None, mesh=None,
        state=None, final: bool = False,
    ):
        from ..federated.partials import Partials

        if state is None:
            raise ValueError("gmm partials need the broadcast FitState")
        validate_matmul_precision(self.matmul_precision)
        mesh = mesh or default_mesh()
        ds = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        x = ds.x.astype(jnp.float32)
        d = x.shape[1]
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        step = _make_em_stats_step(
            mesh, n_loc, self.k, d, self.chunk_rows, self.matmul_precision
        )
        covs_d = jnp.asarray(state.params["covariances"], jnp.float32)
        chols = _gmm_chols(covs_d, jnp.float32(self.reg_covar))
        logw = jnp.log(jnp.asarray(state.params["weights"], jnp.float32))
        means_d = jnp.asarray(state.params["means"], jnp.float32)
        nk, sums, outer, ll = step(
            x, ds.w, jnp.zeros((d,), jnp.float32), logw, means_d, chols
        )
        return Partials(
            family=self.partials_family,
            stats={
                "nk": np.asarray(jax.device_get(nk)),
                "sums": np.asarray(jax.device_get(sums)),
                "outer": np.asarray(jax.device_get(outer)),
                "ll": np.asarray(jax.device_get(ll)),
            },
            n_rows=float(np.asarray(jax.device_get(jnp.sum(ds.w)))),
            state_version=state.version,
        )

    def apply_partials(self, state, merged):
        from ..federated.partials import FitState

        means, covs, weights = _gmm_m_step(
            jnp.asarray(merged.stats["nk"]),
            jnp.asarray(merged.stats["sums"]),
            jnp.asarray(merged.stats["outer"]),
            jnp.float32(self.reg_covar),
        )
        ll = np.float32(np.asarray(merged.stats["ll"]))
        prev_ll = np.float32(state.meta.get("prev_ll", float("inf")))
        version = state.version + 1
        # host-f32 mirror of the device `|ll − prev_ll| >= tol` exit —
        # same f32 operands, same iteration counts
        done = bool(np.abs(ll - prev_ll) < np.float32(self.tol))
        done = done or version >= self.max_iter
        return FitState(
            family=self.partials_family, version=version,
            params={
                "weights": np.asarray(jax.device_get(weights)),
                "means": np.asarray(jax.device_get(means)),
                "covariances": np.asarray(jax.device_get(covs)),
            },
            meta={
                "prev_ll": float(ll),
                "ll": float(ll),
                "n": float(merged.n_rows),
            },
        ), done

    def fit_from_partials(self, merged, state=None) -> GaussianMixtureModel:
        if state is None:
            raise ValueError(
                "gmm fit_from_partials needs the converged FitState"
            )
        ll = float(state.meta.get("ll", 0.0))
        n = float(state.meta.get("n", 0.0))
        return GaussianMixtureModel(
            weights=np.asarray(state.params["weights"], np.float32),
            means=np.asarray(state.params["means"], np.float32),
            covariances=np.asarray(state.params["covariances"], np.float32),
            log_likelihood=ll,
            avg_log_likelihood=ll / max(n, 1.0),
            n_iter=state.version,
        )

    def _fit_outofcore(self, hd, mesh: Mesh, on_iteration=None) -> GaussianMixtureModel:
        """Rows ≫ HBM: per EM iteration, stream ``max_device_rows`` blocks
        through the mesh accumulating the SAME psum'd sufficient statistics
        (nk, Σr·x, Σr·xxᵀ, ll) as the resident chunk scan, then apply one
        M-step — device memory bounded by the block size.

        ``checkpoint_dir`` composes with this path (VERDICT r3 next #5):
        EM state commits at iteration boundaries (block streaming is
        inside an iteration), so preempted long out-of-core fits resume
        from the last commit."""
        d = hd.n_features
        n = hd.count()
        if n == 0:
            raise ValueError("GaussianMixture fit on an empty dataset")

        ckpt = None
        resumed = None
        if self.checkpoint_dir:
            from ..io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "GaussianMixture", "storage": "outofcore",
                "k": self.k, "d": d,
                "data": data_fingerprint(hd.x, hd.w),
                "n": hd.n, "seed": self.seed,
                "warm": self._warm_fingerprint(),
                "reg_covar": self.reg_covar, "tol": self.tol,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()

        warm = self._warm_params(d)
        if warm is None:
            valid = hd.sample_rows(self.init_sample_size, self.seed)
            shift = (
                valid.mean(axis=0).astype(np.float32)
                if valid.shape[0]
                else np.zeros((d,), np.float32)
            )
        else:
            # warm fits run unshifted (see warm_start_params note)
            valid = None
            shift = np.zeros((d,), np.float32)
        start_it = 1
        prev_ll_resume = -np.inf
        if resumed is not None:
            step0, arrays, extra = resumed
            # checkpoints store UNSHIFTED means (resident convention)
            means = arrays["means"].astype(np.float32) - shift
            covs = arrays["covariances"].astype(np.float32)
            weights = arrays["weights"].astype(np.float32)
            prev_ll_resume = float(extra.get("prev_ll", -np.inf))
            start_it = step0 + 1
        elif warm is not None:
            weights, means, covs = warm
        else:
            means, covs, weights = _init_params(
                valid - shift, self.k, d, self.seed, self.reg_covar
            )
        means_d = jnp.asarray(means)
        covs_d = jnp.asarray(covs)
        weights_d = jnp.asarray(weights)
        shift_d = jnp.asarray(shift)
        reg = jnp.float32(self.reg_covar)

        _, b = hd.block_shape(mesh)
        n_loc = b // mesh.shape[DATA_AXIS]
        step = _make_em_stats_step(
            mesh, n_loc, self.k, d, self.chunk_rows, self.matmul_precision
        )

        ll = prev_ll_resume if np.isfinite(prev_ll_resume) else 0.0
        prev_ll = prev_ll_resume
        it = start_it - 1
        for it in range(start_it, self.max_iter + 1):
            chols = _gmm_chols(covs_d, reg)
            logw = jnp.log(weights_d)
            tot = None
            for blk in hd.blocks(mesh):
                s = step(blk.x, blk.w, shift_d, logw, means_d, chols)
                tot = s if tot is None else _gmm_add_stats(tot, s)
            nk, sums, outer, ll_dev = tot
            means_d, covs_d, weights_d = _gmm_m_step(nk, sums, outer, reg)
            ll = float(ll_dev)  # TOTAL log-likelihood — Spark tol semantics
            if ckpt is not None and it % max(self.checkpoint_every, 1) == 0:
                ckpt.save(
                    it,
                    {
                        "means": np.asarray(jax.device_get(means_d)) + shift,
                        "covariances": np.asarray(jax.device_get(covs_d)),
                        "weights": np.asarray(jax.device_get(weights_d)),
                    },
                    extra={"prev_ll": ll},
                )
            if on_iteration is not None:
                on_iteration(it, ll)
            if abs(ll - prev_ll) < self.tol:
                break
            prev_ll = ll

        return GaussianMixtureModel(
            weights=np.asarray(jax.device_get(weights_d)),
            means=np.asarray(jax.device_get(means_d)) + shift,
            covariances=np.asarray(jax.device_get(covs_d)),
            log_likelihood=ll,
            avg_log_likelihood=ll / max(n, 1.0),
            n_iter=it,
        )
