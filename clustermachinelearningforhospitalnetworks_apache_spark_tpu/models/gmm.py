"""GaussianMixture — EM with full covariances (BASELINE config 3: k=32).

Capability parity: ``pyspark.ml.clustering.GaussianMixture`` (fit/transform,
``weights``, ``gaussians`` (mean+cov), ``summary.logLikelihood``; defaults
maxIter=100, tol=0.01, full covariance).  Spark distributes the E-step and
accumulates the M-step sufficient statistics (Σr, Σr·x, Σr·xxᵀ) per
partition with ``treeAggregate``; here both steps are one jit'd pass over
the row-sharded dataset — responsibilities come from a batched
Cholesky-based log-pdf, the moment accumulations are einsums contracting
the sharded row axis (XLA inserts the psum), and the (k,d,d) refit happens
replicated on every device.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.special import logsumexp

from ..io.model_io import register_model
from ..parallel.mesh import default_mesh
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset
from .kmeans import _kmeans_pp_init, _lloyd_refine


def _chol_log_pdf(x, mean, chol):
    """Row-wise log N(x; mean, L·Lᵀ) given the Cholesky factor L (d,d)."""
    d = x.shape[-1]
    diff = x - mean[None, :]
    sol = jax.scipy.linalg.solve_triangular(chol, diff.T, lower=True).T
    maha = jnp.sum(sol * sol, axis=-1)
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
    return -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + maha)


@partial(jax.jit, static_argnames=())
def _e_step(x, w, log_weights, means, chols):
    # (n,k) component log-densities via vmap over components.
    log_pdf = jax.vmap(lambda m, L: _chol_log_pdf(x, m, L))(means, chols).T
    log_resp_un = log_pdf + log_weights[None, :]
    log_norm = logsumexp(log_resp_un, axis=1)
    resp = jnp.exp(log_resp_un - log_norm[:, None]) * w[:, None]
    log_likelihood = jnp.sum(log_norm * w)
    return resp, log_likelihood


@partial(jax.jit, static_argnames=())
def _m_step_stats(x, resp):
    # Sufficient statistics; contraction over the sharded row axis.
    nk = jnp.sum(resp, axis=0)                          # (k,)
    sums = resp.T @ x                                   # (k, d)
    outer = jnp.einsum("nk,nd,ne->kde", resp, x, x)     # (k, d, d)
    return nk, sums, outer


def _em_iteration(x, w, means, covs, weights, reg_covar, eye):
    """One full EM iteration (shared by the host loop and the device
    loop) → (means, covs, weights, total log-likelihood)."""
    chols = jnp.linalg.cholesky(covs + reg_covar * eye[None])
    resp, ll = _e_step(x, w, jnp.log(weights), means, chols)
    nk, sums, outer = _m_step_stats(x, resp)
    nk = jnp.maximum(nk, 1e-6)
    means = sums / nk[:, None]
    covs = outer / nk[:, None, None] - jnp.einsum("kd,ke->kde", means, means)
    covs = covs + reg_covar * eye[None]
    weights = nk / jnp.sum(nk)
    return means, covs, weights, ll


@partial(jax.jit, static_argnames=("max_iter",))
def _em_loop(x, w, means, covs, weights, reg_covar, tol, eye, max_iter: int):
    """The whole EM fit as one device computation (lax.while_loop) — a
    single host sync per fit; the Python loop in ``fit`` is kept only when
    checkpoint/on_iteration hooks need the host each iteration.
    Convergence matches the host loop: |ll_t − ll_{t−1}| < tol."""

    def cond(carry):
        it, _, _, _, prev_ll, ll = carry
        return (it < max_iter) & (jnp.abs(ll - prev_ll) >= tol)

    def body(carry):
        it, means, covs, weights, _, ll = carry
        means, covs, weights, new_ll = _em_iteration(
            x, w, means, covs, weights, reg_covar, eye
        )
        return it + 1, means, covs, weights, ll, new_ll

    init = (
        jnp.int32(0), means, covs, weights,
        jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
    )
    it, means, covs, weights, _, ll = lax.while_loop(cond, body, init)
    return means, covs, weights, ll, it


@register_model("GaussianMixtureModel")
@dataclass
class GaussianMixtureModel(Model):
    weights: np.ndarray      # (k,)
    means: np.ndarray        # (k, d)
    covariances: np.ndarray  # (k, d, d)
    log_likelihood: float = 0.0      # TOTAL (Spark summary.logLikelihood)
    avg_log_likelihood: float = 0.0  # per-row mean (sklearn .score parity)
    n_iter: int = 0

    @property
    def k(self) -> int:
        return self.means.shape[0]

    def _device_params(self):
        means = jnp.asarray(self.means, jnp.float32)
        chols = jnp.linalg.cholesky(jnp.asarray(self.covariances, jnp.float32))
        logw = jnp.log(jnp.asarray(self.weights, jnp.float32))
        return logw, means, chols

    def predict_proba(self, x: jax.Array) -> jax.Array:
        logw, means, chols = self._device_params()
        x = x.astype(jnp.float32)
        log_pdf = jax.vmap(lambda m, L: _chol_log_pdf(x, m, L))(means, chols).T
        log_resp = log_pdf + logw[None, :]
        return jnp.exp(log_resp - logsumexp(log_resp, axis=1)[:, None])

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_proba(x), axis=1).astype(jnp.int32)

    def score(self, data, mesh=None) -> float:
        """Mean per-row log-likelihood."""
        ds = as_device_dataset(data, mesh=mesh)
        logw, means, chols = self._device_params()
        _, ll = _e_step(ds.x.astype(jnp.float32), ds.w, logw, means, chols)
        return float(ll / jnp.maximum(jnp.sum(ds.w), 1.0))

    def _artifacts(self):
        return (
            "GaussianMixtureModel",
            {
                "log_likelihood": self.log_likelihood,
                "avg_log_likelihood": self.avg_log_likelihood,
                "n_iter": self.n_iter,
            },
            {
                "weights": np.asarray(self.weights),
                "means": np.asarray(self.means),
                "covariances": np.asarray(self.covariances),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            weights=arrays["weights"],
            means=arrays["means"],
            covariances=arrays["covariances"],
            log_likelihood=float(params.get("log_likelihood", 0.0)),
            avg_log_likelihood=float(params.get("avg_log_likelihood", 0.0)),
            n_iter=int(params.get("n_iter", 0)),
        )


@dataclass(frozen=True)
class GaussianMixture(Estimator):
    k: int = 2
    max_iter: int = 100        # Spark default
    tol: float = 0.01          # Spark default (log-likelihood delta)
    seed: int = 0
    reg_covar: float = 1e-6
    init_sample_size: int = 65536
    # Mid-training checkpointing (io/fit_checkpoint.py): commit EM state
    # (means, covariances, weights, log-likelihood) every N iterations so a
    # preempted fit resumes from the last commit.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 5

    def fit(
        self, data, label_col: str | None = None, mesh=None, on_iteration=None
    ) -> GaussianMixtureModel:
        """``on_iteration(it, log_likelihood)`` (optional) fires after every
        EM step — progress reporting and fault-injection hooks."""
        mesh = mesh or default_mesh()
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh)
        x = ds.x.astype(jnp.float32)
        w = ds.w
        d = x.shape[1]
        n = float(jax.device_get(jnp.sum(w)))
        if n == 0:
            raise ValueError("GaussianMixture fit on an empty dataset")

        ckpt = None
        resumed = None
        if self.checkpoint_dir:
            from ..io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "GaussianMixture", "k": self.k, "d": d,
                "data": data_fingerprint(x, w),
                "n_padded": ds.n_padded, "seed": self.seed,
                "reg_covar": self.reg_covar, "tol": self.tol,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()

        start_it = 1
        prev_ll = -np.inf
        if resumed is not None:
            step0, arrays, extra = resumed
            means = arrays["means"].astype(np.float32)
            covs = arrays["covariances"].astype(np.float32)
            weights = arrays["weights"].astype(np.float32)
            prev_ll = float(extra.get("prev_ll", -np.inf))
            start_it = step0 + 1
        else:
            # Init on a bounded host sample (only the sample leaves the
            # device).
            from ..parallel.sharding import sample_valid_rows

            valid = sample_valid_rows(
                DeviceDataset(x, ds.y, w), self.init_sample_size, self.seed
            )
            # k-means++ seeding + short Lloyd refinement (sklearn's
            # init_params="kmeans" equivalent) — raw ++ points alone leave
            # EM in visibly worse local optima on close blob pairs.
            means64, assign0 = _lloyd_refine(
                valid, _kmeans_pp_init(valid, self.k, self.seed), iters=10,
                return_assign=True,
            )
            means = means64.astype(np.float32)
            # Per-cluster diagonal covariance + cluster-share weights from
            # the init assignment (global variance spans the blob spread and
            # makes the first E-step responsibilities near-uniform,
            # collapsing means).
            covs = np.empty((self.k, d, d), dtype=np.float32)
            weights = np.empty((self.k,), dtype=np.float32)
            global_var = np.maximum(valid.var(axis=0), self.reg_covar)
            for j in range(self.k):
                mask = assign0 == j
                weights[j] = max(mask.mean(), 1e-6)
                if mask.sum() >= 2:
                    covs[j] = np.diag(
                        np.maximum(valid[mask].var(axis=0), self.reg_covar)
                    )
                else:
                    covs[j] = np.diag(global_var)
            weights = weights / weights.sum()

        means_d = jnp.asarray(means)
        covs_d = jnp.asarray(covs)
        weights_d = jnp.asarray(weights)
        eye = jnp.eye(d, dtype=jnp.float32)

        # A resume that lands past max_iter skips the loop entirely — seed
        # ll from the checkpoint so the returned model reports the real
        # likelihood, not 0.0.
        ll = prev_ll if np.isfinite(prev_ll) else 0.0
        it = start_it - 1
        if ckpt is None and on_iteration is None and start_it <= self.max_iter:
            # Fast path: the whole EM fit is one device computation
            # (single host sync instead of one per iteration).
            means_d, covs_d, weights_d, ll_dev, it_dev = _em_loop(
                x, w, means_d, covs_d, weights_d,
                jnp.float32(self.reg_covar), jnp.float32(self.tol), eye,
                self.max_iter - (start_it - 1),
            )
            ll = float(ll_dev)
            it = (start_it - 1) + int(it_dev)
        else:
            for it in range(start_it, self.max_iter + 1):
                means_d, covs_d, weights_d, ll_dev = _em_iteration(
                    x, w, means_d, covs_d, weights_d,
                    jnp.float32(self.reg_covar), eye,
                )
                ll = float(ll_dev)  # TOTAL log-likelihood — Spark tol here
                if ckpt is not None and it % max(self.checkpoint_every, 1) == 0:
                    ckpt.save(
                        it,
                        {
                            "means": np.asarray(jax.device_get(means_d)),
                            "covariances": np.asarray(jax.device_get(covs_d)),
                            "weights": np.asarray(jax.device_get(weights_d)),
                        },
                        extra={"prev_ll": ll},
                    )
                if on_iteration is not None:
                    on_iteration(it, ll)
                if abs(ll - prev_ll) < self.tol:
                    prev_ll = ll
                    break
                prev_ll = ll

        return GaussianMixtureModel(
            weights=np.asarray(jax.device_get(weights_d)),
            means=np.asarray(jax.device_get(means_d)),
            covariances=np.asarray(jax.device_get(covs_d)),
            log_likelihood=ll,
            avg_log_likelihood=ll / max(n, 1.0),
            n_iter=it,
        )
