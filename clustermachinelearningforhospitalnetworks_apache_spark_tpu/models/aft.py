"""AFTSurvivalRegression — Weibull accelerated-failure-time model.

Parity with ``pyspark.ml.regression.AFTSurvivalRegression``: censored
log-likelihood of ``log T = xβ + b + σ·ε`` with ε standard
extreme-value (Gumbel minimum), ``censor_col`` marking 1.0 = event
observed / 0.0 = right-censored (Spark's convention), L-BFGS over
(β, b, log σ), and ``quantile_probabilities``/``predict_quantiles``.

The per-row log-likelihood (Spark's AFTAggregator):

    z = (log y − xβ − b) / σ
    observed:  −log σ + z − eᶻ
    censored:  −eᶻ

One jitted ``optax.lbfgs`` scan over the row-sharded dataset — the
gradient reduction is the usual psum-under-GSPMD matmul, replacing
Spark's treeAggregate of hand-derived per-row gradients with
``jax.grad``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features


@partial(jax.jit, static_argnames=("max_iter", "fit_intercept"))
def _fit_aft(x, logy, censor, w, max_iter: int, fit_intercept: bool, tol=1e-6):
    d = x.shape[1]
    wsum = jnp.maximum(jnp.sum(w), 1.0)

    def loss_fn(theta):
        beta = theta[:d]
        b = theta[d] if fit_intercept else 0.0
        log_sigma = theta[-1]
        sigma = jnp.exp(log_sigma)
        z = (logy - x @ beta - b) / sigma
        ez = jnp.exp(z)
        ll = jnp.where(censor > 0, -log_sigma + z - ez, -ez)
        return -jnp.sum(ll * w) / wsum

    from ._opt import lbfgs_minimize

    theta0 = jnp.zeros((d + (2 if fit_intercept else 1),), jnp.float32)
    return lbfgs_minimize(loss_fn, theta0, max_iter, tol)


@lru_cache(maxsize=32)
def _make_block_step(d: int, fit_intercept: bool):
    """One jitted out-of-core Adam step per (d, fit_intercept), cached so
    repeated fits (CV folds, lifecycle warm retrains) reuse the traced
    executable — an inline per-fit ``@jax.jit`` closure recompiled every
    fit (ISSUE 13 ``jit-in-function``; the PR 5 retrace-per-fit class)."""
    import optax

    opt = optax.adam(1e-2)

    @jax.jit
    def block_step(theta, state, x, logy, cen, w):
        wsum = jnp.maximum(jnp.sum(w), 1.0)

        def loss_fn(t):
            beta = t[:d]
            b = t[d] if fit_intercept else 0.0
            log_sigma = t[-1]
            sigma = jnp.exp(log_sigma)
            z = (logy - x @ beta - b) / sigma
            ez = jnp.exp(z)
            ll = jnp.where(cen > 0, -log_sigma + z - ez, -ez)
            return -jnp.sum(ll * w) / wsum

        l, grads = jax.value_and_grad(loss_fn)(theta)
        updates, state_new = opt.update(grads, state)
        return optax.apply_updates(theta, updates), state_new, l

    return block_step



@register_model("AFTSurvivalRegressionModel")
@dataclass
class AFTSurvivalRegressionModel(Model):
    coefficients: np.ndarray
    intercept: float
    scale: float                      # σ (Spark's .scale)
    quantile_probabilities: tuple = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99)

    def predict(self, x: jax.Array) -> jax.Array:
        """exp(xβ + b) — Spark's ``prediction`` column (the Weibull scale
        parameter / characteristic life, NOT the distribution mean, which
        would carry an extra Γ(1+σ) factor)."""
        check_features(x, np.asarray(self.coefficients).shape[0], type(self).__name__)
        eta = jnp.asarray(x, jnp.float32) @ jnp.asarray(
            self.coefficients, jnp.float32
        ) + jnp.float32(self.intercept)
        return jnp.exp(eta)

    def predict_quantiles(self, x: jax.Array) -> jax.Array:
        """(n, len(quantile_probabilities)) survival-time quantiles:
        t_p = exp(xβ + b)·(−log(1−p))^σ (Weibull inverse CDF)."""
        check_features(
            x, np.asarray(self.coefficients).shape[0], type(self).__name__
        )
        eta = jnp.asarray(x, jnp.float32) @ jnp.asarray(
            self.coefficients, jnp.float32
        ) + jnp.float32(self.intercept)
        p = jnp.asarray(self.quantile_probabilities, jnp.float32)
        q = (-jnp.log1p(-p)) ** jnp.float32(self.scale)
        return jnp.exp(eta)[:, None] * q[None, :]

    def _artifacts(self):
        return (
            "AFTSurvivalRegressionModel",
            {
                "intercept": float(self.intercept),
                "scale": float(self.scale),
                "quantile_probabilities": list(self.quantile_probabilities),
            },
            {"coefficients": np.asarray(self.coefficients)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=arrays["coefficients"],
            intercept=float(params["intercept"]),
            scale=float(params["scale"]),
            quantile_probabilities=tuple(params.get("quantile_probabilities", ())),
        )


@dataclass(frozen=True)
class AFTSurvivalRegression(Estimator):
    """``censor_col`` rows: 1.0 = event observed, 0.0 = right-censored
    (Spark's convention).  Labels must be positive survival times."""

    censor_col: str = "censor"
    max_iter: int = 100
    fit_intercept: bool = True
    quantile_probabilities: tuple = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99)
    label_col: str = "length_of_stay"
    features_col: str = "features"

    def fit(self, data, label_col: str | None = None, mesh=None, censor=None):
        """``censor`` may be passed directly as an array for non-table
        inputs; table inputs resolve ``censor_col``."""
        from ..features.assembler import AssembledTable
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            if censor is None:
                raise ValueError(
                    "HostDataset inputs need censor= as an array (there is "
                    "no table column to resolve)"
                )
            return self._fit_outofcore(data, censor, mesh)
        if censor is None:
            if not isinstance(data, AssembledTable):
                raise ValueError(
                    f"censor_col={self.censor_col!r} needs a table input "
                    "(or pass censor= as an array)"
                )
            if self.censor_col not in data.table.schema:
                raise KeyError(
                    f"censor_col {self.censor_col!r} is not a column of the "
                    f"table; available: {data.table.schema.names}"
                )
            censor = np.asarray(data.table.column(self.censor_col), np.float32)
        censor = np.asarray(censor, np.float32)
        if not np.all(np.isin(censor, (0.0, 1.0))):
            raise ValueError("censor values must be 0.0 (censored) or 1.0 (event)")
        ds = as_device_dataset(data, label_col or self.label_col, mesh=mesh)
        n_rows = int(np.sum(np.asarray(jax.device_get(ds.w)) > 0))
        if censor.shape[0] != n_rows:
            raise ValueError(
                f"censor has {censor.shape[0]} entries but the data has "
                f"{n_rows} rows — a short censor array would silently mark "
                "the tail as censored"
            )
        if ds.y is None:
            raise ValueError("AFTSurvivalRegression needs labels (survival times)")
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        if (y_host[w_host > 0] <= 0).any():
            raise ValueError("survival times must be positive")
        cen = np.zeros((ds.n_padded,), np.float32)
        cen[: censor.shape[0]] = censor
        from ..parallel.sharding import shard_rows

        logy = jnp.log(jnp.maximum(ds.y.astype(jnp.float32), 1e-12))
        theta, _, _ = _fit_aft(
            ds.x.astype(jnp.float32), logy, shard_rows(cen, mesh),
            ds.w.astype(jnp.float32), self.max_iter, self.fit_intercept,
        )
        th = np.asarray(jax.device_get(theta), np.float64)
        d = ds.n_features
        return AFTSurvivalRegressionModel(
            coefficients=th[:d],
            intercept=float(th[d]) if self.fit_intercept else 0.0,
            scale=float(np.exp(th[-1])),
            quantile_probabilities=tuple(self.quantile_probabilities),
        )

    def _fit_outofcore(self, hd, censor, mesh=None):
        """Rows ≫ HBM Weibull AFT (VERDICT r4 weak #4): streaming
        MINIBATCH Adam on the censored log-likelihood — each epoch scans
        the ``max_device_rows`` host blocks (shuffled per epoch; the
        censor column is sliced per block on host alongside them).  The
        resident path keeps the full-batch L-BFGS; this path trades
        solver parity for bounded device memory, converging to the same
        optimum statistically.  ``max_iter`` counts epochs."""
        import optax

        from ..parallel.mesh import default_mesh

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError(
                "AFTSurvivalRegression needs labels (survival times): "
                "HostDataset(y=...)"
            )
        censor = np.asarray(censor, np.float32)
        if not np.all(np.isin(censor, (0.0, 1.0))):
            raise ValueError("censor values must be 0.0 (censored) or 1.0 (event)")
        if censor.shape[0] != hd.n:
            raise ValueError(
                f"censor has {censor.shape[0]} entries but the data has "
                f"{hd.n} rows — a short censor array would silently mark "
                "the tail as censored"
            )
        y_host = np.asarray(hd.y)
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        if y_host[w_host > 0].size == 0:
            raise ValueError("AFTSurvivalRegression fit on an empty dataset")
        if (y_host[w_host > 0] <= 0).any():
            raise ValueError("survival times must be positive")

        d = hd.n_features
        theta = jnp.zeros((d + (2 if self.fit_intercept else 1),), jnp.float32)
        opt = optax.adam(1e-2)
        state = opt.init(theta)
        block_step = _make_block_step(d, self.fit_intercept)

        n_blocks, b = hd.block_shape(mesh)
        shuffle = np.random.default_rng(1)
        for _ in range(self.max_iter):
            perm = shuffle.permutation(n_blocks)
            for i, blk in zip(perm, hd.blocks(mesh, order=perm)):
                s, e = int(i) * b, min(int(i) * b + b, hd.n)
                cb = np.zeros((b,), np.float32)
                cb[: e - s] = censor[s:e]
                from ..parallel.sharding import shard_rows

                block_step_out = block_step(
                    theta, state,
                    blk.x.astype(jnp.float32),
                    jnp.log(jnp.maximum(blk.y.astype(jnp.float32), 1e-12)),
                    shard_rows(cb, mesh),
                    blk.w.astype(jnp.float32),
                )
                theta, state, _ = block_step_out
        th = np.asarray(jax.device_get(theta), np.float64)
        return AFTSurvivalRegressionModel(
            coefficients=th[:d],
            intercept=float(th[d]) if self.fit_intercept else 0.0,
            scale=float(np.exp(th[-1])),
            quantile_probabilities=tuple(self.quantile_probabilities),
        )
