"""LDA — latent Dirichlet allocation (``pyspark.ml.clustering.LDA``).

Online variational Bayes (Hoffman, Blei & Bach 2010) — the algorithm
behind Spark's default ``optimizer="online"``.  Spark runs it as RDD
mini-batches with a driver-side λ update; here each iteration is one
jitted pass over the row-sharded document-term matrix:

- E-step: every document's variational γ runs as a FIXED number of
  batched fixed-point sweeps (``lax.fori_loop``) of
  ``γ = α + (counts · φ)`` with φ ∝ exp(E[log θ])·exp(E[log β]) — all
  documents at once, two matmuls per sweep on the MXU (the classic
  Blei-code vectorization: work with the (n, k) and (k, v) expected-log
  matrices, never materialize per-word φ).
- M-step: λ ← (1−ρ)λ + ρ·λ̂ with ρ_t = (τ₀+t)^{−κ} (Spark's
  learningOffset/learningDecay defaults 1024/0.51).

``transform`` returns per-document topic mixtures; ``describe_topics``
and the variational ``log_perplexity`` bound mirror Spark's surface.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features


def _dirichlet_expectation(a):
    """Row-wise E[log X] for X ~ Dir(a) on a 2-D parameter matrix:
    digamma(a) − digamma(Σ_row a)."""
    return jax.scipy.special.digamma(a) - jax.scipy.special.digamma(
        jnp.sum(a, axis=-1, keepdims=True)
    )


@partial(jax.jit, static_argnames=("n_sweeps",))
def _e_step(counts, w, expelog_beta, alpha, n_sweeps: int):
    """Batched variational E-step.

    counts: (n, v) document-term matrix (pad rows w=0 are inert);
    expelog_beta: (k, v) exp(E[log β]).  → (γ (n, k), sstats (k, v)).
    """
    n, v = counts.shape
    k = expelog_beta.shape[0]
    gamma0 = jnp.ones((n, k), jnp.float32)

    def sweep(_, gamma):
        expelog_theta = jnp.exp(_dirichlet_expectation(gamma))    # (n, k)
        # φ normalizer per (doc, word): Σ_k expelogθ·expelogβ
        norm = expelog_theta @ expelog_beta + 1e-30               # (n, v)
        gamma = alpha + expelog_theta * (
            (counts / norm) @ expelog_beta.T
        )
        return gamma

    gamma = lax.fori_loop(0, n_sweeps, sweep, gamma0)
    expelog_theta = jnp.exp(_dirichlet_expectation(gamma))
    norm = expelog_theta @ expelog_beta + 1e-30
    # sufficient statistics for λ̂: sstats[k, w] = Σ_d φ_dwk·counts (before
    # the final expelog_beta factor, which multiplies back in the M-step)
    sstats = expelog_theta.T @ ((counts * w[:, None]) / norm)     # (k, v)
    return gamma, sstats


@register_model("LDAModel")
@dataclass
class LDAModel(Model):
    lam: np.ndarray                  # (k, v) topic-word Dirichlet params
    alpha: float
    eta: float
    n_docs_trained: float = 0.0
    e_step_sweeps: int = 50          # inference sweeps (fit-time setting)

    @property
    def k(self) -> int:
        return self.lam.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.lam.shape[1]

    def topics_matrix(self) -> np.ndarray:
        """(vocab, k) column-normalized topic-word probabilities (Spark's
        ``topicsMatrix`` orientation)."""
        t = np.asarray(self.lam, np.float64)
        return (t / t.sum(axis=1, keepdims=True)).T

    def describe_topics(self, max_terms: int = 10):
        """[(term indices, weights), ...] per topic — Spark's surface."""
        probs = self.topics_matrix().T        # (k, v)
        out = []
        for kk in range(self.k):
            idx = np.argsort(probs[kk])[::-1][:max_terms]
            out.append((idx.astype(np.int64), probs[kk][idx]))
        return out

    def _expelog_beta(self):
        lam = jnp.asarray(self.lam, jnp.float32)
        return jnp.exp(_dirichlet_expectation(lam))

    def transform(self, counts, mesh=None) -> np.ndarray:
        """(n, k) normalized per-document topic mixtures (Spark's
        ``topicDistribution`` column)."""
        x = jnp.asarray(counts, jnp.float32)
        check_features(x, self.vocab_size, "LDAModel")
        gamma, _ = _e_step(
            x, jnp.ones((x.shape[0],), jnp.float32), self._expelog_beta(),
            jnp.float32(self.alpha), self.e_step_sweeps,
        )
        g = np.asarray(jax.device_get(gamma), np.float64)
        return g / g.sum(axis=1, keepdims=True)

    def log_perplexity(self, counts) -> float:
        """Upper bound on per-token perplexity via the variational bound
        (lower is better; Spark's ``logPerplexity`` analogue)."""
        x = jnp.asarray(counts, jnp.float32)
        check_features(x, self.vocab_size, "LDAModel")
        gamma, _ = _e_step(
            x, jnp.ones((x.shape[0],), jnp.float32), self._expelog_beta(),
            jnp.float32(self.alpha), self.e_step_sweeps,
        )
        expelog_theta = jnp.exp(_dirichlet_expectation(gamma))
        norm = expelog_theta @ self._expelog_beta() + 1e-30
        ll = jnp.sum(x * jnp.log(norm))
        tokens = jnp.maximum(jnp.sum(x), 1.0)
        return float(-ll / tokens)

    def _artifacts(self):
        return (
            "LDAModel",
            {
                "alpha": float(self.alpha),
                "eta": float(self.eta),
                "n_docs_trained": float(self.n_docs_trained),
                "e_step_sweeps": int(self.e_step_sweeps),
            },
            {"lam": np.asarray(self.lam)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            lam=arrays["lam"],
            alpha=float(params["alpha"]),
            eta=float(params["eta"]),
            n_docs_trained=float(params.get("n_docs_trained", 0.0)),
            e_step_sweeps=int(params.get("e_step_sweeps", 50)),
        )


@dataclass(frozen=True)
class LDA(Estimator):
    """Spark defaults: k 10, maxIter 20, docConcentration α = 1/k,
    topicConcentration η = 1/k, learningOffset 1024, learningDecay 0.51,
    optimizer "online" (the one implemented)."""

    k: int = 10
    max_iter: int = 20
    doc_concentration: float | None = None      # None → 1/k (Spark auto)
    topic_concentration: float | None = None    # None → 1/k
    learning_offset: float = 1024.0
    learning_decay: float = 0.51
    e_step_sweeps: int = 50
    optimizer: str = "online"
    seed: int = 0

    def fit(self, counts, label_col: str | None = None, mesh=None) -> LDAModel:
        """``counts``: (n_docs, vocab) term-count matrix (CountVectorizer
        output shape) or a DeviceDataset of the same."""
        if self.optimizer != "online":
            raise ValueError(
                f"optimizer must be 'online' (Spark's default; EM is not "
                f"implemented); got {self.optimizer!r}"
            )
        if self.k < 2:
            raise ValueError(f"k must be >= 2, got {self.k}")
        from ..parallel.outofcore import HostDataset

        if isinstance(counts, HostDataset):
            return self._fit_outofcore(counts, mesh)
        ds = as_device_dataset(counts, mesh=mesh)
        x_host_min = float(jax.device_get(jnp.min(ds.x)))
        if x_host_min < 0:
            raise ValueError("LDA needs a non-negative term-count matrix")
        n, v = int(jax.device_get(jnp.sum((ds.w > 0)))), ds.n_features
        if n == 0:
            raise ValueError("LDA fit on an empty dataset")
        alpha = self.doc_concentration if self.doc_concentration is not None else 1.0 / self.k
        eta = self.topic_concentration if self.topic_concentration is not None else 1.0 / self.k

        rng = np.random.default_rng(self.seed)
        lam = jnp.asarray(
            rng.gamma(100.0, 1.0 / 100.0, size=(self.k, v)).astype(np.float32)
        )
        x = ds.x.astype(jnp.float32)
        w = ds.w.astype(jnp.float32)
        for t in range(self.max_iter):
            expelog_beta = jnp.exp(_dirichlet_expectation(lam))
            _, sstats = _e_step(
                x, w, expelog_beta, jnp.float32(alpha), self.e_step_sweeps
            )
            lam_hat = eta + sstats * expelog_beta
            rho = (self.learning_offset + t) ** (-self.learning_decay)
            lam = (1.0 - rho) * lam + rho * lam_hat
        return LDAModel(
            lam=np.asarray(jax.device_get(lam)),
            alpha=float(alpha),
            eta=float(eta),
            n_docs_trained=float(n),
            e_step_sweeps=self.e_step_sweeps,
        )

    def _fit_outofcore(self, hd, mesh=None) -> LDAModel:
        """Docs ≫ HBM online VB — this is Hoffman's algorithm in its
        NATIVE form: each update consumes one minibatch (here: one
        streamed host block) with sufficient statistics scaled by
        n/|batch|, blended at rate ρ_t.  The resident path trains
        full-batch (every doc in every update); both converge to the
        same variational objective, and Spark's online optimizer is
        itself the minibatch form (miniBatchFraction).  Each block step
        counts as one iteration (Spark's convention too)."""
        from ..parallel.mesh import default_mesh

        mesh = mesh or default_mesh()
        if np.min(hd.x) < 0:
            raise ValueError("LDA needs a non-negative term-count matrix")
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        n = int(np.sum(w_host > 0))
        if n == 0:
            raise ValueError("LDA fit on an empty dataset")
        v = hd.n_features
        alpha = (
            self.doc_concentration
            if self.doc_concentration is not None
            else 1.0 / self.k
        )
        eta = (
            self.topic_concentration
            if self.topic_concentration is not None
            else 1.0 / self.k
        )
        rng = np.random.default_rng(self.seed)
        lam = jnp.asarray(
            rng.gamma(100.0, 1.0 / 100.0, size=(self.k, v)).astype(np.float32)
        )
        n_blocks, b = hd.block_shape(mesh)
        shuffle = np.random.default_rng(self.seed + 1)
        t = 0
        while t < self.max_iter:
            perm = shuffle.permutation(n_blocks)
            for i, blk in zip(perm, hd.blocks(mesh, order=perm)):
                if t >= self.max_iter:
                    break
                s, e = int(i) * b, min(int(i) * b + b, hd.n)
                bsz = max(float(np.sum(w_host[s:e] > 0)), 1.0)
                expelog_beta = jnp.exp(_dirichlet_expectation(lam))
                _, sstats = _e_step(
                    blk.x.astype(jnp.float32), blk.w.astype(jnp.float32),
                    expelog_beta, jnp.float32(alpha), self.e_step_sweeps,
                )
                lam_hat = eta + (n / bsz) * sstats * expelog_beta
                rho = (self.learning_offset + t) ** (-self.learning_decay)
                lam = (1.0 - rho) * lam + rho * lam_hat
                t += 1
        return LDAModel(
            lam=np.asarray(jax.device_get(lam)),
            alpha=float(alpha),
            eta=float(eta),
            n_docs_trained=float(n),
            e_step_sweeps=self.e_step_sweeps,
        )
