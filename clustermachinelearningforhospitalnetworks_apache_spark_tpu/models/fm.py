"""Factorization machines — FMRegressor / FMClassifier.

Parity with ``pyspark.ml.regression.FMRegressor`` and
``...classification.FMClassifier`` (Rendle's 2nd-order FM):

    ŷ(x) = w₀ + wᵀx + ½ Σ_f [(x·V)_f² − (x²·V²)_f]

The pairwise term is exactly two MXU matmuls (``X@V`` and ``X²@V²``) —
the O(n·d·k) linear-time identity Rendle derived is literally the
TPU-friendly form, no pairwise d² blowup.  Training is full-batch Adam
(one jitted ``lax.scan``; Spark trains miniBatchFraction-SGD/AdamW —
full-batch on an accelerator converges in fewer, cheaper passes), with
squared loss (regressor) or logistic loss on ±1 labels (classifier),
L2 ``reg_param`` on w and V (intercept unpenalized, the house rule).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features


def _fm_raw(w0, w, v, x):
    """(n,) FM response: bias + linear + ½((xV)² − x²V²)·1."""
    xv = x @ v                                   # (n, k)
    x2v2 = (x * x) @ (v * v)                     # (n, k)
    return w0 + x @ w + 0.5 * jnp.sum(xv * xv - x2v2, axis=1)


@partial(jax.jit, static_argnames=("max_iter", "loss"))
def _fit_fm(w0, w, v, x, y, wt, reg, step_size, max_iter: int, loss: str):
    import optax

    wsum = jnp.maximum(jnp.sum(wt), 1.0)

    def loss_fn(params):
        w0_, w_, v_ = params
        raw = _fm_raw(w0_, w_, v_, x)
        if loss == "squared":
            per_row = (raw - y) ** 2
        else:  # logistic on ±1 labels — softplus(−m) is the
            # overflow-stable spelling of log(1 + e^{−m})
            ypm = 2.0 * y - 1.0
            per_row = jax.nn.softplus(-ypm * raw)
        data = jnp.sum(per_row * wt) / wsum
        return data + reg * (jnp.sum(w_ * w_) + jnp.sum(v_ * v_))

    opt = optax.adam(step_size)
    state = opt.init((w0, w, v))

    def step(carry, _):
        params, st = carry
        l, grads = jax.value_and_grad(loss_fn)(params)
        updates, st = opt.update(grads, st)
        return (optax.apply_updates(params, updates), st), l

    (params, _), losses = jax.lax.scan(
        step, ((w0, w, v), state), None, length=max_iter
    )
    return params, losses


@lru_cache(maxsize=32)
def _make_block_step(loss: str, step_size: float, reg_param: float):
    """One jitted out-of-core Adam step per (loss, step_size, reg) —
    cached so repeated fits reuse the traced executable instead of
    rebuilding a per-fit ``@jax.jit`` closure (ISSUE 13
    ``jit-in-function``; the PR 5 retrace-per-fit class)."""
    import optax

    opt = optax.adam(step_size)
    reg = jnp.float32(reg_param)

    @jax.jit
    def block_step(params, state, x, y, wt):
        wsum = jnp.maximum(jnp.sum(wt), 1.0)

        def loss_fn(p):
            w0_, w_, v_ = p
            raw = _fm_raw(w0_, w_, v_, x)
            if loss == "squared":
                per_row = (raw - y) ** 2
            else:
                ypm = 2.0 * y - 1.0
                per_row = jax.nn.softplus(-ypm * raw)
            data = jnp.sum(per_row * wt) / wsum
            return data + reg * (jnp.sum(w_ * w_) + jnp.sum(v_ * v_))

        l, grads = jax.value_and_grad(loss_fn)(params)
        updates, state_new = opt.update(grads, state)
        return optax.apply_updates(params, updates), state_new, l

    return block_step



@register_model("FMModel")
@dataclass
class FMModel(Model):
    intercept: float
    linear: np.ndarray            # (d,)
    factors: np.ndarray           # (d, k)
    task: str = "regression"      # "regression" | "classification"

    @property
    def factor_size(self) -> int:
        return self.factors.shape[1]

    def predict_raw(self, x: jax.Array) -> jax.Array:
        check_features(x, np.asarray(self.linear).shape[0], "FMModel")
        return _fm_raw(
            jnp.float32(self.intercept),
            jnp.asarray(self.linear, jnp.float32),
            jnp.asarray(self.factors, jnp.float32),
            jnp.asarray(x, jnp.float32),
        )

    def predict_proba(self, x: jax.Array) -> jax.Array:
        if self.task != "classification":
            raise ValueError("predict_proba is classification-only")
        return jax.nn.sigmoid(self.predict_raw(x))

    def predict(self, x: jax.Array) -> jax.Array:
        raw = self.predict_raw(x)
        if self.task == "regression":
            return raw
        return (raw > 0).astype(jnp.float32)

    def _artifacts(self):
        return (
            "FMModel",
            {"intercept": float(self.intercept), "task": self.task},
            {"linear": np.asarray(self.linear), "factors": np.asarray(self.factors)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            intercept=float(params["intercept"]),
            linear=arrays["linear"],
            factors=arrays["factors"],
            task=params.get("task", "regression"),
        )


@dataclass(frozen=True)
class _FMParams:
    factor_size: int = 8          # Spark default
    max_iter: int = 100           # Spark default
    reg_param: float = 0.0
    step_size: float = 0.05       # full-batch Adam LR (Spark SGD: 1.0)
    init_std: float = 0.01        # Spark default
    seed: int = 0
    label_col: str = "length_of_stay"
    features_col: str = "features"
    weight_col: str | None = None

    def _fit(self, data, label_col, mesh, loss: str) -> FMModel:
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh, loss)
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        if ds.y is None:
            raise ValueError("FM fit needs labels")
        if self.factor_size < 1:
            raise ValueError(f"factor_size must be >= 1, got {self.factor_size}")
        if loss == "logistic":
            yv = np.asarray(jax.device_get(ds.y))
            wv = np.asarray(jax.device_get(ds.w))
            uniq = np.unique(yv[wv > 0])
            if not np.all(np.isin(uniq, (0.0, 1.0))):
                raise ValueError(
                    f"FMClassifier is binary (labels 0/1); got {uniq[:5]}"
                )
        rng = np.random.default_rng(self.seed)
        d = ds.n_features
        w0 = jnp.float32(0.0)
        w = jnp.zeros((d,), jnp.float32)
        v = jnp.asarray(
            rng.normal(0, self.init_std, size=(d, self.factor_size)).astype(
                np.float32
            )
        )
        (w0, w, v), _ = _fit_fm(
            w0, w, v, ds.x.astype(jnp.float32), ds.y.astype(jnp.float32),
            ds.w.astype(jnp.float32), jnp.float32(self.reg_param),
            jnp.float32(self.step_size), self.max_iter, loss,
        )
        return FMModel(
            intercept=float(w0),
            linear=np.asarray(jax.device_get(w)),
            factors=np.asarray(jax.device_get(v)),
            task="regression" if loss == "squared" else "classification",
        )


    def _fit_outofcore(self, hd, mesh, loss: str) -> FMModel:
        """Rows ≫ HBM (VERDICT r4 #5): streaming MINIBATCH Adam — each
        epoch scans the ``max_device_rows`` host blocks through the mesh,
        one Adam step per block on the block's weighted-mean loss.  This
        is Spark's own ``miniBatchFraction`` SGD shape (the resident path
        upgrades to full-batch Adam because the whole matrix is on
        device); the two paths converge to the same optimum statistically
        but are not step-for-step identical.  ``max_iter`` counts epochs
        here (full sweeps), matching the resident pass count."""
        import optax

        from ..parallel.mesh import default_mesh

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError("FM fit needs labels: HostDataset(y=...)")
        if hd.n == 0 or hd.count() == 0.0:
            raise ValueError("FM fit on an empty dataset")
        if self.factor_size < 1:
            raise ValueError(f"factor_size must be >= 1, got {self.factor_size}")
        if loss == "logistic":
            w_host = (
                np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
            )
            uniq = np.unique(np.asarray(hd.y)[w_host > 0])
            if not np.all(np.isin(uniq, (0.0, 1.0))):
                raise ValueError(
                    f"FMClassifier is binary (labels 0/1); got {uniq[:5]}"
                )
        rng = np.random.default_rng(self.seed)
        d = hd.n_features
        params = (
            jnp.float32(0.0),
            jnp.zeros((d,), jnp.float32),
            jnp.asarray(
                rng.normal(0, self.init_std, size=(d, self.factor_size)).astype(
                    np.float32
                )
            ),
        )
        opt = optax.adam(self.step_size)
        state = opt.init(params)
        block_step = _make_block_step(
            loss, float(self.step_size), float(self.reg_param)
        )

        n_blocks, _ = hd.block_shape(mesh)
        shuffle = np.random.default_rng(self.seed + 1)
        for _ in range(self.max_iter):
            # fresh block order per epoch: rows grouped on disk (e.g.
            # label-sorted ETL output) must not make every epoch end on
            # the same class (standard minibatch-SGD shuffling)
            for blk in hd.blocks(mesh, order=shuffle.permutation(n_blocks)):
                params, state, _ = block_step(
                    params, state,
                    blk.x.astype(jnp.float32), blk.y.astype(jnp.float32),
                    blk.w.astype(jnp.float32),
                )
        w0, w, v = params
        return FMModel(
            intercept=float(w0),
            linear=np.asarray(jax.device_get(w)),
            factors=np.asarray(jax.device_get(v)),
            task="regression" if loss == "squared" else "classification",
        )


@dataclass(frozen=True)
class FMRegressor(Estimator, _FMParams):
    def fit(self, data, label_col: str | None = None, mesh=None) -> FMModel:
        return self._fit(data, label_col, mesh, "squared")


@dataclass(frozen=True)
class FMClassifier(Estimator, _FMParams):
    label_col: str = "LOS_binary"

    def fit(self, data, label_col: str | None = None, mesh=None) -> FMModel:
        return self._fit(data, label_col, mesh, "logistic")
