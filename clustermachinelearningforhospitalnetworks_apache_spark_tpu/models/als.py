"""ALS — collaborative filtering (``pyspark.ml.recommendation.ALS``).

The one MLlib estimator family the rest of the framework didn't cover:
alternating least squares over (user, item, rating) triplets, explicit
(ALS-WR, Zhou et al. — Spark's default: per-row regularization scaled by
the rating count) and implicit preference (Hu-Koren confidence weighting,
Spark's ``implicitPrefs=True``).

Spark alternates distributed least-squares solves, shipping factor blocks
between executors per iteration.  The TPU-native shape inverts that into
dense batched linear algebra on static shapes:

- Ratings are grouped per user (then per item) into a PADDED index matrix
  ``(U, C)`` of rated-item ids plus a mask — the same weighted-padding
  rule every estimator here uses for rows.  C is the max per-user count;
  padding entries carry weight 0.
- One half-step gathers the opposite factors ``Y[idx] -> (U, C, f)``,
  builds every user's normal equations with two batched einsums
  (``A_u = Σ m·y yᵀ + λ n_u I``, ``b_u = Σ m r y``) and solves all users
  at once with a batched Cholesky solve (``jnp.linalg.solve`` on
  ``(U, f, f)``) — MXU matmuls + a vectorized small solve, no per-user
  Python.
- Implicit mode follows Hu-Koren: ``A_u = YᵀY + Σ α r yᵀy + λI``,
  ``b_u = Σ (1 + α r) y`` over OBSERVED items only, with the dense
  ``YᵀY`` term computed once per half-step (the classic trick that keeps
  the unobserved-pair sum out of the loop).

Factors stay device-resident across iterations; the index/rating
matrices are built once on host.  ``predict``/``recommend_for_all_users``
are one matmul (+ ``lax.top_k``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model


def _group_ratings(ids: np.ndarray, other: np.ndarray, ratings: np.ndarray, n: int):
    """Triplets grouped by ``ids`` → padded (n, C) index/rating/mask."""
    order = np.argsort(ids, kind="stable")
    sid = ids[order]
    counts = np.bincount(sid, minlength=n)
    c = max(int(counts.max()), 1) if len(ids) else 1
    idx = np.zeros((n, c), np.int32)
    val = np.zeros((n, c), np.float32)
    msk = np.zeros((n, c), np.float32)
    starts = np.r_[0, np.cumsum(counts)[:-1]]
    pos = np.arange(len(ids)) - starts[sid]
    idx[sid, pos] = other[order]
    val[sid, pos] = ratings[order]
    msk[sid, pos] = 1.0
    return idx, val, msk, counts.astype(np.float32)


@partial(jax.jit, static_argnames=("rank",), donate_argnums=())
def _solve_explicit(y, idx, val, msk, cnt, reg, rank: int):
    """ALS-WR half-step: solve every row's (A, b) at once.

    y: (m, f) opposite factors; idx/val/msk: (n, C); cnt: (n,)
    A_u = Σ_c m·y yᵀ + λ·n_u·I  (λ·n_u — Spark's ALS-WR scaling)
    """
    g = y[idx]                                       # (n, C, f)
    gm = g * msk[..., None]
    a = jnp.einsum("ncf,ncg->nfg", gm, g)            # (n, f, f)
    b = jnp.einsum("ncf,nc->nf", gm, val)            # (n, f)
    lam = reg * jnp.maximum(cnt, 1.0)
    a = a + lam[:, None, None] * jnp.eye(rank, dtype=y.dtype)[None]
    return jnp.linalg.solve(a, b[..., None])[..., 0]


@partial(jax.jit, static_argnames=("rank",))
def _solve_implicit(y, idx, val, msk, reg, alpha, rank: int):
    """Hu-Koren half-step: confidence c = 1 + α·r on observed pairs, all
    unobserved pairs carry preference 0 at confidence 1 — absorbed by the
    dense YᵀY term so only observed items enter the batched sums.
    Regularization scales by the per-row count of POSITIVE ratings
    (Spark's als.scala ``numExplicits · regParam``, the same ALS-WR
    weighting as the explicit path)."""
    yty = y.T @ y                                     # (f, f), once
    g = y[idx]                                        # (n, C, f)
    conf_extra = alpha * val * msk                    # c − 1 on observed
    a = yty[None] + jnp.einsum(
        "ncf,nc,ncg->nfg", g, conf_extra, g
    )
    pref = (val > 0).astype(y.dtype) * msk
    n_pos = jnp.sum(pref, axis=1)
    lam = reg * jnp.maximum(n_pos, 1.0)
    a = a + lam[:, None, None] * jnp.eye(rank, dtype=y.dtype)[None]
    b = jnp.einsum("ncf,nc->nf", g, pref * (1.0 + alpha * val))
    return jnp.linalg.solve(a, b[..., None])[..., 0]


@register_model("ALSModel")
@dataclass
class ALSModel(Model):
    user_factors: np.ndarray      # (num_users, rank)
    item_factors: np.ndarray      # (num_items, rank)
    # ids seen at fit time (Spark's coldStartStrategy decides the rest)
    cold_start_strategy: str = "nan"

    @property
    def rank(self) -> int:
        return self.user_factors.shape[1]

    def predict(self, user_ids, item_ids) -> np.ndarray:
        """Per-pair predicted ratings; unseen ids follow
        ``cold_start_strategy``: "nan" marks them NaN, "drop" removes the
        pairs (Spark's two strategies)."""
        u = np.asarray(user_ids, np.int64)
        i = np.asarray(item_ids, np.int64)
        if u.shape != i.shape:
            raise ValueError(f"user/item id shapes differ: {u.shape} vs {i.shape}")
        known = (
            (u >= 0) & (u < self.user_factors.shape[0])
            & (i >= 0) & (i < self.item_factors.shape[0])
        )
        uf = self.user_factors[np.clip(u, 0, self.user_factors.shape[0] - 1)]
        vf = self.item_factors[np.clip(i, 0, self.item_factors.shape[0] - 1)]
        pred = np.einsum("nf,nf->n", uf, vf)
        if self.cold_start_strategy == "drop":
            return pred[known]
        pred = pred.astype(np.float64)
        pred[~known] = np.nan
        return pred

    def recommend_for_all_users(self, num_items: int):
        """→ (item ids (U, k), scores (U, k)) — one matmul + top_k."""
        scores = jnp.asarray(self.user_factors) @ jnp.asarray(self.item_factors).T
        k = min(num_items, self.item_factors.shape[0])
        top, ids = lax.top_k(scores, k)
        return np.asarray(ids), np.asarray(top)

    def recommend_for_all_items(self, num_users: int):
        scores = jnp.asarray(self.item_factors) @ jnp.asarray(self.user_factors).T
        k = min(num_users, self.user_factors.shape[0])
        top, ids = lax.top_k(scores, k)
        return np.asarray(ids), np.asarray(top)

    def _artifacts(self):
        return (
            "ALSModel",
            {"cold_start_strategy": self.cold_start_strategy},
            {
                "user_factors": np.asarray(self.user_factors),
                "item_factors": np.asarray(self.item_factors),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            user_factors=arrays["user_factors"],
            item_factors=arrays["item_factors"],
            cold_start_strategy=params.get("cold_start_strategy", "nan"),
        )


@dataclass(frozen=True)
class ALS(Estimator):
    """Spark defaults: rank 10, maxIter 10, regParam 0.1, alpha 1.0,
    implicitPrefs False, coldStartStrategy "nan".  ``nonnegative`` is the
    one Spark param not supported (projected-gradient NNLS is a different
    solver); it raises rather than silently ignoring."""

    rank: int = 10
    max_iter: int = 10
    reg_param: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 1.0
    seed: int = 0
    cold_start_strategy: str = "nan"
    nonnegative: bool = False

    def fit(self, ratings, label_col: str | None = None, mesh=None) -> ALSModel:
        """``ratings``: (user, item, rating) as a 3-tuple of arrays, an
        (n, 3) array, or a Table with user/item/rating columns."""
        if self.nonnegative:
            raise NotImplementedError(
                "nonnegative=True (Spark's NNLS solver) is not supported; "
                "use the default least-squares solver"
            )
        if self.cold_start_strategy not in ("nan", "drop"):
            raise ValueError(
                f"cold_start_strategy must be nan|drop, got "
                f"{self.cold_start_strategy!r}"
            )
        users, items, vals = self._coerce(ratings)
        if len(users) == 0:
            raise ValueError("ALS fit on an empty rating set")
        if self.implicit_prefs and (vals < 0).any():
            raise ValueError("implicit_prefs=True needs non-negative ratings")
        n_users = int(users.max()) + 1
        n_items = int(items.max()) + 1

        u_idx, u_val, u_msk, u_cnt = _group_ratings(users, items, vals, n_users)
        i_idx, i_val, i_msk, i_cnt = _group_ratings(items, users, vals, n_items)

        rng = np.random.default_rng(self.seed)
        # Spark seeds factors with scaled |N(0,1)|-ish draws; scale keeps
        # initial predictions O(mean rating)
        scale = 1.0 / np.sqrt(self.rank)
        uf = jnp.asarray(
            rng.normal(0, scale, size=(n_users, self.rank)).astype(np.float32)
        )
        vf = jnp.asarray(
            rng.normal(0, scale, size=(n_items, self.rank)).astype(np.float32)
        )
        reg = jnp.float32(self.reg_param)
        alpha = jnp.float32(self.alpha)
        # the index/rating/mask matrices never change: one transfer each
        u_idx, u_val, u_msk, u_cnt = (
            jnp.asarray(a) for a in (u_idx, u_val, u_msk, u_cnt)
        )
        i_idx, i_val, i_msk, i_cnt = (
            jnp.asarray(a) for a in (i_idx, i_val, i_msk, i_cnt)
        )

        for _ in range(self.max_iter):
            if self.implicit_prefs:
                uf = _solve_implicit(
                    vf, u_idx, u_val, u_msk, reg, alpha, self.rank
                )
                vf = _solve_implicit(
                    uf, i_idx, i_val, i_msk, reg, alpha, self.rank
                )
            else:
                uf = _solve_explicit(
                    vf, u_idx, u_val, u_msk, u_cnt, reg, self.rank
                )
                vf = _solve_explicit(
                    uf, i_idx, i_val, i_msk, i_cnt, reg, self.rank
                )
        return ALSModel(
            user_factors=np.asarray(jax.device_get(uf)),
            item_factors=np.asarray(jax.device_get(vf)),
            cold_start_strategy=self.cold_start_strategy,
        )

    @staticmethod
    def _coerce(ratings):
        from ..core.table import Table

        if isinstance(ratings, Table):
            cols = ratings.columns
            need = [c for c in ("user", "item", "rating") if c not in cols]
            if need:
                raise ValueError(
                    f"ALS table input needs user/item/rating columns; "
                    f"missing {need} (have {sorted(cols)})"
                )
            u = np.asarray(ratings.column("user"))
            i = np.asarray(ratings.column("item"))
            r = np.asarray(ratings.column("rating"), np.float32)
        elif isinstance(ratings, tuple) and len(ratings) == 3:
            u, i, r = (np.asarray(a) for a in ratings)
            r = r.astype(np.float32)
        else:
            arr = np.asarray(ratings)
            if arr.ndim != 2 or arr.shape[1] != 3:
                raise ValueError(
                    "ALS expects (user, item, rating) arrays, an (n, 3) "
                    f"matrix, or a Table; got shape {getattr(arr, 'shape', None)}"
                )
            u, i, r = arr[:, 0], arr[:, 1], arr[:, 2].astype(np.float32)
        ui = np.asarray(u)
        ii = np.asarray(i)
        if len(ui) and (np.min(ui) < 0 or np.min(ii) < 0):
            raise ValueError("ALS ids must be non-negative integers")
        return ui.astype(np.int64), ii.astype(np.int64), np.asarray(r, np.float32)
