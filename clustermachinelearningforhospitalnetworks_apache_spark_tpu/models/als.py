"""ALS — collaborative filtering (``pyspark.ml.recommendation.ALS``).

The one MLlib estimator family the rest of the framework didn't cover:
alternating least squares over (user, item, rating) triplets, explicit
(ALS-WR, Zhou et al. — Spark's default: per-row regularization scaled by
the rating count) and implicit preference (Hu-Koren confidence weighting,
Spark's ``implicitPrefs=True``).

Spark alternates distributed least-squares solves, shipping factor blocks
between executors per iteration.  The TPU-native shape inverts that into
dense batched linear algebra on static shapes:

- Ratings are grouped per user (then per item) into COUNT-CAPPED padded
  buckets (:func:`_group_ratings_bucketed`): rows are binned by rating
  count into power-of-4 caps, each bucket a dense ``(U_b, C_b)`` index/
  rating/mask block.  Total padded cells stay ≤ 4× nnz, so one
  power-law user cannot inflate the whole gather (a single global
  ``(U, C, f)`` with C = max count would be ~10³× too big on skewed
  data).
- One half-step gathers the opposite factors ``Y[idx] -> (U_b, C_b, f)``,
  builds every user's normal equations with two batched einsums
  (``A_u = Σ m·y yᵀ + λ n_u I``, ``b_u = Σ m r y``) and solves each
  bucket's users at once with a batched Cholesky solve
  (``jnp.linalg.solve`` on ``(U_b, f, f)``) — MXU matmuls + a vectorized
  small solve, no per-user Python.
- Implicit mode follows Hu-Koren: ``A_u = YᵀY + Σ α r yᵀy + λI``,
  ``b_u = Σ (1 + α r) y`` over OBSERVED items only, with the dense
  ``YᵀY`` term computed once per half-step (the classic trick that keeps
  the unobserved-pair sum out of the loop).
- With a ``mesh``, each bucket's rows are SHARDED across the ``data``
  axis (every device solves its slice of the normal equations — the
  analogue of Spark's in-link blocks on executors) against replicated
  opposite factors; the per-half-step collective is the all-gather of
  solved factors back to replicated form, emitted by XLA on ICI.
  Sharded and single-device fits produce identical factors (same math,
  same shapes — only the row layout differs).

Factors stay device-resident across iterations; the index/rating
matrices are built once on host.  ``predict``/``recommend_for_all_users``
are one matmul (+ ``lax.top_k``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model


def _group_ratings(ids: np.ndarray, other: np.ndarray, ratings: np.ndarray, n: int):
    """Single padded (n, C) layout with C = the max count — the ORACLE
    layout (tests drive the half-step solvers with it directly); the
    production fit uses :func:`_group_ratings_bucketed`, of which this is
    the one-bucket-per-row scatter."""
    counts = np.bincount(ids, minlength=n) if len(ids) else np.zeros(n, np.int64)
    c = max(int(counts.max()), 1) if len(ids) else 1
    idx = np.zeros((n, c), np.int32)
    val = np.zeros((n, c), np.float32)
    msk = np.zeros((n, c), np.float32)
    for rows, bidx, bval, bmsk, _ in _group_ratings_bucketed(ids, other, ratings, n):
        w = bidx.shape[1]
        idx[rows, :w] = bidx
        val[rows, :w] = bval
        msk[rows, :w] = bmsk
    return idx, val, msk, counts.astype(np.float32)


#: smallest bucket cap and cap growth factor for the count-capped padding
#: (powers of _BUCKET_FACTOR from _BUCKET_BASE): every row's padded width
#: is < _BUCKET_FACTOR × its true count (or _BUCKET_BASE for tiny rows),
#: so total padded cells are bounded by max(_BUCKET_BASE, _BUCKET_FACTOR)
#: × nnz — one power-law user can no longer inflate every row to its C.
_BUCKET_BASE = 4
_BUCKET_FACTOR = 4


def _bucket_caps(max_count: int) -> list[int]:
    caps, c = [], _BUCKET_BASE
    while c < max_count:
        caps.append(c)
        c *= _BUCKET_FACTOR
    caps.append(max(max_count, _BUCKET_BASE))
    return caps


def _group_ratings_bucketed(
    ids: np.ndarray, other: np.ndarray, ratings: np.ndarray, n: int
):
    """Triplets grouped by ``ids`` → COUNT-CAPPED padded buckets.

    VERDICT r4 #3's scalability cliff: a single (n, C) layout takes C from
    the heaviest row, so one user with 10⁴ ratings inflates the whole
    (n, C, f) gather ~10³×.  Rows are instead binned by rating count into
    power-of-:data:`_BUCKET_FACTOR` caps; each bucket is its own dense
    (U_b, C_b) problem with the SAME batched-Cholesky half-step, and the
    per-bucket shapes are what jit specializes on (few buckets — cap
    growth is geometric).  → list of (row_ids, idx, val, msk, counts)."""
    counts = np.bincount(ids, minlength=n)
    order = np.argsort(ids, kind="stable")
    sid = ids[order]
    soth = other[order]
    sval = ratings[order]
    starts = np.r_[0, np.cumsum(counts)[:-1]]
    pos_all = np.arange(len(sid)) - starts[sid]

    out = []
    prev = 0
    for cap in _bucket_caps(int(counts.max()) if len(ids) else 1):
        rows = np.flatnonzero((counts > prev) & (counts <= cap))
        prev = cap
        if rows.size == 0:
            continue
        local = np.full(n, -1, np.int64)
        local[rows] = np.arange(rows.size)
        in_b = local[sid] >= 0
        lr = local[sid[in_b]]
        pos = pos_all[in_b]
        idx = np.zeros((rows.size, cap), np.int32)
        val = np.zeros((rows.size, cap), np.float32)
        msk = np.zeros((rows.size, cap), np.float32)
        idx[lr, pos] = soth[in_b]
        val[lr, pos] = sval[in_b]
        msk[lr, pos] = 1.0
        out.append((rows, idx, val, msk, counts[rows].astype(np.float32)))
    return out


def _nnls_cd(a, b, rank: int, sweeps: int = 60):
    """Batched non-negative least squares: minimize ½xᵀAx − bᵀx s.t.
    x ≥ 0 for every row's (A, b) at once, by projected cyclic coordinate
    descent — Spark's ``nonnegative=True`` runs a per-user NNLS in scala;
    here each sweep is ``rank`` vectorized (n,)-wide updates (rank is
    static and small, so the f-loop unrolls into pure VPU work inside one
    jitted fori_loop).  A is PD (λ·n_u·I ridge), so CD converges to the
    unique constrained optimum; the warm start is the clipped
    unconstrained solve."""
    diag = jnp.maximum(jnp.diagonal(a, axis1=1, axis2=2), 1e-12)  # (n, f)

    def sweep(_, x):
        for f in range(rank):  # static unroll — rank ~ 10
            resid = (
                b[:, f]
                - jnp.einsum("nr,nr->n", a[:, f, :], x)
                + diag[:, f] * x[:, f]
            )
            x = x.at[:, f].set(jnp.maximum(resid / diag[:, f], 0.0))
        return x

    x0 = jnp.maximum(jnp.linalg.solve(a, b[..., None])[..., 0], 0.0)
    return lax.fori_loop(0, sweeps, sweep, x0)


@partial(jax.jit, static_argnames=("rank", "nonnegative"), donate_argnums=())
def _solve_explicit(y, idx, val, msk, cnt, reg, rank: int, nonnegative: bool = False):
    """ALS-WR half-step: solve every row's (A, b) at once.

    y: (m, f) opposite factors; idx/val/msk: (n, C); cnt: (n,)
    A_u = Σ_c m·y yᵀ + λ·n_u·I  (λ·n_u — Spark's ALS-WR scaling)
    """
    g = y[idx]                                       # (n, C, f)
    gm = g * msk[..., None]
    a = jnp.einsum("ncf,ncg->nfg", gm, g)            # (n, f, f)
    b = jnp.einsum("ncf,nc->nf", gm, val)            # (n, f)
    lam = reg * jnp.maximum(cnt, 1.0)
    a = a + lam[:, None, None] * jnp.eye(rank, dtype=y.dtype)[None]
    if nonnegative:
        return _nnls_cd(a, b, rank)
    return jnp.linalg.solve(a, b[..., None])[..., 0]


@partial(jax.jit, static_argnames=("rank", "nonnegative"))
def _solve_implicit(
    y, yty, idx, val, msk, reg, alpha, rank: int, nonnegative: bool = False
):
    """Hu-Koren half-step: confidence c = 1 + α·r on observed pairs, all
    unobserved pairs carry preference 0 at confidence 1 — absorbed by the
    dense YᵀY term so only observed items enter the batched sums.
    ``yty`` is computed ONCE per half-step by the caller (shared across
    the count buckets).  Regularization scales by the per-row count of
    POSITIVE ratings (Spark's als.scala ``numExplicits · regParam``, the
    same ALS-WR weighting as the explicit path)."""
    g = y[idx]                                        # (n, C, f)
    conf_extra = alpha * val * msk                    # c − 1 on observed
    a = yty[None] + jnp.einsum(
        "ncf,nc,ncg->nfg", g, conf_extra, g
    )
    pref = (val > 0).astype(y.dtype) * msk
    n_pos = jnp.sum(pref, axis=1)
    lam = reg * jnp.maximum(n_pos, 1.0)
    a = a + lam[:, None, None] * jnp.eye(rank, dtype=y.dtype)[None]
    b = jnp.einsum("ncf,nc->nf", g, pref * (1.0 + alpha * val))
    if nonnegative:
        return _nnls_cd(a, b, rank)
    return jnp.linalg.solve(a, b[..., None])[..., 0]


@register_model("ALSModel")
@dataclass
class ALSModel(Model):
    user_factors: np.ndarray      # (num_users, rank)
    item_factors: np.ndarray      # (num_items, rank)
    # ids seen at fit time (Spark's coldStartStrategy decides the rest)
    cold_start_strategy: str = "nan"

    @property
    def rank(self) -> int:
        return self.user_factors.shape[1]

    def predict(self, user_ids, item_ids) -> np.ndarray:
        """Per-pair predicted ratings; unseen ids follow
        ``cold_start_strategy``: "nan" marks them NaN, "drop" removes the
        pairs (Spark's two strategies)."""
        u = np.asarray(user_ids, np.int64)
        i = np.asarray(item_ids, np.int64)
        if u.shape != i.shape:
            raise ValueError(f"user/item id shapes differ: {u.shape} vs {i.shape}")
        known = (
            (u >= 0) & (u < self.user_factors.shape[0])
            & (i >= 0) & (i < self.item_factors.shape[0])
        )
        uf = self.user_factors[np.clip(u, 0, self.user_factors.shape[0] - 1)]
        vf = self.item_factors[np.clip(i, 0, self.item_factors.shape[0] - 1)]
        pred = np.einsum("nf,nf->n", uf, vf)
        if self.cold_start_strategy == "drop":
            return pred[known]
        pred = pred.astype(np.float64)
        pred[~known] = np.nan
        return pred

    @staticmethod
    def _top_k_recs(query_factors, target_factors, k: int):
        """One copy of the recommend body — (query, f) @ (f, T) scores,
        top-k over targets — shared by the all-/subset- user/item calls
        so their rankings are identical by construction."""
        scores = jnp.asarray(query_factors) @ jnp.asarray(target_factors).T
        k = min(k, target_factors.shape[0])
        top, ids = lax.top_k(scores, k)
        return np.asarray(ids), np.asarray(top)

    def recommend_for_all_users(self, num_items: int):
        """→ (item ids (U, k), scores (U, k)) — one matmul + top_k."""
        return self._top_k_recs(self.user_factors, self.item_factors, num_items)

    def recommend_for_all_items(self, num_users: int):
        return self._top_k_recs(self.item_factors, self.user_factors, num_users)

    def recommend_for_user_subset(self, user_ids, num_items: int):
        """Spark's ``recommendForUserSubset``: top items for the GIVEN
        users only → (item ids (len(user_ids), k), scores).  Unknown ids
        raise (the Spark call joins on known ids; a silent clip would
        return another user's recommendations)."""
        u = self._check_subset_ids(user_ids, self.user_factors.shape[0], "user")
        return self._top_k_recs(self.user_factors[u], self.item_factors, num_items)

    def recommend_for_item_subset(self, item_ids, num_users: int):
        """Spark's ``recommendForItemSubset``: top users for the GIVEN
        items only."""
        i = self._check_subset_ids(item_ids, self.item_factors.shape[0], "item")
        return self._top_k_recs(self.item_factors[i], self.user_factors, num_users)

    @staticmethod
    def _check_subset_ids(ids, bound: int, kind: str) -> np.ndarray:
        out = np.asarray(ids, np.int64).reshape(-1)
        bad = (out < 0) | (out >= bound)
        if bad.any():
            raise ValueError(
                f"unknown {kind} id(s) {out[bad][:5].tolist()} — fit saw "
                f"{kind} ids 0..{bound - 1}"
            )
        return out

    def _artifacts(self):
        return (
            "ALSModel",
            {"cold_start_strategy": self.cold_start_strategy},
            {
                "user_factors": np.asarray(self.user_factors),
                "item_factors": np.asarray(self.item_factors),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            user_factors=arrays["user_factors"],
            item_factors=arrays["item_factors"],
            cold_start_strategy=params.get("cold_start_strategy", "nan"),
        )


@dataclass(frozen=True)
class ALS(Estimator):
    """Spark defaults: rank 10, maxIter 10, regParam 0.1, alpha 1.0,
    implicitPrefs False, nonnegative False, coldStartStrategy "nan".
    ``nonnegative=True`` solves each half-step's normal equations under
    x ≥ 0 (Spark's NNLS solver) via batched projected coordinate descent
    — see :func:`_nnls_cd`."""

    rank: int = 10
    max_iter: int = 10
    reg_param: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 1.0
    seed: int = 0
    cold_start_strategy: str = "nan"
    nonnegative: bool = False

    def fit(self, ratings, label_col: str | None = None, mesh=None) -> ALSModel:
        """``ratings``: (user, item, rating) as a 3-tuple of arrays, an
        (n, 3) array, or a Table with user/item/rating columns."""
        if self.cold_start_strategy not in ("nan", "drop"):
            raise ValueError(
                f"cold_start_strategy must be nan|drop, got "
                f"{self.cold_start_strategy!r}"
            )
        users, items, vals = self._coerce(ratings)
        if len(users) == 0:
            raise ValueError("ALS fit on an empty rating set")
        if self.implicit_prefs and (vals < 0).any():
            raise ValueError("implicit_prefs=True needs non-negative ratings")
        n_users = int(users.max()) + 1
        n_items = int(items.max()) + 1

        u_buckets = self._stage_buckets(
            _group_ratings_bucketed(users, items, vals, n_users), mesh
        )
        i_buckets = self._stage_buckets(
            _group_ratings_bucketed(items, users, vals, n_items), mesh
        )

        rng = np.random.default_rng(self.seed)
        # Spark seeds factors with scaled |N(0,1)|-ish draws; scale keeps
        # initial predictions O(mean rating)
        scale = 1.0 / np.sqrt(self.rank)
        uf = rng.normal(0, scale, size=(n_users, self.rank)).astype(np.float32)
        vf = rng.normal(0, scale, size=(n_items, self.rank)).astype(np.float32)
        if self.nonnegative:
            # Spark seeds |N| draws for NNLS — a first half-step against
            # mixed-sign factors would start CD from a meaningless corner
            uf, vf = np.abs(uf), np.abs(vf)
        # rows with no ratings are never solved; zero them like the solver
        # does (λI a, 0 b → 0), so id gaps keep the pre-bucketing behavior
        uf[np.bincount(users, minlength=n_users) == 0] = 0.0
        vf[np.bincount(items, minlength=n_items) == 0] = 0.0
        if mesh is not None:
            from ..parallel.sharding import replicate

            uf, vf = replicate(uf, mesh), replicate(vf, mesh)
        else:
            uf, vf = jnp.asarray(uf), jnp.asarray(vf)
        reg = jnp.float32(self.reg_param)
        alpha = jnp.float32(self.alpha)

        for _ in range(self.max_iter):
            uf = self._half_step(vf, u_buckets, uf, reg, alpha)
            vf = self._half_step(uf, i_buckets, vf, reg, alpha)
        return ALSModel(
            user_factors=np.asarray(jax.device_get(uf)),
            item_factors=np.asarray(jax.device_get(vf)),
            cold_start_strategy=self.cold_start_strategy,
        )

    def _stage_buckets(self, buckets, mesh):
        """Host buckets → device arrays, staged once before the loop.

        With a mesh, a bucket with ≥ one row per device is padded to the
        data axis and SHARDED across it: each device owns U_b/P rows'
        normal equations — the analogue of Spark distributing its in-link
        blocks across executors — while the opposite factor matrix stays
        replicated, so the only cross-device traffic per half-step is the
        all-gather of freshly solved (sharded) factors back to replicated
        form, which XLA emits on the ICI ring.  Row padding of a sharded
        bucket is < P rows ≤ the bucket's own row count, so it at most
        doubles that bucket — the documented ≤ 4×nnz cell bound survives
        sharding.  Buckets with FEWER rows than devices (the heavy tail:
        one power-law user in the top cap) are REPLICATED instead — row-
        padding those to P would re-inflate exactly the cells the
        bucketing removed (P − 1 copies of the widest row).  Padding rows
        (mask 0, count 0) solve the λI system to 0 and are sliced off."""
        if mesh is None:
            return [
                (jnp.asarray(rows), *map(jnp.asarray, rest), rows.size)
                for rows, *rest in buckets
            ]
        from ..parallel.mesh import DATA_AXIS
        from ..parallel.sharding import pad_rows, replicate, shard_rows

        p = mesh.shape[DATA_AXIS]
        staged = []
        for rows, idx, val, msk, cnt in buckets:
            if rows.size < p:
                staged.append(
                    (
                        jnp.asarray(rows),
                        *(replicate(a, mesh) for a in (idx, val, msk, cnt)),
                        rows.size,
                    )
                )
                continue
            pad = pad_rows(rows.size, p) - rows.size

            def padded(a):
                return np.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))

            staged.append(
                (
                    jnp.asarray(rows),
                    shard_rows(padded(idx), mesh),
                    shard_rows(padded(val), mesh),
                    shard_rows(padded(msk), mesh),
                    shard_rows(padded(cnt), mesh),
                    rows.size,
                )
            )
        return staged

    def _half_step(self, y, buckets, out, reg, alpha):
        """Solve every count bucket against ``y`` and scatter the results
        into ``out`` (replicated factors)."""
        yty = (y.T @ y) if self.implicit_prefs else None
        for rows, idx, val, msk, cnt, n_rows in buckets:
            if self.implicit_prefs:
                solved = _solve_implicit(
                    y, yty, idx, val, msk, reg, alpha, self.rank,
                    self.nonnegative,
                )
            else:
                solved = _solve_explicit(
                    y, idx, val, msk, cnt, reg, self.rank, self.nonnegative
                )
            out = out.at[rows].set(solved[:n_rows])
        return out

    @staticmethod
    def _coerce(ratings):
        from ..core.table import Table

        if isinstance(ratings, Table):
            cols = ratings.columns
            need = [c for c in ("user", "item", "rating") if c not in cols]
            if need:
                raise ValueError(
                    f"ALS table input needs user/item/rating columns; "
                    f"missing {need} (have {sorted(cols)})"
                )
            u = np.asarray(ratings.column("user"))
            i = np.asarray(ratings.column("item"))
            r = np.asarray(ratings.column("rating"), np.float32)
        elif isinstance(ratings, tuple) and len(ratings) == 3:
            u, i, r = (np.asarray(a) for a in ratings)
            r = r.astype(np.float32)
        else:
            arr = np.asarray(ratings)
            if arr.ndim != 2 or arr.shape[1] != 3:
                raise ValueError(
                    "ALS expects (user, item, rating) arrays, an (n, 3) "
                    f"matrix, or a Table; got shape {getattr(arr, 'shape', None)}"
                )
            u, i, r = arr[:, 0], arr[:, 1], arr[:, 2].astype(np.float32)
        ui = np.asarray(u)
        ii = np.asarray(i)
        if len(ui) and (np.min(ui) < 0 or np.min(ii) < 0):
            raise ValueError("ALS ids must be non-negative integers")
        return ui.astype(np.int64), ii.astype(np.int64), np.asarray(r, np.float32)
