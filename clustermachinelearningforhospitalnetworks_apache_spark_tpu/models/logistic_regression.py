"""LogisticRegression — sharded Newton/IRLS binary classifier.

The reference's dead incremental-training hook names LogisticRegression as
its intended per-batch model (``mllearnforhospitalnetwork.py:93`` comment;
SURVEY.md C6/D2) but only ever defines a LinearRegression — this module
supplies the intended capability, with ``pyspark.ml.classification
.LogisticRegression`` semantics (binary, L2 ``reg_param``, standardized
regularization, intercept unpenalized).

MLlib trains this with L-BFGS over ``treeAggregate``'d gradients.  At the
reference's feature width (d=4) the TPU-native shape is better served by
full Newton/IRLS: each iteration is one jit'd pass over the row-sharded
dataset building the (d+1) gradient and (d+1)² Hessian — two MXU matmuls
whose cross-shard reduction lowers to ``psum`` — followed by a tiny
on-device solve.  Convergence is quadratic, typically <10 iterations,
i.e. fewer passes over HBM than L-BFGS would take.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset
from .linear_regression import standardized_design


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter"))
def _irls_fit(x, y, w, reg_param, tol, fit_intercept: bool, standardize: bool, max_iter: int):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(x, w, reg_param, fit_intercept, standardize)
    d = xa.shape[1]

    def newton_step(theta):
        z = xa @ theta
        p = jax.nn.sigmoid(z)
        grad = xa.T @ (w * (p - y)) + ridge * theta
        # IRLS weights, floored so the Hessian stays meaningful when the
        # classes separate perfectly and p saturates to 0/1.
        r = jnp.maximum(w * p * (1.0 - p), 1e-10 * w)
        hess = (xa * r[:, None]).T @ xa + jnp.diag(ridge)
        # Trace-scaled jitter: keeps the f32 solve finite under exact
        # feature collinearity (relative bias ~1e-6, invisible otherwise).
        jitter = 1e-6 * jnp.trace(hess) / d + 1e-8
        delta = jnp.linalg.solve(hess + jitter * jnp.eye(d, dtype=x.dtype), grad)
        # Damped Newton: cap the step so separable data walks the margin
        # out gradually instead of overshooting into saturation.
        dmax = jnp.max(jnp.abs(delta))
        delta = delta * jnp.minimum(1.0, 20.0 / (dmax + 1e-30))
        return theta - delta, jnp.max(jnp.abs(delta))

    def cond(carry):
        _, it, dmax = carry
        return (it < max_iter) & (dmax > tol)

    def body(carry):
        theta, it, _ = carry
        theta, dmax = newton_step(theta)
        return theta, it + 1, dmax

    theta0 = jnp.zeros((d,), x.dtype)
    theta, n_iter, _ = lax.while_loop(cond, body, (theta0, 0, jnp.float32(jnp.inf)))
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)
    return coef, intercept, n_iter


@register_model("LogisticRegressionModel")
@dataclass
class LogisticRegressionModel(Model):
    coefficients: jax.Array
    intercept: jax.Array
    threshold: float = 0.5
    n_iter: int = 0

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """Log-odds (Spark's rawPrediction margin)."""
        return x.astype(jnp.float32) @ self.coefficients + self.intercept

    def predict_proba(self, x: jax.Array) -> jax.Array:
        """P(class = 1)."""
        return jax.nn.sigmoid(self.predict_raw(x))

    def predict(self, x: jax.Array) -> jax.Array:
        return (self.predict_proba(x) > self.threshold).astype(jnp.float32)

    def transform_proba(self, data, label_col: str | None = None, mesh=None):
        """Like ``transform`` but the prediction column holds P(class=1)
        instead of hard labels — the score input
        BinaryClassificationEvaluator (AUC) needs, mirroring Spark's
        ``probability``/``rawPrediction`` columns."""
        from .base import PredictionResult, as_device_dataset

        ds = as_device_dataset(data, label_col=label_col, mesh=mesh)
        return PredictionResult(
            prediction=self.predict_proba(ds.x), label=ds.y, weight=ds.w
        )

    def _artifacts(self):
        return (
            "LogisticRegressionModel",
            {"threshold": self.threshold, "n_iter": self.n_iter},
            {
                "coefficients": np.asarray(self.coefficients),
                "intercept": np.asarray(self.intercept),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=jnp.asarray(arrays["coefficients"]),
            intercept=jnp.asarray(arrays["intercept"]),
            threshold=float(params.get("threshold", 0.5)),
            n_iter=int(params.get("n_iter", 0)),
        )


@dataclass(frozen=True)
class LogisticRegression(Estimator):
    features_col: str = "features"
    label_col: str = "LOS_binary"
    reg_param: float = 0.0
    max_iter: int = 100        # Spark default
    tol: float = 1e-6          # Spark default
    threshold: float = 0.5     # Spark default
    fit_intercept: bool = True
    standardize: bool = True

    def fit(self, data, label_col: str | None = None, mesh=None) -> LogisticRegressionModel:
        ds: DeviceDataset = as_device_dataset(data, label_col or self.label_col, mesh=mesh)
        coef, intercept, n_iter = _irls_fit(
            ds.x, ds.y, ds.w, jnp.float32(self.reg_param), jnp.float32(self.tol),
            self.fit_intercept, self.standardize, self.max_iter,
        )
        return LogisticRegressionModel(
            coefficients=coef, intercept=intercept,
            threshold=self.threshold, n_iter=int(n_iter),
        )
