"""LogisticRegression — sharded Newton/IRLS binary classifier.

The reference's dead incremental-training hook names LogisticRegression as
its intended per-batch model (``mllearnforhospitalnetwork.py:93`` comment;
SURVEY.md C6/D2) but only ever defines a LinearRegression — this module
supplies the intended capability, with ``pyspark.ml.classification
.LogisticRegression`` semantics (binary, L2 ``reg_param``, standardized
regularization, intercept unpenalized).

MLlib trains this with L-BFGS over ``treeAggregate``'d gradients.  At the
reference's feature width (d=4) the TPU-native shape is better served by
full Newton/IRLS: each iteration is one jit'd pass over the row-sharded
dataset building the (d+1) gradient and (d+1)² Hessian — two MXU matmuls
whose cross-shard reduction lowers to ``psum`` — followed by a tiny
on-device solve.  Convergence is quadratic, typically <10 iterations,
i.e. fewer passes over HBM than L-BFGS would take.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset, check_features
from .linear_regression import standardized_design


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter"))
def _irls_fit(x, y, w, reg_param, tol, fit_intercept: bool, standardize: bool, max_iter: int):
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa, ridge, nfeat, _ = standardized_design(x, w, reg_param, fit_intercept, standardize)
    d = xa.shape[1]

    def newton_step(theta):
        z = xa @ theta
        p = jax.nn.sigmoid(z)
        grad = xa.T @ (w * (p - y)) + ridge * theta
        # IRLS weights, floored so the Hessian stays meaningful when the
        # classes separate perfectly and p saturates to 0/1.
        r = jnp.maximum(w * p * (1.0 - p), 1e-10 * w)
        hess = (xa * r[:, None]).T @ xa + jnp.diag(ridge)
        # Trace-scaled jitter: keeps the f32 solve finite under exact
        # feature collinearity (relative bias ~1e-6, invisible otherwise).
        jitter = 1e-6 * jnp.trace(hess) / d + 1e-8
        delta = jnp.linalg.solve(hess + jitter * jnp.eye(d, dtype=x.dtype), grad)
        # Damped Newton: cap the step so separable data walks the margin
        # out gradually instead of overshooting into saturation.
        dmax = jnp.max(jnp.abs(delta))
        delta = delta * jnp.minimum(1.0, 20.0 / (dmax + 1e-30))
        return theta - delta, jnp.max(jnp.abs(delta))

    def cond(carry):
        _, it, dmax = carry
        return (it < max_iter) & (dmax > tol)

    def body(carry):
        theta, it, _ = carry
        theta, dmax = newton_step(theta)
        return theta, it + 1, dmax

    theta0 = jnp.zeros((d,), x.dtype)
    theta, n_iter, _ = lax.while_loop(cond, body, (theta0, 0, jnp.float32(jnp.inf)))
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)
    return coef, intercept, n_iter


@partial(
    jax.jit,
    static_argnames=("num_classes", "fit_intercept", "standardize", "max_iter", "chunk"),
)
def _multinomial_fit(
    x, y, w, reg_param, tol,
    num_classes: int, fit_intercept: bool, standardize: bool, max_iter: int,
    chunk: int,
):
    """Softmax (multinomial) regression via damped Newton.

    Spark's ``family="multinomial"`` capability (the estimator named by the
    reference's dead incremental hook, ``mllearnforhospitalnetwork.py:93``)
    — full K coefficient vectors, standardized L2, intercepts unpenalized.

    The (K·D)² Hessian is accumulated on the MXU using the exact PSD
    factorization  diag(p) − ppᵀ = BBᵀ with  B = diag(√p) − p√pᵀ :
    per chunk, E[n, c, (a, i)] = √wₙ·B[a,c]·xa[n,i] and H += EᵀE — one
    matmul with an n·K-deep contraction instead of a scatter or a 4-way
    einsum.  Rows are processed in ``lax.scan`` chunks so the E transient
    stays bounded at BASELINE scale.
    """
    k = num_classes
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    yi = y.astype(jnp.int32)
    xa, ridge1, nfeat, _ = standardized_design(
        x, w, reg_param, fit_intercept, standardize
    )
    dd = xa.shape[1]
    kd = k * dd
    ridge = jnp.tile(ridge1, k)                       # (K·D,) per-class L2

    n_rows = xa.shape[0]
    c = min(chunk, max(n_rows, 1))
    pad = (-n_rows) % c
    if pad:
        xa = jnp.pad(xa, ((0, pad), (0, 0)))
        yi = jnp.pad(yi, (0, pad))
        w = jnp.pad(w, (0, pad))
    nchunks = (n_rows + pad) // c

    def stats(theta):
        """One data pass → (grad (K·D,), hess (K·D, K·D))."""
        th = theta.reshape(k, dd)

        def body(carry, i):
            g_acc, h_acc = carry
            sl = i * c
            xc = lax.dynamic_slice_in_dim(xa, sl, c, axis=0)      # (C, D)
            yc = lax.dynamic_slice_in_dim(yi, sl, c, axis=0)
            wc = lax.dynamic_slice_in_dim(w, sl, c, axis=0)
            z = xc @ th.T                                          # (C, K)
            p = jax.nn.softmax(z, axis=1)
            yoh = jax.nn.one_hot(yc, k, dtype=jnp.float32)
            g_acc = g_acc + ((p - yoh) * wc[:, None]).T @ xc       # (K, D)
            sqp = jnp.sqrt(p)
            b = (
                sqp[:, :, None] * jnp.eye(k, dtype=jnp.float32)[None]
                - p[:, :, None] * sqp[:, None, :]
            )                                                      # (C, K, K) b[n,a,c]
            e = (
                jnp.sqrt(wc)[:, None, None, None]
                * b[:, :, :, None]
                * xc[:, None, None, :]
            )                                                      # (C, a, c, i)
            e2 = jnp.transpose(e, (0, 2, 1, 3)).reshape(c * k, kd)
            h_acc = h_acc + e2.T @ e2
            return (g_acc, h_acc), None

        (g, h), _ = lax.scan(
            body,
            (jnp.zeros((k, dd), jnp.float32), jnp.zeros((kd, kd), jnp.float32)),
            jnp.arange(nchunks),
        )
        return g.reshape(kd) + ridge * theta, h + jnp.diag(ridge)

    def newton_step(theta):
        grad, hess = stats(theta)
        # jitter keeps the solve finite: the unregularized multinomial
        # parameterization has a null direction (adding a constant vector
        # to every class), which the tiny trace-scaled ridge pins down
        jitter = 1e-6 * jnp.trace(hess) / kd + 1e-8
        delta = jnp.linalg.solve(hess + jitter * jnp.eye(kd, dtype=jnp.float32), grad)
        dmax = jnp.max(jnp.abs(delta))
        delta = delta * jnp.minimum(1.0, 20.0 / (dmax + 1e-30))
        return theta - delta, jnp.max(jnp.abs(delta))

    def cond(carry):
        _, it, dmax = carry
        return (it < max_iter) & (dmax > tol)

    def body(carry):
        theta, it, _ = carry
        theta, dmax = newton_step(theta)
        return theta, it + 1, dmax

    theta0 = jnp.zeros((kd,), jnp.float32)
    theta, n_iter, _ = lax.while_loop(cond, body, (theta0, 0, jnp.float32(jnp.inf)))
    th = theta.reshape(k, dd)
    coef = th[:, :nfeat]
    intercept = th[:, nfeat] if fit_intercept else jnp.zeros((k,), jnp.float32)
    return coef, intercept, n_iter


@partial(jax.jit, static_argnames=("fit_intercept",))
def _logit_block_newton_stats(x, y, w, theta, fit_intercept: bool):
    """One block's (gradient, Hessian) contribution at ``theta`` — the
    EXACT per-row math of the resident ``_irls_fit`` Newton step, emitted
    as sufficient statistics so the out-of-core driver can sum them across
    blocks (two MXU matmuls per block, psum'd over the mesh by the
    sharded inputs)."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    w = w.astype(jnp.float32)
    xa = (
        jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        if fit_intercept
        else x
    )
    z = xa @ theta
    p = jax.nn.sigmoid(z)
    grad = xa.T @ (w * (p - y))
    r = jnp.maximum(w * p * (1.0 - p), 1e-10 * w)
    hess = (xa * r[:, None]).T @ xa
    return grad, hess


@jax.jit
def _newton_update_from_stats(theta, grad, hess, ridge):
    """Accumulated (grad, hess) → damped Newton step — identical update
    rule to the resident ``_irls_fit`` (ridge, trace-scaled jitter, step
    cap 20)."""
    d = theta.shape[0]
    grad = grad + ridge * theta
    hess = hess + jnp.diag(ridge)
    jitter = 1e-6 * jnp.trace(hess) / d + 1e-8
    delta = jnp.linalg.solve(hess + jitter * jnp.eye(d, dtype=theta.dtype), grad)
    dmax = jnp.max(jnp.abs(delta))
    delta = delta * jnp.minimum(1.0, 20.0 / (dmax + 1e-30))
    return theta - delta, jnp.max(jnp.abs(delta))


@partial(jax.jit, static_argnames=("num_classes", "fit_intercept", "chunk"))
def _multinomial_block_stats(x, y, w, theta, num_classes: int, fit_intercept: bool, chunk: int):
    """One block's (gradient, Hessian) for the softmax fit — the same
    PSD-factorized accumulation as the resident ``_multinomial_fit``
    (E = √w·B⊗x chunks contracted on the MXU), per streamed block."""
    k = num_classes
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    yi = y.astype(jnp.int32)
    xa = (
        jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        if fit_intercept
        else x
    )
    dd = xa.shape[1]
    kd = k * dd
    th = theta.reshape(k, dd)

    n_rows = xa.shape[0]
    c = min(chunk, max(n_rows, 1))
    pad = (-n_rows) % c
    if pad:
        xa = jnp.pad(xa, ((0, pad), (0, 0)))
        yi = jnp.pad(yi, (0, pad))
        w = jnp.pad(w, (0, pad))
    nchunks = (n_rows + pad) // c

    def body(carry, i):
        g_acc, h_acc = carry
        sl = i * c
        xc = lax.dynamic_slice_in_dim(xa, sl, c, axis=0)
        yc = lax.dynamic_slice_in_dim(yi, sl, c, axis=0)
        wc = lax.dynamic_slice_in_dim(w, sl, c, axis=0)
        z = xc @ th.T
        p = jax.nn.softmax(z, axis=1)
        yoh = jax.nn.one_hot(yc, k, dtype=jnp.float32)
        g_acc = g_acc + ((p - yoh) * wc[:, None]).T @ xc
        sqp = jnp.sqrt(p)
        b = (
            sqp[:, :, None] * jnp.eye(k, dtype=jnp.float32)[None]
            - p[:, :, None] * sqp[:, None, :]
        )
        e = (
            jnp.sqrt(wc)[:, None, None, None]
            * b[:, :, :, None]
            * xc[:, None, None, :]
        )
        e2 = jnp.transpose(e, (0, 2, 1, 3)).reshape(c * k, kd)
        h_acc = h_acc + e2.T @ e2
        return (g_acc, h_acc), None

    (g, h), _ = lax.scan(
        body,
        (jnp.zeros((k, dd), jnp.float32), jnp.zeros((kd, kd), jnp.float32)),
        jnp.arange(nchunks),
    )
    return g.reshape(kd), h


@register_model("MultinomialLogisticRegressionModel")
@dataclass
class MultinomialLogisticRegressionModel(Model):
    """K-class softmax model — Spark's ``coefficientMatrix`` /
    ``interceptVector`` surface."""

    coefficient_matrix: jax.Array      # (K, d)
    intercept_vector: jax.Array        # (K,)
    n_iter: int = 0
    _summary: object | None = field(default=None, repr=False, compare=False)

    @property
    def num_classes(self) -> int:
        return int(self.coefficient_matrix.shape[0])

    @property
    def has_summary(self) -> bool:
        return self._summary is not None

    def release_summary(self) -> None:
        """Unpin the training dataset (see models/summary.py)."""
        self._summary = None

    @property
    def summary(self):
        """Multiclass training summary (accuracy / per-label + weighted
        P/R/F/TPR/FPR) — fresh fits only, like Spark's ``hasSummary``."""
        if self._summary is None:
            from .summary import summary_unavailable

            raise summary_unavailable("MultinomialLogisticRegressionModel")
        return self._summary

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """(n, K) class margins."""
        check_features(
            x, self.coefficient_matrix.shape[1], "MultinomialLogisticRegressionModel"
        )
        return (
            x.astype(jnp.float32) @ self.coefficient_matrix.T
            + self.intercept_vector[None, :]
        )

    def predict_proba(self, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(self.predict_raw(x), axis=1)

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_raw(x), axis=1).astype(jnp.float32)

    def _artifacts(self):
        return (
            "MultinomialLogisticRegressionModel",
            {"n_iter": self.n_iter},
            {
                "coefficient_matrix": np.asarray(self.coefficient_matrix),
                "intercept_vector": np.asarray(self.intercept_vector),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficient_matrix=jnp.asarray(arrays["coefficient_matrix"]),
            intercept_vector=jnp.asarray(arrays["intercept_vector"]),
            n_iter=int(params.get("n_iter", 0)),
        )


@register_model("LogisticRegressionModel")
@dataclass
class LogisticRegressionModel(Model):
    coefficients: jax.Array
    intercept: jax.Array
    threshold: float = 0.5
    n_iter: int = 0
    _summary: object | None = field(default=None, repr=False, compare=False)

    @property
    def has_summary(self) -> bool:
        return self._summary is not None

    def release_summary(self) -> None:
        """Drop the summary's reference to the training dataset, unpinning
        it from device memory (see models/summary.py memory note)."""
        self._summary = None

    @property
    def summary(self):
        """Binary training summary (accuracy/AUC/per-label PRF) — fresh
        fits only, like Spark's ``hasSummary``."""
        if self._summary is None:
            from .summary import summary_unavailable

            raise summary_unavailable("LogisticRegressionModel")
        return self._summary

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """Log-odds (Spark's rawPrediction margin)."""
        check_features(x, self.coefficients.shape[0], "LogisticRegressionModel")
        return x.astype(jnp.float32) @ self.coefficients + self.intercept

    def predict_proba(self, x: jax.Array) -> jax.Array:
        """P(class = 1)."""
        return jax.nn.sigmoid(self.predict_raw(x))

    def predict(self, x: jax.Array) -> jax.Array:
        return (self.predict_proba(x) > self.threshold).astype(jnp.float32)

    def transform_proba(self, data, label_col: str | None = None, mesh=None):
        """Like ``transform`` but the prediction column holds P(class=1)
        instead of hard labels — the score input
        BinaryClassificationEvaluator (AUC) needs, mirroring Spark's
        ``probability``/``rawPrediction`` columns."""
        from .base import PredictionResult, as_device_dataset

        ds = as_device_dataset(data, label_col=label_col, mesh=mesh)
        return PredictionResult(
            prediction=self.predict_proba(ds.x), label=ds.y, weight=ds.w
        )

    def _artifacts(self):
        return (
            "LogisticRegressionModel",
            {"threshold": self.threshold, "n_iter": self.n_iter},
            {
                "coefficients": np.asarray(self.coefficients),
                "intercept": np.asarray(self.intercept),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=jnp.asarray(arrays["coefficients"]),
            intercept=jnp.asarray(arrays["intercept"]),
            threshold=float(params.get("threshold", 0.5)),
            n_iter=int(params.get("n_iter", 0)),
        )


@dataclass(frozen=True)
class LogisticRegression(Estimator):
    """``family`` mirrors Spark: "auto" picks binomial for ≤2 label values
    and multinomial otherwise; "binomial"/"multinomial" force the path.
    The multinomial fit returns a
    :class:`MultinomialLogisticRegressionModel` (coefficientMatrix /
    interceptVector surface)."""

    features_col: str = "features"
    label_col: str = "LOS_binary"
    reg_param: float = 0.0
    max_iter: int = 100        # Spark default
    tol: float = 1e-6          # Spark default
    threshold: float = 0.5     # Spark default
    fit_intercept: bool = True
    standardize: bool = True
    family: str = "auto"       # Spark default
    weight_col: str | None = None  # Spark's weightCol

    def fit(self, data, label_col: str | None = None, mesh=None):
        if self.family not in ("auto", "binomial", "multinomial"):
            raise ValueError(
                f"family must be auto|binomial|multinomial, got {self.family!r}"
            )
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds: DeviceDataset = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        family = self.family
        # one tiny sync: the class count is a static shape parameter (and
        # the binomial-on-multiclass guard Spark also enforces)
        num_classes = int(
            jax.device_get(jnp.max(jnp.where(ds.w > 0, ds.y, 0.0)))
        ) + 1
        if family == "auto":
            family = "binomial" if num_classes <= 2 else "multinomial"
        elif family == "binomial" and num_classes > 2:
            raise ValueError(
                f"binomial family supports 1 or 2 outcome classes, found "
                f"{num_classes}; use family='multinomial'"
            )
        if family == "multinomial":
            # bound the Hessian-factor transient: the per-chunk e tensor is
            # chunk·K²·D floats, so the chunk shrinks as K²·D grows (same
            # rule as every other chunked path's tile budget)
            k = max(num_classes, 2)
            dd = ds.n_features + (1 if self.fit_intercept else 0)
            chunk = int(min(65536, max(256, (1 << 25) // max(1, k * k * dd))))
            coef, intercept, n_iter = _multinomial_fit(
                ds.x, ds.y, ds.w, jnp.float32(self.reg_param),
                jnp.float32(self.tol), k,
                self.fit_intercept, self.standardize, self.max_iter,
                chunk,
            )
            model = MultinomialLogisticRegressionModel(
                coefficient_matrix=coef, intercept_vector=intercept,
                n_iter=int(n_iter),
            )
            from .summary import MulticlassLogisticRegressionTrainingSummary

            model._summary = MulticlassLogisticRegressionTrainingSummary(
                model, ds
            )
            return model
        coef, intercept, n_iter = _irls_fit(
            ds.x, ds.y, ds.w, jnp.float32(self.reg_param), jnp.float32(self.tol),
            self.fit_intercept, self.standardize, self.max_iter,
        )
        model = LogisticRegressionModel(
            coefficients=coef, intercept=intercept,
            threshold=self.threshold, n_iter=int(n_iter),
        )
        from .summary import BinaryLogisticRegressionTrainingSummary

        model._summary = BinaryLogisticRegressionTrainingSummary(model, ds)
        return model

    def _fit_outofcore(self, hd, mesh=None):
        """Rows ≫ HBM Newton/IRLS (VERDICT r3 next #4): every Newton
        iteration is one streaming pass over ``max_device_rows`` host
        blocks accumulating the SAME (gradient, Hessian) statistics the
        resident fit computes in one shot, followed by the identical
        damped solve — Spark's disk-backed partition streaming at
        reference ``mllearnforhospitalnetwork.py:150-158``, one block at a
        time through the mesh.  The training ``summary`` is unavailable on
        this path (it would pin the full dataset on device)."""
        from ..parallel.mesh import default_mesh
        from ..parallel.outofcore import add_stats

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError("LogisticRegression needs labels: HostDataset(y=...)")
        if hd.n == 0:
            raise ValueError("LogisticRegression fit on an empty dataset")

        # pass 0: standardization moments (→ Spark's standardized-L2
        # ridge) + class count, via the shared out-of-core pre-pass
        # (parallel/outofcore.py; "ymax" accumulates by max, not add)
        from ..parallel.outofcore import streamed_standardization

        n, _, std, ymax = streamed_standardization(hd, mesh, extra="ymax")
        scale = std if self.standardize else np.ones_like(std)
        num_classes = int(ymax) + 1

        family = self.family
        if family == "auto":
            family = "binomial" if num_classes <= 2 else "multinomial"
        elif family == "binomial" and num_classes > 2:
            raise ValueError(
                f"binomial family supports 1 or 2 outcome classes, found "
                f"{num_classes}; use family='multinomial'"
            )
        nfeat = hd.n_features
        dd = nfeat + (1 if self.fit_intercept else 0)
        ridge1 = np.zeros((dd,), np.float32)
        ridge1[:nfeat] = self.reg_param * n * scale * scale

        if family == "multinomial":
            k = max(num_classes, 2)
            kd = k * dd
            chunk = int(min(65536, max(256, (1 << 25) // max(1, k * k * dd))))
            ridge = jnp.asarray(np.tile(ridge1, k))
            theta = jnp.zeros((kd,), jnp.float32)
            it = 0
            for it in range(1, self.max_iter + 1):
                tot = None
                for blk in hd.blocks(mesh):
                    s = _multinomial_block_stats(
                        blk.x, blk.y, blk.w, theta, k, self.fit_intercept, chunk
                    )
                    tot = s if tot is None else add_stats(tot, s)
                theta, dmax = _newton_update_from_stats(theta, *tot, ridge)
                if float(dmax) <= self.tol:
                    break
            th = np.asarray(jax.device_get(theta)).reshape(k, dd)
            return MultinomialLogisticRegressionModel(
                coefficient_matrix=jnp.asarray(th[:, :nfeat]),
                intercept_vector=(
                    jnp.asarray(th[:, nfeat])
                    if self.fit_intercept
                    else jnp.zeros((k,), jnp.float32)
                ),
                n_iter=it,
            )

        ridge = jnp.asarray(ridge1)
        theta = jnp.zeros((dd,), jnp.float32)
        it = 0
        for it in range(1, self.max_iter + 1):
            tot = None
            for blk in hd.blocks(mesh):
                s = _logit_block_newton_stats(
                    blk.x, blk.y, blk.w, theta, self.fit_intercept
                )
                tot = s if tot is None else add_stats(tot, s)
            theta, dmax = _newton_update_from_stats(theta, *tot, ridge)
            if float(dmax) <= self.tol:
                break
        theta_h = np.asarray(jax.device_get(theta))
        return LogisticRegressionModel(
            coefficients=jnp.asarray(theta_h[:nfeat]),
            intercept=(
                jnp.asarray(theta_h[nfeat])
                if self.fit_intercept
                else jnp.zeros((), jnp.float32)
            ),
            threshold=self.threshold,
            n_iter=it,
        )
