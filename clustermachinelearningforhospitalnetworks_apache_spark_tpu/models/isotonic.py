"""IsotonicRegression — pool-adjacent-violators with device interpolation.

Parity with ``pyspark.ml.regression.IsotonicRegression``: single active
feature (``feature_index`` into the assembled vector), ``isotonic=True``
for increasing / False for decreasing, weighted, prediction by linear
interpolation between fitted boundaries (Spark's rule, which is also
``jnp.interp``'s: clamp outside the boundary range).

Shape notes: PAVA is inherently sequential, but its input is the
sorted-by-x sequence of (Σwy/Σw) groups — tiny compared to the row count
after duplicate-x pooling.  So the fit is: device → host fetch of the
(x, y, w) triples (one transfer), host sort + duplicate pooling
(vectorized numpy), then linear-time PAVA over the pooled blocks (the
same split Spark makes: per-partition PAVA, then a final driver-side
pass).  Prediction stays on device: one ``jnp.interp`` over the (b,)
boundary tables, sharded rows in, sharded predictions out.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset


def _pava(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Pool-adjacent-violators (increasing), linear time amortized: keep a
    stack of monotone blocks; a new point merges backwards while it
    violates the previous block's mean — each merge permanently removes a
    block, so total merges ≤ n."""
    starts: list[int] = []     # block start index
    means: list[float] = []    # block weighted mean
    weights: list[float] = []  # block weight
    for i in range(y.size):
        cs, cm, cw = i, float(y[i]), float(w[i])
        while means and means[-1] > cm:
            cm = (means[-1] * weights[-1] + cm * cw) / (weights[-1] + cw)
            cw += weights[-1]
            cs = starts[-1]
            starts.pop(); means.pop(); weights.pop()
        starts.append(cs)
        means.append(cm)
        weights.append(cw)
    fitted = np.empty(y.size, dtype=np.float64)
    bounds = starts + [y.size]
    for j, mval in enumerate(means):
        fitted[bounds[j] : bounds[j + 1]] = mval
    return fitted


@register_model("IsotonicRegressionModel")
@dataclass
class IsotonicRegressionModel(Model):
    boundaries: np.ndarray    # (b,) ascending x values
    predictions: np.ndarray   # (b,) fitted values at the boundaries
    isotonic: bool = True
    feature_index: int = 0

    def predict(self, x: jax.Array) -> jax.Array:
        xv = x[:, self.feature_index] if x.ndim == 2 else x
        xb = jnp.asarray(self.boundaries, jnp.float32)
        yb = jnp.asarray(self.predictions, jnp.float32)
        # jnp.interp clamps outside the range — Spark's boundary rule
        return jnp.interp(xv.astype(jnp.float32), xb, yb)

    def _artifacts(self):
        return (
            "IsotonicRegressionModel",
            {"isotonic": bool(self.isotonic), "feature_index": int(self.feature_index)},
            {
                "boundaries": np.asarray(self.boundaries),
                "predictions": np.asarray(self.predictions),
            },
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            boundaries=arrays["boundaries"],
            predictions=arrays["predictions"],
            isotonic=bool(params.get("isotonic", True)),
            feature_index=int(params.get("feature_index", 0)),
        )


@dataclass(frozen=True)
class IsotonicRegression(Estimator):
    isotonic: bool = True          # Spark default: increasing
    feature_index: int = 0         # Spark's featureIndex
    label_col: str = "length_of_stay"
    features_col: str = "features"
    weight_col: str | None = None

    def _check_feature_index(self, n_features: int) -> None:
        if not 0 <= self.feature_index < n_features:
            raise ValueError(
                f"feature_index {self.feature_index} out of range "
                f"[0, {n_features})"
            )

    def fit(self, data, label_col: str | None = None, mesh=None) -> IsotonicRegressionModel:
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            # Isotonic consumes ONE feature column + labels + weights —
            # 1-D host vectors regardless of how wide or HBM-oversized
            # the matrix is, and PAVA is host work anyway (a sort +
            # reduceat).  So the out-of-core path never stages anything:
            # it slices the column straight out of the host (possibly
            # memmap) matrix.  The f32 round-trip mirrors the device
            # path's staging cast, so both paths pool the SAME distinct
            # x values on float64 input.
            if data.y is None:
                raise ValueError(
                    "IsotonicRegression needs labels: HostDataset(y=...)"
                )
            self._check_feature_index(data.n_features)
            x = (
                np.asarray(data.x[:, self.feature_index], np.float32)
                .astype(np.float64)
            )
            y = np.asarray(data.y, np.float32).astype(np.float64)
            w = (
                np.asarray(data.w, np.float32).astype(np.float64)
                if data.w is not None
                else np.ones(data.n, np.float64)
            )
        else:
            ds = as_device_dataset(
                data, label_col or self.label_col, mesh=mesh,
                weight_col=self.weight_col,
            )
            self._check_feature_index(ds.n_features)
            x = np.asarray(jax.device_get(ds.x))[:, self.feature_index].astype(
                np.float64
            )
            y = np.asarray(jax.device_get(ds.y), dtype=np.float64)
            w = np.asarray(jax.device_get(ds.w), dtype=np.float64)
        valid = w > 0
        x, y, w = x[valid], y[valid], w[valid]
        if x.size == 0:
            raise ValueError("isotonic fit on an empty dataset")

        order = np.argsort(x, kind="stable")
        xs, ys, ws = x[order], y[order], w[order]
        # pool duplicate x values (weighted means) — PAVA block count then
        # equals the number of DISTINCT x values
        ux, first = np.unique(xs, return_index=True)
        sums = np.add.reduceat(ys * ws, first)
        wsum = np.add.reduceat(ws, first)
        gy = sums / wsum
        if not self.isotonic:
            gy = -gy
        fitted = _pava(gy, wsum)
        if not self.isotonic:
            fitted = -fitted
        # compress runs of equal fitted values to their end-points — the
        # (boundary, prediction) table Spark stores
        keep = np.ones(ux.size, dtype=bool)
        if ux.size > 2:
            interior_same = (fitted[1:-1] == fitted[:-2]) & (
                fitted[1:-1] == fitted[2:]
            )
            keep[1:-1] = ~interior_same
        return IsotonicRegressionModel(
            boundaries=ux[keep],
            predictions=fitted[keep],
            isotonic=self.isotonic,
            feature_index=self.feature_index,
        )
