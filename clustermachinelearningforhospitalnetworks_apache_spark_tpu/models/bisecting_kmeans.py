"""BisectingKMeans — hierarchical divisive clustering (BASELINE config 4).

Capability parity: ``pyspark.ml.clustering.BisectingKMeans`` (k, maxIter,
seed, minDivisibleClusterSize; model exposes centers and ``computeCost``).
Spark grows the tree **level by level** — "the bisecting steps of clusters
on the same level are grouped together to increase parallelism", with
larger clusters given priority when splitting everything would overshoot k.

The TPU-native form goes one step further: the ENTIRE tree growth is one
jitted device computation — level scheduling (divisibility, the k budget,
Spark's larger-cluster priority), child seeding (``jax.random`` folded per
level), the constrained 2-means Lloyd loop, and the leaf bookkeeping all
run inside a single ``lax.while_loop`` under ``shard_map``, with exactly
ONE host sync per tree (``n_restarts`` whole-tree candidates per fit; the
lowest-cost tree wins — see the ``n_restarts`` field note).  That matters
doubly on remote-attached chips where every host↔device round trip costs
tens of milliseconds.

Within a level, the L splitting leaves contribute a flattened (2L, d)
children tensor; each row's distance row (chunk, 2L) — one MXU matmul, the
same shape as the KMeans step — is masked so the row competes only between
its own leaf's two children, and child sums/counts are ``psum``'d over the
mesh's data axis.  Lloyd iterations rank children by ``|c|² − 2x·c`` (the
``|x|²`` term cancels inside a row), so the convergence loop reads strictly
less HBM than a full distance pass; the true SSE is computed once on the
converged centers.

Two split schedules share the one executable: ``strategy="level"`` (Spark
parity, above) and ``strategy="sequential"`` (one largest-SSE split per
level — sklearn's ``bisecting_strategy="biggest_inertia"`` — better local
optima when k is small relative to the true cluster count, still a single
host sync per fit).

Per-hospital federation (BASELINE config 4 "one partition per TPU chip"):
the level step's math is placement-invariant (weighted psum sums), so a
dataset laid out with each hospital's rows on one data shard converges
identically to a shuffled layout.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..io.model_io import register_model
from ..ops.distance import normalize_rows, pairwise_sqdist, sq_norms
from ..parallel.mesh import DATA_AXIS, default_mesh
from ..parallel.partitioner import family as _partitioner_family

#: row-parallel bisecting layouts — rules in parallel/partitioner.py
_PT = _partitioner_family("bisecting")
from ..parallel.sharding import DeviceDataset
from .base import Estimator, as_device_dataset
from ..parallel.sharding import chunk_layout, chunked_pad
from .kmeans import KMeansModel

# np scalar, not jnp: a module-level jnp constant would initialize
# the backend at import time (hangs when the TPU tunnel is down)
_BIG = np.float32(1e30)


@lru_cache(maxsize=32)
def _make_fit_loop(
    mesh: Mesh,
    n_loc: int,
    k: int,
    L: int,
    d: int,
    chunk_rows: int,
    cosine: bool,
    max_iter: int,
    tol_sq: float,
    by_sse: bool,
):
    """The whole BisectingKMeans fit as one jitted shard_map computation.

    State arrays carry k+1 rows: row k is a write-only dummy slot so masked
    scatters (failed splits) need no dynamic shapes.  Returns (centers,
    sizes, sse, n_splits) — one host transfer per fit.
    """
    n_chunks, chunk = chunk_layout(n_loc, chunk_rows)
    pad_to = n_chunks * chunk
    K2 = 2 * L
    child_iota = jnp.arange(K2, dtype=jnp.int32)

    def _vary(z):
        return jax.tree.map(lambda a: lax.pcast(a, DATA_AXIS, to="varying"), z)

    def _lloyd_scan(x_c, w_c, pos_c, cen, shift):
        """Per-shard (sums, counts) for one Lloyd iteration.  Children are
        ranked by |c|²−2x·c — the |x|² term cancels within a row.  ``shift``
        recenters rows chunk-by-chunk (fused into the read; see shard_fn)."""
        c_sq = sq_norms(cen)

        def body(carry, inputs):
            sums, counts = carry
            xb, wb, pb = inputs
            xb = xb - shift[None, :]
            # HIGHEST precision, matching pairwise_sqdist: the two children
            # are seeded deliberately close, and a bf16 dot can tie them.
            cross = jnp.dot(xb, cen.T, precision=lax.Precision.HIGHEST)
            d2 = c_sq[None, :] - 2.0 * cross                  # (chunk, K2)
            d2 = jnp.where((child_iota[None, :] // 2) == pb[:, None], d2, _BIG)
            arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
            wv = jnp.where((pb >= 0) & (wb > 0), wb, 0.0)
            onehot = jax.nn.one_hot(arg, K2, dtype=xb.dtype) * wv[:, None]
            return (sums + onehot.T @ xb, counts + jnp.sum(onehot, axis=0)), None

        init = _vary((jnp.zeros((K2, d), x_c.dtype), jnp.zeros((K2,), x_c.dtype)))
        (sums, counts), _ = lax.scan(body, init, (x_c, w_c, pos_c))
        return lax.psum(sums, DATA_AXIS), lax.psum(counts, DATA_AXIS)

    def _stats_scan(x_c, w_c, pos_c, cen, shift):
        """Final pass on converged centers: true per-child counts/SSE plus
        each row's child bit."""
        c_sq = sq_norms(cen)

        def body(carry, inputs):
            counts, sse = carry
            xb, wb, pb = inputs
            xb = xb - shift[None, :]
            d2 = pairwise_sqdist(xb, cen, c_sq=c_sq)
            d2 = jnp.where((child_iota[None, :] // 2) == pb[:, None], d2, _BIG)
            arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
            mind = jnp.maximum(jnp.min(d2, axis=1), 0.0)
            live = (pb >= 0) & (wb > 0)
            wv = jnp.where(live, wb, 0.0)
            onehot = jax.nn.one_hot(arg, K2, dtype=xb.dtype) * wv[:, None]
            counts = counts + jnp.sum(onehot, axis=0)
            sse = sse + onehot.T @ jnp.where(live, mind, 0.0)
            return (counts, sse), arg % 2

        init = _vary((jnp.zeros((K2,), x_c.dtype), jnp.zeros((K2,), x_c.dtype)))
        (counts, sse), bits = lax.scan(body, init, (x_c, w_c, pos_c))
        return lax.psum(counts, DATA_AXIS), lax.psum(sse, DATA_AXIS), bits

    def shard_fn(x, w, key, min_div, is_frac):
        x_c, w_c = chunked_pad(x, w, n_chunks, chunk)

        # ---- root leaf: weighted mean, then a per-row SSE pass ----------
        def mean_body(carry, inputs):
            s0, s1 = carry
            xb, wb = inputs
            return (s0 + jnp.sum(wb), s1 + wb @ xb), None

        init = _vary((jnp.zeros((), x.dtype), jnp.zeros((d,), x.dtype)))
        (s0, s1), _ = lax.scan(mean_body, init, (x_c, w_c))
        s0 = lax.psum(s0, DATA_AXIS)
        s1 = lax.psum(s1, DATA_AXIS)
        mean = s1 / jnp.maximum(s0, 1.0)
        # All cluster math runs in data RECENTERED around the global mean
        # (Euclidean SSE/assignments are translation-invariant): with the
        # raw values, an unstandardized table whose mean dwarfs its spread
        # (hospital counts, timestamps) loses the entire split signal to
        # f32 cancellation in |c|²−2x·c and in the center sums.  The shift
        # is fused into each chunk read — no second copy of x in HBM.  The
        # cosine path is already on the unit sphere (bounded magnitudes)
        # and must not be translated.
        shift = jnp.zeros((d,), x.dtype) if cosine else mean
        root = mean - shift
        if cosine:
            root = root / jnp.maximum(jnp.linalg.norm(root), 1e-12)

        # Per-row (x−c)² accumulation — the moment formula Σw|x|²−n|c|²
        # cancels catastrophically for the same reason as above.
        def sse_body(acc, inputs):
            xb, wb = inputs
            diff = (xb - shift[None, :]) - root[None, :]
            return acc + jnp.sum(jnp.sum(diff * diff, axis=1) * wb), None

        (root_sse), _ = lax.scan(sse_body, _vary(jnp.zeros((), x.dtype)), (x_c, w_c))
        root_sse = lax.psum(root_sse, DATA_AXIS)
        min_size = jnp.maximum(jnp.where(is_frac > 0, min_div * s0, min_div), 2.0)

        centers = jnp.zeros((k + 1, d), x.dtype).at[0].set(root)
        sizes = jnp.zeros((k + 1,), x.dtype).at[0].set(s0)
        sse = jnp.zeros((k + 1,), x.dtype).at[0].set(root_sse)
        divisible = jnp.zeros((k + 1,), bool).at[0].set(True)
        assign = _vary(jnp.zeros((n_loc,), jnp.int32))

        def outer_cond(carry):
            level, _, _, sizes, _, divisible, n_leaves, _ = carry
            cand = divisible[:k] & (sizes[:k] >= min_size)
            return (n_leaves < k) & jnp.any(cand)

        def outer_body(carry):
            level, assign, centers, sizes, sse, divisible, n_leaves, n_splits = carry
            # -- schedule: level strategy ranks by size (Spark's
            # larger-cluster priority); sequential ranks by SSE and splits
            # one leaf per level (sklearn biggest_inertia)
            cand = divisible[:k] & (sizes[:k] >= min_size)
            priority = sse[:k] if by_sse else sizes[:k]
            order = jnp.argsort(-jnp.where(cand, priority, -1.0))
            sel = order[:L]                                   # (L,) leaf ids
            slot_valid = (jnp.arange(L) < (k - n_leaves)) & cand[sel]
            slot_of = (
                jnp.full((k + 1,), -1, jnp.int32)
                .at[sel]
                .set(jnp.where(slot_valid, jnp.arange(L, dtype=jnp.int32), -1))
            )
            # -- seed children: parent ± RMS-radius perturbation
            radius = jnp.sqrt(
                jnp.maximum(sse[sel], 1e-12) / jnp.maximum(sizes[sel], 1.0)
            )
            dirs = jax.random.normal(jax.random.fold_in(key, level), (L, d), x.dtype)
            dirs = dirs / jnp.maximum(
                jnp.linalg.norm(dirs, axis=1, keepdims=True), 1e-12
            ) * radius[:, None]
            parents = centers[sel]
            c01 = jnp.stack([parents + 0.5 * dirs, parents - 0.5 * dirs], axis=1)
            if cosine:
                c01 = normalize_rows(c01.reshape(K2, d)).reshape(L, 2, d)
            cen0 = c01.reshape(K2, d)

            pos = slot_of[jnp.clip(jnp.pad(assign, (0, pad_to - n_loc)), 0, k)]
            pos = jnp.where(w_c.reshape(pad_to) > 0, pos, -1)
            pos_c = pos.reshape(n_chunks, chunk)

            # -- constrained 2-means Lloyd loop over ALL splitting leaves
            def cond(c):
                it, _, move = c
                return (it < max_iter) & (move > tol_sq)

            def body(c):
                it, cen, _ = c
                sums, counts = _lloyd_scan(x_c, w_c, pos_c, cen, shift)
                new_cen = jnp.where(
                    (counts > 0)[:, None], sums / jnp.maximum(counts, 1.0)[:, None], cen
                )
                if cosine:
                    new_cen = normalize_rows(new_cen)
                valid2 = jnp.repeat(slot_valid, 2)
                move = jnp.max(jnp.sum((new_cen - cen) ** 2, axis=1) * valid2)
                return it + 1, new_cen, move

            _, cen, _ = lax.while_loop(cond, body, (jnp.int32(0), cen0, jnp.float32(jnp.inf)))

            counts, csse, bits = _stats_scan(x_c, w_c, pos_c, cen, shift)
            counts2 = counts.reshape(L, 2)
            csse2 = csse.reshape(L, 2)
            cen2 = cen.reshape(L, 2, d)

            # -- bookkeeping: a split succeeds iff the new child got rows
            succ = slot_valid & (counts2[:, 1] > 0)
            new_id = jnp.where(
                succ, n_leaves + jnp.cumsum(succ.astype(jnp.int32)) - 1, k
            )
            bit = bits.reshape(pad_to)[:n_loc]
            pos_n = pos[:n_loc]
            safe_p = jnp.clip(pos_n, 0, L - 1)
            relabel = (pos_n >= 0) & (bit == 1) & succ[safe_p]
            assign = jnp.where(relabel, new_id[safe_p], assign)

            centers = centers.at[sel].set(
                jnp.where(succ[:, None], cen2[:, 0], centers[sel])
            )
            sizes = sizes.at[sel].set(jnp.where(succ, counts2[:, 0], sizes[sel]))
            sse = sse.at[sel].set(jnp.where(succ, csse2[:, 0], sse[sel]))
            # parent stays divisible iff it kept rows; a failed split (new
            # child empty — duplicate-point cluster) pins the leaf closed.
            divisible = divisible.at[sel].set(
                jnp.where(slot_valid, succ & (counts2[:, 0] > 0), divisible[sel])
            )
            centers = centers.at[new_id].set(
                jnp.where(succ[:, None], cen2[:, 1], centers[new_id])
            )
            sizes = sizes.at[new_id].set(jnp.where(succ, counts2[:, 1], sizes[new_id]))
            sse = sse.at[new_id].set(jnp.where(succ, csse2[:, 1], sse[new_id]))
            divisible = divisible.at[new_id].set(
                jnp.where(succ, True, divisible[new_id])
            )
            grown = jnp.sum(succ.astype(jnp.int32))
            return (
                level + 1,
                assign,
                centers,
                sizes,
                sse,
                divisible,
                n_leaves + grown,
                n_splits + grown,
            )

        carry = (jnp.int32(0), assign, centers, sizes, sse, divisible, jnp.int32(1), jnp.int32(0))
        _, _, centers, sizes, sse, _, _, n_splits = lax.while_loop(
            outer_cond, outer_body, carry
        )
        # undo the recentering on the way out
        return centers[:k] + shift[None, :], sizes[:k], sse[:k], n_splits

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(_PT.spec("batch/x", 2), _PT.spec("batch/w", 1))
            + (_PT.spec("const/state"),) * 3,
            out_specs=(_PT.spec("const/state"),) * 4,
        )
    )


@jax.jit
def _bkm_lloyd_block(x, w, pos, cen, shift):
    """One streamed block's 2-means sufficient stats for ALL splitting
    leaves at once: each row belongs to leaf slot ``pos`` (−1 = not
    splitting) and chooses the nearer of that leaf's two children in
    ``cen`` (2L, d).  Euclidean argmin on (optionally unit-sphere) data
    serves both distance measures — on the sphere it is monotone with
    cosine distance, the same fact the resident scan uses."""
    L = cen.shape[0] // 2
    xb = x.astype(jnp.float32) - shift[None, :]
    safe = jnp.clip(pos, 0, L - 1)
    c0 = cen[2 * safe]
    c1 = cen[2 * safe + 1]
    d0 = jnp.sum((xb - c0) ** 2, axis=1)
    d1 = jnp.sum((xb - c1) ** 2, axis=1)
    bit = (d1 < d0).astype(jnp.int32)
    child = 2 * safe + bit
    live = ((pos >= 0) & (w > 0)).astype(jnp.float32) * w
    oh = jax.nn.one_hot(child, cen.shape[0], dtype=jnp.float32) * live[:, None]
    return oh.T @ xb, jnp.sum(oh, axis=0)


@jax.jit
def _bkm_stats_block(x, w, pos, cen, shift):
    """Final per-level pass: child (counts, SSE) + each row's side bit."""
    L = cen.shape[0] // 2
    xb = x.astype(jnp.float32) - shift[None, :]
    safe = jnp.clip(pos, 0, L - 1)
    c0 = cen[2 * safe]
    c1 = cen[2 * safe + 1]
    d0 = jnp.sum((xb - c0) ** 2, axis=1)
    d1 = jnp.sum((xb - c1) ** 2, axis=1)
    bit = (d1 < d0).astype(jnp.int32)
    child = 2 * safe + bit
    live = ((pos >= 0) & (w > 0)).astype(jnp.float32) * w
    oh = jax.nn.one_hot(child, cen.shape[0], dtype=jnp.float32) * live[:, None]
    mind = jnp.where(bit == 1, d1, d0)
    return jnp.sum(oh, axis=0), jnp.sum(oh * mind[:, None], axis=0), bit


@register_model("BisectingKMeansModel")
@dataclass
class BisectingKMeansModel(KMeansModel):
    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        return ("BisectingKMeansModel", meta, arrays)


@dataclass(frozen=True)
class BisectingKMeans(Estimator):
    k: int = 4
    max_iter: int = 20                    # Lloyd iterations per level (Spark default)
    seed: int = 0
    min_divisible_cluster_size: float = 1.0  # rows (>=1) or fraction (<1), Spark semantics
    distance_measure: str = "euclidean"
    # "level": Spark parity — every divisible bottom-level leaf bisects in
    # the same device step (larger clusters first when the k budget runs
    # short); fastest, ~log₂k levels.  "sequential": one split per level,
    # largest-SSE first (sklearn bisecting_strategy="biggest_inertia") —
    # k−1 levels, still one host sync total, and materially better local
    # optima when k is small relative to the true cluster count (a level
    # split can waste budget halving a pure cluster while two merged ones
    # share a leaf).
    strategy: str = "level"
    # 131072 measured fastest on v5e across a 32k-2M sweep (K2≤16, d=8 —
    # the narrow 2-means level step amortizes scan overhead over bigger
    # chunks than the k=256 KMeans step's 32768 optimum).
    chunk_rows: int = 131072
    weight_col: str | None = None  # Spark's weightCol (3.1+)
    # Best-of-n WHOLE-TREE restarts: grow n_restarts complete split trees
    # (restart r reseeds child directions from fold_in(base_key, r); r=0
    # is the base key, so n_restarts=1 reproduces the single-tree
    # behavior exactly) and keep the tree with the lowest final total
    # SSE.  Restarting whole trees — not individual splits — is what
    # makes recovery robust to seed: a greedy per-level criterion can
    # actively prefer an unrecoverable branch (peeling one far cluster
    # off 4 blobs minimizes THAT level's SSE, then the level schedule
    # wastes the k budget halving a pure cluster), whereas whole-tree
    # selection wins whenever ANY restart finds the better structure.
    # 4 is the measured knee: robust across 16 seeds on the blob-recovery
    # gate (2 is not), at half the cost of 8.  Large fits that want the
    # old single-tree cost set n_restarts=1 (bench config 4 does).
    n_restarts: int = 4

    def fit(self, data, label_col: str | None = None, mesh=None) -> BisectingKMeansModel:
        mesh = mesh or default_mesh()
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        x = ds.x.astype(jnp.float32)
        cosine = self.distance_measure == "cosine"
        if cosine:
            # train in the same geometry predict uses: unit sphere
            x = normalize_rows(x) * (ds.w[:, None] > 0)  # 0/1 mask, not the
            # weight value: fractional sample weights must not rescale the
            # unit vectors (they enter via the weighted stats instead)
        d = x.shape[1]

        if self.strategy not in ("level", "sequential"):
            raise ValueError(f"unknown strategy {self.strategy!r}")
        if self.n_restarts < 1:
            raise ValueError(f"n_restarts must be >= 1, got {self.n_restarts}")
        sequential = self.strategy == "sequential"
        # At most ⌊k/2⌋ leaves ever split in one level (n_leaves + #splits
        # ≤ k and #splits ≤ n_leaves); pad L to a power of two so ONE
        # compiled executable serves every level of the fit.  Sequential
        # strategy splits exactly one leaf per level (L=1 → K2=2, the
        # cheapest possible pass).
        L = 1 if sequential else 1 << (max(1, self.k // 2) - 1).bit_length()
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        loop = _make_fit_loop(
            mesh, n_loc, self.k, L, d, self.chunk_rows, cosine, self.max_iter,
            1e-8, sequential,
        )
        is_frac = 1.0 if self.min_divisible_cluster_size < 1.0 else 0.0
        base_key = jax.random.PRNGKey(self.seed)
        best = None  # (cost, centers, sizes, sse, n_splits)
        # one executable, n_restarts whole trees; keep the lowest-cost one
        # (one host sync per tree — n_restarts syncs per fit)
        for r in range(self.n_restarts):
            key_r = base_key if r == 0 else jax.random.fold_in(base_key, r)
            centers, sizes, sse, n_splits = jax.device_get(
                loop(
                    x,
                    ds.w,
                    key_r,
                    jnp.float32(self.min_divisible_cluster_size),
                    jnp.float32(is_frac),
                )
            )
            if float(sizes.sum()) == 0.0:
                raise ValueError("BisectingKMeans fit on an empty dataset")
            cost = float(sse[sizes > 0].sum())
            if best is None or cost < best[0]:
                best = (cost, centers, sizes, sse, n_splits)
        cost, centers, sizes, sse, n_splits = best

        # Compact away empty leaves (failed/one-sided splits); the row
        # assignment never references them.
        keep = np.flatnonzero(sizes > 0)
        return BisectingKMeansModel(
            cluster_centers=np.asarray(centers)[keep].astype(np.float32),
            distance_measure=self.distance_measure,
            training_cost=float(sse[keep].sum()),
            n_iter=int(n_splits),
            cluster_sizes=np.asarray(sizes)[keep],
        )

    def _fit_outofcore(self, hd, mesh=None) -> BisectingKMeansModel:
        """Rows ≫ HBM hierarchical bisection: the SAME level algorithm
        with the per-row leaf assignment carried on HOST (n int32 — tiny
        next to the host-resident matrix itself) and every Lloyd
        iteration / stats pass a streamed block sweep.  All cluster math
        runs recentered around the global mean exactly like the resident
        shard_map loop (same f32-cancellation argument), children are
        seeded from the same ``fold_in(key, level)`` draws, and the
        level bookkeeping (priority, min-size gate, failed-split
        pinning) is the resident logic in host numpy — so both paths
        walk the same split tree up to block-sum rounding."""
        from ..parallel.mesh import default_mesh as _dm
        from ..parallel.outofcore import add_stats, block_moments
        from ..parallel.sharding import replicate, shard_rows

        mesh = mesh or _dm()
        if self.strategy not in ("level", "sequential"):
            raise ValueError(f"unknown strategy {self.strategy!r}")
        if self.n_restarts < 1:
            raise ValueError(f"n_restarts must be >= 1, got {self.n_restarts}")
        sequential = self.strategy == "sequential"
        cosine = self.distance_measure == "cosine"
        k = self.k
        L = 1 if sequential else 1 << (max(1, k // 2) - 1).bit_length()
        d = hd.n_features
        if hd.n == 0:
            raise ValueError("BisectingKMeans fit on an empty dataset")

        from .kmeans import _cosine_prep

        def prep(blk):
            return _cosine_prep(blk.x, blk.w) if cosine else blk.x

        # pass 0: global mean → recentering shift; root center + SSE
        mom = None
        for blk in hd.blocks(mesh):
            # w doubles as the (ignored) y slot — clustering blocks carry
            # no labels and block_moments touches y only for extra stats
            s = block_moments(prep(blk), blk.w, blk.w)
            mom = s if mom is None else add_stats(mom, s)
        sw = max(float(jax.device_get(mom[0])), 0.0)
        if sw == 0.0:
            raise ValueError("BisectingKMeans fit on an empty dataset")
        mean = np.asarray(jax.device_get(mom[1])) / max(sw, 1.0)
        shift = np.zeros((d,), np.float32) if cosine else mean.astype(np.float32)
        root = (mean.astype(np.float32) - shift)
        if cosine:
            root = root / max(np.linalg.norm(root), 1e-12)
        shift_dev = replicate(shift, mesh)

        root_cen = replicate(
            np.broadcast_to(root, (2, d)).astype(np.float32).copy(), mesh
        )
        tot = None
        for i, blk in enumerate(hd.blocks(mesh)):
            pos_b = np.zeros((blk.x.shape[0],), np.int32)
            _, csse, _ = _bkm_stats_block(
                prep(blk), blk.w, shard_rows(pos_b, mesh), root_cen, shift_dev
            )
            tot = csse if tot is None else add_stats(tot, csse)
        root_sse = float(np.asarray(jax.device_get(tot)).sum())

        is_frac = self.min_divisible_cluster_size < 1.0
        min_size = max(
            self.min_divisible_cluster_size * sw
            if is_frac
            else self.min_divisible_cluster_size,
            2.0,
        )

        _, b = hd.block_shape(mesh)

        def grow_tree(tree_key):
            """One complete split tree from ``tree_key`` — the resident
            level loop in host numpy; → (cost, centers, sizes, sse,
            n_splits)."""
            centers = np.zeros((k + 1, d), np.float32)
            centers[0] = root
            sizes = np.zeros((k + 1,), np.float32)
            sizes[0] = sw
            sse = np.zeros((k + 1,), np.float32)
            sse[0] = root_sse
            divisible = np.zeros((k + 1,), bool)
            divisible[0] = True
            assign = np.zeros((hd.n,), np.int32)
            n_leaves, n_splits, level = 1, 0, 0

            while n_leaves < k:
                cand = divisible[:k] & (sizes[:k] >= min_size)
                if not cand.any():
                    break
                priority = sse[:k] if sequential else sizes[:k]
                order = np.argsort(-np.where(cand, priority, -1.0), kind="stable")
                sel = order[:L]
                slot_valid = (np.arange(L) < (k - n_leaves)) & cand[sel]
                slot_of = np.full((k + 1,), -1, np.int32)
                slot_of[sel] = np.where(slot_valid, np.arange(L, dtype=np.int32), -1)

                radius = np.sqrt(
                    np.maximum(sse[sel], 1e-12) / np.maximum(sizes[sel], 1.0)
                )
                dirs = np.asarray(
                    jax.random.normal(jax.random.fold_in(tree_key, level), (L, d)),
                    np.float32,
                )
                dirs = dirs / np.maximum(
                    np.linalg.norm(dirs, axis=1, keepdims=True), 1e-12
                ) * radius[:, None]
                parents = centers[sel]
                cen = np.stack(
                    [parents + 0.5 * dirs, parents - 0.5 * dirs], axis=1
                ).reshape(2 * L, d)
                if cosine:
                    cen = np.asarray(jax.device_get(normalize_rows(jnp.asarray(cen))))
                cen_dev = replicate(cen.astype(np.float32), mesh)

                def block_pos(i: int, rows: int) -> np.ndarray:
                    s, e = i * b, min(i * b + b, hd.n)
                    p = np.full((rows,), -1, np.int32)
                    p[: e - s] = slot_of[np.clip(assign[s:e], 0, k)]
                    return p

                for _ in range(self.max_iter):
                    tot = None
                    for i, blk in enumerate(hd.blocks(mesh)):
                        pos_b = block_pos(i, blk.x.shape[0])
                        s2 = _bkm_lloyd_block(
                            prep(blk), blk.w, shard_rows(pos_b, mesh),
                            cen_dev, shift_dev,
                        )
                        tot = s2 if tot is None else add_stats(tot, s2)
                    sums, counts = (np.asarray(jax.device_get(v)) for v in tot)
                    new_cen = np.where(
                        (counts > 0)[:, None],
                        sums / np.maximum(counts, 1.0)[:, None],
                        cen,
                    )
                    if cosine:
                        new_cen = np.asarray(
                            jax.device_get(normalize_rows(jnp.asarray(new_cen)))
                        )
                    valid2 = np.repeat(slot_valid, 2)
                    move = float(
                        np.max(np.sum((new_cen - cen) ** 2, axis=1) * valid2)
                    )
                    cen = new_cen.astype(np.float32)
                    cen_dev = replicate(cen, mesh)
                    if move <= 1e-8:
                        break

                counts_t = sse_t = None
                bits_blocks = []
                for i, blk in enumerate(hd.blocks(mesh)):
                    pos_b = block_pos(i, blk.x.shape[0])
                    c, cs, bit = _bkm_stats_block(
                        prep(blk), blk.w, shard_rows(pos_b, mesh),
                        cen_dev, shift_dev,
                    )
                    counts_t = c if counts_t is None else add_stats(counts_t, c)
                    sse_t = cs if sse_t is None else add_stats(sse_t, cs)
                    bits_blocks.append((i, pos_b, np.asarray(jax.device_get(bit))))
                counts2 = np.asarray(jax.device_get(counts_t)).reshape(L, 2)
                csse2 = np.asarray(jax.device_get(sse_t)).reshape(L, 2)
                cen2 = cen.reshape(L, 2, d)

                succ = slot_valid & (counts2[:, 1] > 0)
                new_id = np.where(
                    succ, n_leaves + np.cumsum(succ.astype(np.int32)) - 1, k
                ).astype(np.int32)
                for i, pos_b, bit in bits_blocks:
                    s, e = i * b, min(i * b + b, hd.n)
                    p = pos_b[: e - s]
                    bt = bit[: e - s]
                    safe_p = np.clip(p, 0, L - 1)
                    relabel = (p >= 0) & (bt == 1) & succ[safe_p]
                    if relabel.any():
                        seg = assign[s:e]
                        seg[relabel] = new_id[safe_p[relabel]]
                        assign[s:e] = seg

                upd = sel[succ]
                centers[upd] = cen2[succ, 0]
                sizes[upd] = counts2[succ, 0]
                sse[upd] = csse2[succ, 0]
                divisible[sel[slot_valid]] = (
                    succ[slot_valid] & (counts2[slot_valid, 0] > 0)
                )
                nid = new_id[succ]
                centers[nid] = cen2[succ, 1]
                sizes[nid] = counts2[succ, 1]
                sse[nid] = csse2[succ, 1]
                divisible[nid] = True
                grown = int(succ.sum())
                n_leaves += grown
                n_splits += grown
                level += 1
                if grown == 0 and not divisible[:k].any():
                    break

            cost = float(sse[:k][sizes[:k] > 0].sum())
            return cost, centers, sizes, sse, n_splits

        # best-of-n WHOLE-TREE restarts, the same schedule as the resident
        # path (restart r reseeds from fold_in(base_key, r); r=0 is the
        # base key itself) — both paths therefore grow the same candidate
        # trees and select by the same final-cost criterion
        base_key = jax.random.PRNGKey(self.seed)
        best = None
        for r in range(self.n_restarts):
            tree_key = base_key if r == 0 else jax.random.fold_in(base_key, r)
            out = grow_tree(tree_key)
            if best is None or out[0] < best[0]:
                best = out
        _, centers, sizes, sse, n_splits = best

        keep = np.flatnonzero(sizes[:k] > 0)
        return BisectingKMeansModel(
            cluster_centers=(centers[:k] + shift[None, :])[keep].astype(
                np.float32
            ),
            distance_measure=self.distance_measure,
            training_cost=float(sse[:k][keep].sum()),
            n_iter=int(n_splits),
            cluster_sizes=sizes[:k][keep],
        )
