"""BisectingKMeans — hierarchical divisive clustering (BASELINE config 4).

Capability parity: ``pyspark.ml.clustering.BisectingKMeans`` (k, maxIter,
seed, minDivisibleClusterSize; model exposes centers and ``computeCost``).
Spark grows the tree **level by level** — "the bisecting steps of clusters
on the same level are grouped together to increase parallelism", with
larger clusters given priority when splitting everything would overshoot k.

The TPU-native form goes one step further: the ENTIRE tree growth is one
jitted device computation — level scheduling (divisibility, the k budget,
Spark's larger-cluster priority), child seeding (``jax.random`` folded per
level), the constrained 2-means Lloyd loop, and the leaf bookkeeping all
run inside a single ``lax.while_loop`` under ``shard_map``, with exactly
ONE host sync per fit.  That matters doubly on remote-attached chips where
every host↔device round trip costs tens of milliseconds.

Within a level, the L splitting leaves contribute a flattened (2L, d)
children tensor; each row's distance row (chunk, 2L) — one MXU matmul, the
same shape as the KMeans step — is masked so the row competes only between
its own leaf's two children, and child sums/counts are ``psum``'d over the
mesh's data axis.  Lloyd iterations rank children by ``|c|² − 2x·c`` (the
``|x|²`` term cancels inside a row), so the convergence loop reads strictly
less HBM than a full distance pass; the true SSE is computed once on the
converged centers.

Two split schedules share the one executable: ``strategy="level"`` (Spark
parity, above) and ``strategy="sequential"`` (one largest-SSE split per
level — sklearn's ``bisecting_strategy="biggest_inertia"`` — better local
optima when k is small relative to the true cluster count, still a single
host sync per fit).

Per-hospital federation (BASELINE config 4 "one partition per TPU chip"):
the level step's math is placement-invariant (weighted psum sums), so a
dataset laid out with each hospital's rows on one data shard converges
identically to a shuffled layout.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..io.model_io import register_model
from ..ops.distance import normalize_rows, pairwise_sqdist, sq_norms
from ..parallel.mesh import DATA_AXIS, default_mesh
from ..parallel.sharding import DeviceDataset
from .base import Estimator, as_device_dataset
from .kmeans import KMeansModel, _chunked

# np scalar, not jnp: a module-level jnp constant would initialize
# the backend at import time (hangs when the TPU tunnel is down)
_BIG = np.float32(1e30)


@lru_cache(maxsize=32)
def _make_fit_loop(
    mesh: Mesh,
    n_loc: int,
    k: int,
    L: int,
    d: int,
    chunk_rows: int,
    cosine: bool,
    max_iter: int,
    tol_sq: float,
    by_sse: bool,
):
    """The whole BisectingKMeans fit as one jitted shard_map computation.

    State arrays carry k+1 rows: row k is a write-only dummy slot so masked
    scatters (failed splits) need no dynamic shapes.  Returns (centers,
    sizes, sse, n_splits) — one host transfer per fit.
    """
    n_chunks, chunk = _chunked(n_loc, chunk_rows)
    pad_to = n_chunks * chunk
    K2 = 2 * L
    child_iota = jnp.arange(K2, dtype=jnp.int32)

    def _vary(z):
        return jax.tree.map(lambda a: lax.pcast(a, DATA_AXIS, to="varying"), z)

    def _lloyd_scan(x_c, w_c, pos_c, cen, shift):
        """Per-shard (sums, counts) for one Lloyd iteration.  Children are
        ranked by |c|²−2x·c — the |x|² term cancels within a row.  ``shift``
        recenters rows chunk-by-chunk (fused into the read; see shard_fn)."""
        c_sq = sq_norms(cen)

        def body(carry, inputs):
            sums, counts = carry
            xb, wb, pb = inputs
            xb = xb - shift[None, :]
            # HIGHEST precision, matching pairwise_sqdist: the two children
            # are seeded deliberately close, and a bf16 dot can tie them.
            cross = jnp.dot(xb, cen.T, precision=lax.Precision.HIGHEST)
            d2 = c_sq[None, :] - 2.0 * cross                  # (chunk, K2)
            d2 = jnp.where((child_iota[None, :] // 2) == pb[:, None], d2, _BIG)
            arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
            wv = jnp.where((pb >= 0) & (wb > 0), wb, 0.0)
            onehot = jax.nn.one_hot(arg, K2, dtype=xb.dtype) * wv[:, None]
            return (sums + onehot.T @ xb, counts + jnp.sum(onehot, axis=0)), None

        init = _vary((jnp.zeros((K2, d), x_c.dtype), jnp.zeros((K2,), x_c.dtype)))
        (sums, counts), _ = lax.scan(body, init, (x_c, w_c, pos_c))
        return lax.psum(sums, DATA_AXIS), lax.psum(counts, DATA_AXIS)

    def _stats_scan(x_c, w_c, pos_c, cen, shift):
        """Final pass on converged centers: true per-child counts/SSE plus
        each row's child bit."""
        c_sq = sq_norms(cen)

        def body(carry, inputs):
            counts, sse = carry
            xb, wb, pb = inputs
            xb = xb - shift[None, :]
            d2 = pairwise_sqdist(xb, cen, c_sq=c_sq)
            d2 = jnp.where((child_iota[None, :] // 2) == pb[:, None], d2, _BIG)
            arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
            mind = jnp.maximum(jnp.min(d2, axis=1), 0.0)
            live = (pb >= 0) & (wb > 0)
            wv = jnp.where(live, wb, 0.0)
            onehot = jax.nn.one_hot(arg, K2, dtype=xb.dtype) * wv[:, None]
            counts = counts + jnp.sum(onehot, axis=0)
            sse = sse + onehot.T @ jnp.where(live, mind, 0.0)
            return (counts, sse), arg % 2

        init = _vary((jnp.zeros((K2,), x_c.dtype), jnp.zeros((K2,), x_c.dtype)))
        (counts, sse), bits = lax.scan(body, init, (x_c, w_c, pos_c))
        return lax.psum(counts, DATA_AXIS), lax.psum(sse, DATA_AXIS), bits

    def shard_fn(x, w, key, min_div, is_frac):
        xp = jnp.pad(x, ((0, pad_to - n_loc), (0, 0)))
        wp = jnp.pad(w, (0, pad_to - n_loc))
        x_c = xp.reshape(n_chunks, chunk, d)
        w_c = wp.reshape(n_chunks, chunk)

        # ---- root leaf: weighted mean, then a per-row SSE pass ----------
        def mean_body(carry, inputs):
            s0, s1 = carry
            xb, wb = inputs
            return (s0 + jnp.sum(wb), s1 + wb @ xb), None

        init = _vary((jnp.zeros((), x.dtype), jnp.zeros((d,), x.dtype)))
        (s0, s1), _ = lax.scan(mean_body, init, (x_c, w_c))
        s0 = lax.psum(s0, DATA_AXIS)
        s1 = lax.psum(s1, DATA_AXIS)
        mean = s1 / jnp.maximum(s0, 1.0)
        # All cluster math runs in data RECENTERED around the global mean
        # (Euclidean SSE/assignments are translation-invariant): with the
        # raw values, an unstandardized table whose mean dwarfs its spread
        # (hospital counts, timestamps) loses the entire split signal to
        # f32 cancellation in |c|²−2x·c and in the center sums.  The shift
        # is fused into each chunk read — no second copy of x in HBM.  The
        # cosine path is already on the unit sphere (bounded magnitudes)
        # and must not be translated.
        shift = jnp.zeros((d,), x.dtype) if cosine else mean
        root = mean - shift
        if cosine:
            root = root / jnp.maximum(jnp.linalg.norm(root), 1e-12)

        # Per-row (x−c)² accumulation — the moment formula Σw|x|²−n|c|²
        # cancels catastrophically for the same reason as above.
        def sse_body(acc, inputs):
            xb, wb = inputs
            diff = (xb - shift[None, :]) - root[None, :]
            return acc + jnp.sum(jnp.sum(diff * diff, axis=1) * wb), None

        (root_sse), _ = lax.scan(sse_body, _vary(jnp.zeros((), x.dtype)), (x_c, w_c))
        root_sse = lax.psum(root_sse, DATA_AXIS)
        min_size = jnp.maximum(jnp.where(is_frac > 0, min_div * s0, min_div), 2.0)

        centers = jnp.zeros((k + 1, d), x.dtype).at[0].set(root)
        sizes = jnp.zeros((k + 1,), x.dtype).at[0].set(s0)
        sse = jnp.zeros((k + 1,), x.dtype).at[0].set(root_sse)
        divisible = jnp.zeros((k + 1,), bool).at[0].set(True)
        assign = _vary(jnp.zeros((n_loc,), jnp.int32))

        def outer_cond(carry):
            level, _, _, sizes, _, divisible, n_leaves, _ = carry
            cand = divisible[:k] & (sizes[:k] >= min_size)
            return (n_leaves < k) & jnp.any(cand)

        def outer_body(carry):
            level, assign, centers, sizes, sse, divisible, n_leaves, n_splits = carry
            # -- schedule: level strategy ranks by size (Spark's
            # larger-cluster priority); sequential ranks by SSE and splits
            # one leaf per level (sklearn biggest_inertia)
            cand = divisible[:k] & (sizes[:k] >= min_size)
            priority = sse[:k] if by_sse else sizes[:k]
            order = jnp.argsort(-jnp.where(cand, priority, -1.0))
            sel = order[:L]                                   # (L,) leaf ids
            slot_valid = (jnp.arange(L) < (k - n_leaves)) & cand[sel]
            slot_of = (
                jnp.full((k + 1,), -1, jnp.int32)
                .at[sel]
                .set(jnp.where(slot_valid, jnp.arange(L, dtype=jnp.int32), -1))
            )
            # -- seed children: parent ± RMS-radius perturbation
            radius = jnp.sqrt(
                jnp.maximum(sse[sel], 1e-12) / jnp.maximum(sizes[sel], 1.0)
            )
            dirs = jax.random.normal(jax.random.fold_in(key, level), (L, d), x.dtype)
            dirs = dirs / jnp.maximum(
                jnp.linalg.norm(dirs, axis=1, keepdims=True), 1e-12
            ) * radius[:, None]
            parents = centers[sel]
            c01 = jnp.stack([parents + 0.5 * dirs, parents - 0.5 * dirs], axis=1)
            if cosine:
                c01 = normalize_rows(c01.reshape(K2, d)).reshape(L, 2, d)
            cen0 = c01.reshape(K2, d)

            pos = slot_of[jnp.clip(jnp.pad(assign, (0, pad_to - n_loc)), 0, k)]
            pos = jnp.where(wp > 0, pos, -1)
            pos_c = pos.reshape(n_chunks, chunk)

            # -- constrained 2-means Lloyd loop over ALL splitting leaves
            def cond(c):
                it, _, move = c
                return (it < max_iter) & (move > tol_sq)

            def body(c):
                it, cen, _ = c
                sums, counts = _lloyd_scan(x_c, w_c, pos_c, cen, shift)
                new_cen = jnp.where(
                    (counts > 0)[:, None], sums / jnp.maximum(counts, 1.0)[:, None], cen
                )
                if cosine:
                    new_cen = normalize_rows(new_cen)
                valid2 = jnp.repeat(slot_valid, 2)
                move = jnp.max(jnp.sum((new_cen - cen) ** 2, axis=1) * valid2)
                return it + 1, new_cen, move

            _, cen, _ = lax.while_loop(cond, body, (jnp.int32(0), cen0, jnp.float32(jnp.inf)))

            counts, csse, bits = _stats_scan(x_c, w_c, pos_c, cen, shift)
            counts2 = counts.reshape(L, 2)
            csse2 = csse.reshape(L, 2)
            cen2 = cen.reshape(L, 2, d)

            # -- bookkeeping: a split succeeds iff the new child got rows
            succ = slot_valid & (counts2[:, 1] > 0)
            new_id = jnp.where(
                succ, n_leaves + jnp.cumsum(succ.astype(jnp.int32)) - 1, k
            )
            bit = bits.reshape(pad_to)[:n_loc]
            pos_n = pos[:n_loc]
            safe_p = jnp.clip(pos_n, 0, L - 1)
            relabel = (pos_n >= 0) & (bit == 1) & succ[safe_p]
            assign = jnp.where(relabel, new_id[safe_p], assign)

            centers = centers.at[sel].set(
                jnp.where(succ[:, None], cen2[:, 0], centers[sel])
            )
            sizes = sizes.at[sel].set(jnp.where(succ, counts2[:, 0], sizes[sel]))
            sse = sse.at[sel].set(jnp.where(succ, csse2[:, 0], sse[sel]))
            # parent stays divisible iff it kept rows; a failed split (new
            # child empty — duplicate-point cluster) pins the leaf closed.
            divisible = divisible.at[sel].set(
                jnp.where(slot_valid, succ & (counts2[:, 0] > 0), divisible[sel])
            )
            centers = centers.at[new_id].set(
                jnp.where(succ[:, None], cen2[:, 1], centers[new_id])
            )
            sizes = sizes.at[new_id].set(jnp.where(succ, counts2[:, 1], sizes[new_id]))
            sse = sse.at[new_id].set(jnp.where(succ, csse2[:, 1], sse[new_id]))
            divisible = divisible.at[new_id].set(
                jnp.where(succ, True, divisible[new_id])
            )
            grown = jnp.sum(succ.astype(jnp.int32))
            return (
                level + 1,
                assign,
                centers,
                sizes,
                sse,
                divisible,
                n_leaves + grown,
                n_splits + grown,
            )

        carry = (jnp.int32(0), assign, centers, sizes, sse, divisible, jnp.int32(1), jnp.int32(0))
        _, _, centers, sizes, sse, _, _, n_splits = lax.while_loop(
            outer_cond, outer_body, carry
        )
        # undo the recentering on the way out
        return centers[:k] + shift[None, :], sizes[:k], sse[:k], n_splits

    return jax.jit(
        jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P(), P()),
            out_specs=(P(), P(), P(), P()),
        )
    )


@register_model("BisectingKMeansModel")
@dataclass
class BisectingKMeansModel(KMeansModel):
    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        return ("BisectingKMeansModel", meta, arrays)


@dataclass(frozen=True)
class BisectingKMeans(Estimator):
    k: int = 4
    max_iter: int = 20                    # Lloyd iterations per level (Spark default)
    seed: int = 0
    min_divisible_cluster_size: float = 1.0  # rows (>=1) or fraction (<1), Spark semantics
    distance_measure: str = "euclidean"
    # "level": Spark parity — every divisible bottom-level leaf bisects in
    # the same device step (larger clusters first when the k budget runs
    # short); fastest, ~log₂k levels.  "sequential": one split per level,
    # largest-SSE first (sklearn bisecting_strategy="biggest_inertia") —
    # k−1 levels, still one host sync total, and materially better local
    # optima when k is small relative to the true cluster count (a level
    # split can waste budget halving a pure cluster while two merged ones
    # share a leaf).
    strategy: str = "level"
    # 131072 measured fastest on v5e across a 32k-2M sweep (K2≤16, d=8 —
    # the narrow 2-means level step amortizes scan overhead over bigger
    # chunks than the k=256 KMeans step's 32768 optimum).
    chunk_rows: int = 131072
    weight_col: str | None = None  # Spark's weightCol (3.1+)

    def fit(self, data, label_col: str | None = None, mesh=None) -> BisectingKMeansModel:
        mesh = mesh or default_mesh()
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh, weight_col=self.weight_col)
        x = ds.x.astype(jnp.float32)
        cosine = self.distance_measure == "cosine"
        if cosine:
            # train in the same geometry predict uses: unit sphere
            x = normalize_rows(x) * (ds.w[:, None] > 0)  # 0/1 mask, not the
            # weight value: fractional sample weights must not rescale the
            # unit vectors (they enter via the weighted stats instead)
        d = x.shape[1]

        if self.strategy not in ("level", "sequential"):
            raise ValueError(f"unknown strategy {self.strategy!r}")
        sequential = self.strategy == "sequential"
        # At most ⌊k/2⌋ leaves ever split in one level (n_leaves + #splits
        # ≤ k and #splits ≤ n_leaves); pad L to a power of two so ONE
        # compiled executable serves every level of the fit.  Sequential
        # strategy splits exactly one leaf per level (L=1 → K2=2, the
        # cheapest possible pass).
        L = 1 if sequential else 1 << (max(1, self.k // 2) - 1).bit_length()
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        loop = _make_fit_loop(
            mesh, n_loc, self.k, L, d, self.chunk_rows, cosine, self.max_iter,
            1e-8, sequential,
        )
        is_frac = 1.0 if self.min_divisible_cluster_size < 1.0 else 0.0
        centers, sizes, sse, n_splits = jax.device_get(
            loop(
                x,
                ds.w,
                jax.random.PRNGKey(self.seed),
                jnp.float32(self.min_divisible_cluster_size),
                jnp.float32(is_frac),
            )
        )
        if float(sizes.sum()) == 0.0:
            raise ValueError("BisectingKMeans fit on an empty dataset")

        # Compact away empty leaves (failed/one-sided splits); the row
        # assignment never references them.
        keep = np.flatnonzero(sizes > 0)
        return BisectingKMeansModel(
            cluster_centers=np.asarray(centers)[keep].astype(np.float32),
            distance_measure=self.distance_measure,
            training_cost=float(sse[keep].sum()),
            n_iter=int(n_splits),
            cluster_sizes=np.asarray(sizes)[keep],
        )
