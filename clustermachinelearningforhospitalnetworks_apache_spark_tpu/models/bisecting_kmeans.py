"""BisectingKMeans — hierarchical divisive clustering (BASELINE config 4).

Capability parity: ``pyspark.ml.clustering.BisectingKMeans`` (k,
maxIter, seed, minDivisibleClusterSize; model exposes centers and can
``computeCost``).  Spark grows the tree by repeatedly running distributed
2-means on the rows of the cluster being split.  The TPU-native form keeps
the *full* row-sharded array resident and bisects by **masking**: the
subset being split is selected with a 0/1 weight vector (no gather, no
dynamic shapes — XLA-friendly), and the inner 2-means is the same jit'd
Lloyd step as :class:`~.kmeans.KMeans` restricted by those weights.  The
leaf chosen for each split is the one with the largest within-cluster SSE
(falling back to largest size), matching Spark's divisible-cluster rule.

Per-hospital federation note (BASELINE config 4 "one partition per TPU
chip"): rows land on data shards by ingest order, so hospital-partitioned
ingest → per-chip hospital locality; the bisection math is unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.distance import assign_clusters, normalize_rows
from ..parallel.mesh import default_mesh
from ..parallel.sharding import DeviceDataset
from .base import Estimator, as_device_dataset
from .kmeans import KMeans, KMeansModel


@jax.jit
def _masked_assign_cost(x, w, centers):
    assign, mind2 = assign_clusters(x, centers)
    return assign, jnp.sum(mind2 * w)


@register_model("BisectingKMeansModel")
@dataclass
class BisectingKMeansModel(KMeansModel):
    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        return ("BisectingKMeansModel", meta, arrays)


@dataclass(frozen=True)
class BisectingKMeans(Estimator):
    k: int = 4
    max_iter: int = 20                    # Lloyd iterations per bisection (Spark default)
    seed: int = 0
    min_divisible_cluster_size: float = 1.0  # rows (>=1) or fraction (<1), Spark semantics
    distance_measure: str = "euclidean"

    def fit(self, data, label_col: str | None = None, mesh=None) -> BisectingKMeansModel:
        mesh = mesh or default_mesh()
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh)
        x = ds.x.astype(jnp.float32)
        if self.distance_measure == "cosine":
            # train in the same geometry predict uses: unit sphere
            x = normalize_rows(x) * ds.w[:, None]
        n_total = float(jax.device_get(jnp.sum(ds.w)))
        if n_total == 0:
            raise ValueError("BisectingKMeans fit on an empty dataset")
        min_size = (
            self.min_divisible_cluster_size
            if self.min_divisible_cluster_size >= 1
            else self.min_divisible_cluster_size * n_total
        )

        # assignment: leaf id per row; root center = weighted mean (on device)
        assign = jnp.zeros((ds.n_padded,), jnp.int32)
        root = np.asarray(
            jax.device_get(
                jnp.sum(x * ds.w[:, None], axis=0) / jnp.maximum(jnp.sum(ds.w), 1.0)
            ),
            dtype=np.float32,
        )
        if self.distance_measure == "cosine":
            root = root / max(float(np.linalg.norm(root)), 1e-12)
        centers: list[np.ndarray] = [root]
        sse = {0: float(jax.device_get(_masked_assign_cost(x, ds.w, jnp.asarray(centers[0])[None])[1]))}
        sizes = {0: n_total}
        rng = np.random.default_rng(self.seed)

        while len(centers) < self.k:
            # pick the divisible leaf with the largest SSE
            candidates = [c for c in sse if sizes[c] >= max(min_size, 2)]
            if not candidates:
                break
            target = max(candidates, key=lambda c: (sse[c], sizes[c]))
            mask = (assign == target).astype(x.dtype) * ds.w

            # inner 2-means on the masked subset (x is already normalized in
            # cosine mode; the inner fit re-normalizes idempotently and keeps
            # its centroids on the sphere)
            sub = KMeans(
                k=2,
                max_iter=self.max_iter,
                seed=int(rng.integers(2**31 - 1)),
                distance_measure=self.distance_measure,
            )
            sub_model = sub.fit(DeviceDataset(x=x, y=ds.y, w=mask), mesh=mesh)
            c2 = jnp.asarray(sub_model.cluster_centers, jnp.float32)
            sub_assign, _ = _masked_assign_cost(x, mask, c2)

            new_id = len(centers)
            in_target = assign == target
            assign = jnp.where(in_target & (sub_assign == 1), new_id, assign)
            centers[target] = sub_model.cluster_centers[0]
            centers.append(sub_model.cluster_centers[1])

            for cid, cen in ((target, centers[target]), (new_id, centers[new_id])):
                m = (assign == cid).astype(x.dtype) * ds.w
                _, cost = _masked_assign_cost(x, m, jnp.asarray(cen)[None])
                sse[cid] = float(jax.device_get(cost))
                sizes[cid] = float(jax.device_get(jnp.sum(m)))

        all_centers = np.stack(centers).astype(np.float32)
        total_cost = sum(sse.values())
        counts = np.array([sizes[i] for i in range(len(centers))])
        return BisectingKMeansModel(
            cluster_centers=all_centers,
            distance_measure=self.distance_measure,
            training_cost=total_cost,
            n_iter=len(centers) - 1,
            cluster_sizes=counts,
        )
