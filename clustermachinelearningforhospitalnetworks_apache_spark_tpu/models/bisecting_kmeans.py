"""BisectingKMeans — hierarchical divisive clustering (BASELINE config 4).

Capability parity: ``pyspark.ml.clustering.BisectingKMeans`` (k,
maxIter, seed, minDivisibleClusterSize; model exposes centers and can
``computeCost``).  Spark grows the tree by repeatedly running distributed
2-means on the rows of the cluster being split.  The TPU-native form keeps
the *full* row-sharded array resident and bisects by **masking**: the
subset being split is selected with a 0/1 weight vector (no gather, no
dynamic shapes — XLA-friendly), and the inner 2-means is the same jit'd
Lloyd step as :class:`~.kmeans.KMeans` restricted by those weights.  The
leaf chosen for each split is the one with the largest within-cluster SSE
(falling back to largest size), matching Spark's divisible-cluster rule.

Per-hospital federation note (BASELINE config 4 "one partition per TPU
chip"): rows land on data shards by ingest order, so hospital-partitioned
ingest → per-chip hospital locality; the bisection math is unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..ops.distance import assign_clusters, normalize_rows
from ..parallel.mesh import default_mesh
from ..parallel.sharding import DeviceDataset
from .base import Estimator, as_device_dataset
from .kmeans import KMeans, KMeansModel


@jax.jit
def _masked_assign_cost(x, w, centers):
    assign, mind2 = assign_clusters(x, centers)
    return assign, jnp.sum(mind2 * w)


@jax.jit
def _split_stats(x, mask, c2):
    """One fused device call per completed bisection: child assignment plus
    both children's SSE and sizes (replaces three separate full-data
    passes — each call costs a host→device dispatch round trip, which
    dominates wall-clock on remote-attached chips)."""
    assign, mind2 = assign_clusters(x, c2)
    m0 = mask * (assign == 0)
    m1 = mask * (assign == 1)
    return (
        assign,
        jnp.sum(mind2 * m0),
        jnp.sum(mind2 * m1),
        jnp.sum(m0),
        jnp.sum(m1),
    )


@register_model("BisectingKMeansModel")
@dataclass
class BisectingKMeansModel(KMeansModel):
    def _artifacts(self):
        name, meta, arrays = super()._artifacts()
        return ("BisectingKMeansModel", meta, arrays)


@dataclass(frozen=True)
class BisectingKMeans(Estimator):
    k: int = 4
    max_iter: int = 20                    # Lloyd iterations per bisection (Spark default)
    seed: int = 0
    min_divisible_cluster_size: float = 1.0  # rows (>=1) or fraction (<1), Spark semantics
    distance_measure: str = "euclidean"

    def fit(self, data, label_col: str | None = None, mesh=None) -> BisectingKMeansModel:
        mesh = mesh or default_mesh()
        ds: DeviceDataset = as_device_dataset(data, mesh=mesh)
        x = ds.x.astype(jnp.float32)
        if self.distance_measure == "cosine":
            # train in the same geometry predict uses: unit sphere
            x = normalize_rows(x) * ds.w[:, None]
        n_total = float(jax.device_get(jnp.sum(ds.w)))
        if n_total == 0:
            raise ValueError("BisectingKMeans fit on an empty dataset")
        min_size = (
            self.min_divisible_cluster_size
            if self.min_divisible_cluster_size >= 1
            else self.min_divisible_cluster_size * n_total
        )

        # assignment: leaf id per row; root center = weighted mean (on device)
        assign = jnp.zeros((ds.n_padded,), jnp.int32)
        root = np.asarray(
            jax.device_get(
                jnp.sum(x * ds.w[:, None], axis=0) / jnp.maximum(jnp.sum(ds.w), 1.0)
            ),
            dtype=np.float32,
        )
        if self.distance_measure == "cosine":
            root = root / max(float(np.linalg.norm(root)), 1e-12)
        centers: list[np.ndarray] = [root]
        sse = {0: float(jax.device_get(_masked_assign_cost(x, ds.w, jnp.asarray(centers[0])[None])[1]))}
        sizes = {0: n_total}
        rng = np.random.default_rng(self.seed)

        # One cached Lloyd step serves every bisection (k=2 padded to the
        # model axis); driving it directly skips KMeans.fit's host-side
        # init sampling — the per-split host↔device round trips that
        # dominated wall-clock on remote-attached chips.
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
        from .kmeans import _make_train_loop

        m_axis = mesh.shape[MODEL_AXIS]
        k_pad = -(-2 // m_axis) * m_axis
        n_loc = ds.n_padded // mesh.shape[DATA_AXIS]
        cosine = self.distance_measure == "cosine"
        # Whole inner 2-means as one device computation (single host sync
        # per bisection instead of one per Lloyd iteration).
        loop = _make_train_loop(
            mesh, n_loc, k_pad, x.shape[1], KMeans().chunk_rows, cosine,
            self.max_iter, 1e-8,
        )
        c_valid = np.zeros((k_pad,), np.float32)
        c_valid[:2] = 1.0
        c_valid_dev = jax.device_put(c_valid, NamedSharding(mesh, P(MODEL_AXIS)))

        while len(centers) < self.k:
            # pick the divisible leaf with the largest SSE
            candidates = [c for c in sse if sizes[c] >= max(min_size, 2)]
            if not candidates:
                break
            target = max(candidates, key=lambda c: (sse[c], sizes[c]))
            mask = (assign == target).astype(x.dtype) * ds.w

            # inner 2-means, initialized Spark-style from the parent center
            # ± an RMS-radius perturbation (no data sampling needed)
            parent = centers[target].astype(np.float64)
            radius = np.sqrt(max(sse[target], 1e-12) / max(sizes[target], 1.0))
            direction = rng.normal(size=parent.shape)
            direction *= radius / max(np.linalg.norm(direction), 1e-12)
            cen0 = np.zeros((k_pad, x.shape[1]), np.float32)
            cen0[0] = parent + 0.5 * direction
            cen0[1] = parent - 0.5 * direction
            if cosine:
                norms = np.linalg.norm(cen0[:2], axis=1, keepdims=True)
                cen0[:2] = cen0[:2] / np.maximum(norms, 1e-12)
            c2 = jax.device_put(cen0, NamedSharding(mesh, P(MODEL_AXIS, None)))
            c2, _, _, _ = loop(x, mask, c2, c_valid_dev)

            sub_assign, sse0, sse1, n0, n1 = _split_stats(x, mask, c2[:2])
            new_id = len(centers)
            in_target = assign == target
            assign = jnp.where(in_target & (sub_assign == 1), new_id, assign)
            # ONE host sync per bisection: everything the split decision
            # needs comes back in a single batched transfer.
            c2_host, s0, s1, z0, z1 = jax.device_get((c2, sse0, sse1, n0, n1))
            centers[target] = np.asarray(c2_host)[0]
            centers.append(np.asarray(c2_host)[1])
            sse[target] = float(s0)
            sse[new_id] = float(s1)
            sizes[target] = float(z0)
            sizes[new_id] = float(z1)

        all_centers = np.stack(centers).astype(np.float32)
        total_cost = sum(sse.values())
        counts = np.array([sizes[i] for i in range(len(centers))])
        return BisectingKMeansModel(
            cluster_centers=all_centers,
            distance_measure=self.distance_measure,
            training_cost=total_cost,
            n_iter=len(centers) - 1,
            cluster_sizes=counts,
        )
