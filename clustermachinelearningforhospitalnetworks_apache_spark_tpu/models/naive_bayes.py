"""NaiveBayes — multinomial, bernoulli, complement & gaussian.

Parity with ``pyspark.ml.classification.NaiveBayes``: the full Spark 3.x
``modelType`` surface — "multinomial" (Spark's default, Laplace
``smoothing``), "bernoulli" (binary features), "complement" (Rennie's CNB,
Spark 3.0+; matches sklearn's ``ComplementNB(norm=False)``), and
"gaussian" (Spark 3.0+).  Class priors use Spark's smoothed convention
``pi = log(n_c + λ) − log(n + kλ)`` (MLlib applies the Laplace lambda to
priors too, unlike sklearn).

MLlib aggregates per-class feature sums with one ``treeAggregate``; here
the same statistics are one jit'd one-hot contraction over the row-sharded
dataset — a (k, d) matmul on the MXU whose cross-shard sum lowers to a
psum — so the whole fit is a single device pass regardless of n (all four
model types consume the same (counts, Σx) statistics except gaussian's
extra Σx² pass).

Prediction is a dense (n, k) log-likelihood matmul + argmax, the same
shape as the KMeans assignment step.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset, check_features


@partial(jax.jit, static_argnames=("k", "binary"))
def _count_sums(x: jax.Array, y: jax.Array, w: jax.Array, k: int, binary: bool = False):
    """Per-class weighted (count, Σx) + a validity flag — the shared
    multinomial/bernoulli/complement stats, one one-hot contraction (no
    Σx² pass).  ``binary`` flags rows whose features aren't exactly 0/1
    (the bernoulli contract); otherwise negatives/NaN."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=x.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)                 # (k,)
    s1 = onehot.T @ x                                # (k, d)
    xm = jnp.where(w[:, None] > 0, x, 0.0)
    if binary:
        bad = jnp.any(~((xm == 0.0) | (xm == 1.0)))
    else:
        # ~(x >= 0) catches BOTH negatives and NaN in one reduction — a NaN
        # would otherwise pass a `< 0` check and silently poison theta
        bad = jnp.any(~(xm >= 0))
    return counts, s1, bad


@partial(jax.jit, static_argnames=("k",))
def _gaussian_stats_centered(
    x: jax.Array, y: jax.Array, w: jax.Array, k: int, gmean: jax.Array
):
    """Per-class weighted (count, Σxc, Σxc²) at a FIXED center — the
    per-block half of :func:`_gaussian_stats` for out-of-core fits (the
    resident version computes ``gmean`` in the same jit)."""
    xm = jnp.where(w[:, None] > 0, x, 0.0)
    xc = xm - gmean[None, :]
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=x.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)
    s1c = onehot.T @ xc
    s2c = onehot.T @ (xc * xc)
    return counts, s1c, s2c


@partial(jax.jit, static_argnames=("k",))
def _gaussian_stats(x: jax.Array, y: jax.Array, w: jax.Array, k: int):
    """Per-class weighted (count, Σxc, Σxc²) of GLOBALLY CENTERED features.

    Centering kills the E[x²] − mean² catastrophic cancellation for
    features whose mean dwarfs their within-class std (e.g. a year
    column): after the shift, class means are O(within-class spread), so
    the f32 sums lose nothing that matters.  One extra cheap global-mean
    reduction buys f64-two-pass-quality variances."""
    n = jnp.maximum(jnp.sum(w), 1.0)
    # mask invalid rows BEFORE any product with x: a NaN in a w=0 row
    # would otherwise poison gmean/s2c (w=0 rows are contractually inert)
    xm = jnp.where(w[:, None] > 0, x, 0.0)
    gmean = jnp.sum(xm * w[:, None], axis=0) / n
    xc = xm - gmean[None, :]
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=x.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)
    s1c = onehot.T @ xc
    s2c = onehot.T @ (xc * xc)
    return counts, s1c, s2c, gmean


@register_model("NaiveBayesModel")
@dataclass
class NaiveBayesModel(Model):
    model_type: str                 # multinomial | bernoulli | complement | gaussian
    pi: np.ndarray                  # (k,) log class priors
    theta: np.ndarray               # (k, d): log P(feat|class) | means | CNB weights
    sigma: np.ndarray | None = None  # (k, d) variances (gaussian only)
    theta2: np.ndarray | None = None  # (k, d) log(1−p) (bernoulli only)

    @property
    def num_classes(self) -> int:
        return self.pi.shape[0]

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """(n, k) joint log-likelihoods (Spark's rawPrediction)."""
        check_features(x, self.theta.shape[1], "NaiveBayesModel")
        x = x.astype(jnp.float32)
        pi = jnp.asarray(self.pi, jnp.float32)
        th = jnp.asarray(self.theta, jnp.float32)
        if self.model_type == "multinomial":
            return x @ th.T + pi[None, :]
        if self.model_type == "bernoulli":
            # Σ_f x log p + (1−x) log(1−p) = x·(log p − log(1−p)) + Σ log(1−p).
            # Inputs are binarized (x > 0 → 1) exactly like sklearn
            # BernoulliNB(binarize=0.0) — raw counts scored against the
            # fit-time 0/1 contract would be silent garbage (Spark raises
            # instead; delta documented).  Negatives and NaN map to 0.
            xb = (x > 0.0).astype(jnp.float32)
            th2 = jnp.asarray(self.theta2, jnp.float32)
            return xb @ (th - th2).T + (pi + jnp.sum(th2, axis=1))[None, :]
        if self.model_type == "complement":
            # Rennie's CNB: score by (negated) complement weights; priors
            # don't enter the multi-class argmax (sklearn ComplementNB)
            return x @ th.T
        var = jnp.asarray(self.sigma, jnp.float32)
        # Σ_d [ -0.5 log(2πσ²) - (x-μ)²/(2σ²) ], expanded so it's matmuls.
        # Everything is shifted by the across-class mean first: with raw
        # values like a year column (~2e3), the x² term (~4e6) would burn
        # the entire f32 mantissa and swamp the discriminative signal.
        ref = jnp.mean(th, axis=0)
        xc = x - ref[None, :]
        thc = th - ref[None, :]
        const = pi - 0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)
        inv = 1.0 / var
        quad = (
            (xc * xc) @ inv.T
            - 2.0 * xc @ (thc * inv).T
            + jnp.sum(thc * thc * inv, axis=1)[None, :]
        )
        return const[None, :] - 0.5 * quad

    def predict_proba(self, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(self.predict_raw(x), axis=1)

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_raw(x), axis=1).astype(jnp.float32)

    def _artifacts(self):
        arrays = {"pi": np.asarray(self.pi), "theta": np.asarray(self.theta)}
        if self.sigma is not None:
            arrays["sigma"] = np.asarray(self.sigma)
        if self.theta2 is not None:
            arrays["theta2"] = np.asarray(self.theta2)
        return ("NaiveBayesModel", {"model_type": self.model_type}, arrays)

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            model_type=params["model_type"],
            pi=arrays["pi"],
            theta=arrays["theta"],
            sigma=arrays.get("sigma"),
            theta2=arrays.get("theta2"),
        )


@dataclass(frozen=True)
class NaiveBayes(Estimator):
    model_type: str = "multinomial"   # Spark's default; also bernoulli |
    # complement | gaussian (the full Spark 3.x modelType surface)
    smoothing: float = 1.0            # Laplace λ (multinomial/bernoulli/complement)
    var_smoothing: float = 1e-9       # gaussian variance floor, sklearn-style
    label_col: str = "LOS_binary"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None) -> NaiveBayesModel:
        if self.model_type not in ("multinomial", "bernoulli", "complement", "gaussian"):
            raise ValueError(
                "model_type must be multinomial|bernoulli|complement|"
                f"gaussian, got {self.model_type!r}"
            )
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds: DeviceDataset = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        x = ds.x.astype(jnp.float32)
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        k = int(y_host[w_host > 0].max()) + 1 if np.any(w_host > 0) else 1

        if self.model_type in ("multinomial", "bernoulli", "complement"):
            counts, s1, bad = _count_sums(
                x, ds.y, ds.w, k, binary=self.model_type == "bernoulli"
            )
            if bool(jax.device_get(bad)):
                self._raise_bad_features()
            counts = np.asarray(counts, dtype=np.float64)
            s1 = np.asarray(s1, dtype=np.float64)
            return self._finalize_discrete(counts, s1, k)
        counts, s1c, s2c, gmean = (
            np.asarray(a, dtype=np.float64)
            for a in _gaussian_stats(x, ds.y, ds.w, k)
        )
        return self._finalize_gaussian(counts, s1c, s2c, gmean)

    def _raise_bad_features(self):
        if self.model_type == "bernoulli":
            raise ValueError(
                "bernoulli NaiveBayes requires 0/1 features; "
                "binarize first (features/binarizer.py)"
            )
        raise ValueError(
            f"{self.model_type} NaiveBayes requires non-negative, "
            "non-NaN features (counts); use model_type='gaussian' "
            "for real-valued data"
        )

    def _finalize_discrete(self, counts: np.ndarray, s1: np.ndarray, k: int):
        """(counts, Σx) → model, shared by the resident and out-of-core
        paths (the statistics are identical; only how they were
        accumulated differs)."""
        sm = self.smoothing
        pi = np.log(counts + sm) - np.log(counts.sum() + k * sm)
        if self.model_type == "multinomial":
            theta = np.log(
                (s1 + sm) / (s1.sum(axis=1, keepdims=True) + sm * s1.shape[1])
            )
            return NaiveBayesModel("multinomial", pi, theta)
        if self.model_type == "bernoulli":
            # P(f=1 | c) = (doc count with f, in c + λ) / (n_c + 2λ)
            p = (s1 + sm) / (counts[:, None] + 2.0 * sm)
            return NaiveBayesModel("bernoulli", pi, np.log(p), theta2=np.log1p(-p))
        # complement (Rennie's CNB, sklearn ComplementNB norm=False):
        # per class, feature mass from every OTHER class's rows
        comp = s1.sum(axis=0, keepdims=True) - s1 + sm          # (k, d)
        theta = -(np.log(comp) - np.log(comp.sum(axis=1, keepdims=True)))
        return NaiveBayesModel("complement", pi, theta)

    def _finalize_gaussian(self, counts, s1c, s2c, gmean):
        # gaussian priors are UNSMOOTHED — Spark's trainGaussianImpl uses
        # log(weightSum) − log(n) (λ applies only to the discrete models),
        # which is also sklearn GaussianNB's convention
        pi = np.log(np.maximum(counts, 1e-300) / max(counts.sum(), 1e-300))
        nk = np.maximum(counts[:, None], 1e-12)
        mean_c = s1c / nk
        var = s2c / nk - mean_c * mean_c
        if not np.isfinite(mean_c).all() or not np.isfinite(var).all():
            raise ValueError(
                "gaussian NaiveBayes saw NaN/Inf features; clean or impute "
                "first (features/imputer.py)"
            )
        # sklearn-style portion-of-largest-variance floor
        floor = self.var_smoothing * max(float(var.max()), 1e-12)
        var = np.maximum(var, floor)
        return NaiveBayesModel("gaussian", pi, mean_c + gmean[None, :], var)

    def _fit_outofcore(self, hd, mesh=None) -> NaiveBayesModel:
        """Rows ≫ HBM (VERDICT r4 #5, the easiest case): NaiveBayes IS one
        pass of psum'd sufficient statistics, so the out-of-core fit just
        accumulates the SAME per-class (count, Σx[, Σx²]) block by block —
        Spark's treeAggregate over disk-backed partitions, one
        ``max_device_rows`` block at a time through the mesh.  Gaussian
        needs the globally-centered two-pass variant: pass 1 computes the
        global weighted mean, pass 2 the centered per-class stats (the
        resident path fuses both in one jit; the math is identical)."""
        from ..parallel.mesh import default_mesh
        from ..parallel.outofcore import add_stats

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError("NaiveBayes needs labels: HostDataset(y=...)")
        if hd.n == 0:
            raise ValueError("NaiveBayes fit on an empty dataset")
        y_host = np.asarray(hd.y)
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        if not np.any(w_host > 0):
            raise ValueError("NaiveBayes fit with no positively-weighted rows")
        k = int(y_host[w_host > 0].max()) + 1

        if self.model_type in ("multinomial", "bernoulli", "complement"):
            # bad flag accumulates ON DEVICE (bool→f32 sum > 0) so the
            # streamed loop never blocks on a per-block host round-trip
            tot = None
            for blk in hd.blocks(mesh):
                counts, s1, bad = _count_sums(
                    blk.x.astype(jnp.float32), blk.y, blk.w, k,
                    binary=self.model_type == "bernoulli",
                )
                s = (counts, s1, bad.astype(jnp.float32))
                tot = s if tot is None else add_stats(tot, s)
            if float(jax.device_get(tot[2])) > 0:
                self._raise_bad_features()
            counts, s1 = (np.asarray(a, dtype=np.float64) for a in tot[:2])
            return self._finalize_discrete(counts, s1, k)

        # gaussian: pass 1 — global weighted mean
        from ..parallel.outofcore import block_moments

        mtot = None
        for blk in hd.blocks(mesh):
            s = block_moments(blk.x, blk.y, blk.w)
            mtot = s if mtot is None else add_stats(mtot, s)
        sw, sx = mtot[0], mtot[1]
        gmean = jnp.asarray(sx) / jnp.maximum(jnp.asarray(sw), 1.0)
        # pass 2 — per-class centered stats at the FIXED global mean
        tot = None
        for blk in hd.blocks(mesh):
            s = _gaussian_stats_centered(
                blk.x.astype(jnp.float32), blk.y, blk.w, k, gmean
            )
            tot = s if tot is None else add_stats(tot, s)
        counts, s1c, s2c = (np.asarray(a, dtype=np.float64) for a in tot)
        return self._finalize_gaussian(
            counts, s1c, s2c, np.asarray(gmean, dtype=np.float64)
        )
