"""NaiveBayes — multinomial & gaussian, one sufficient-stats pass.

Parity with ``pyspark.ml.classification.NaiveBayes`` (model_type
"multinomial", Spark's default, with Laplace ``smoothing``; plus
"gaussian", Spark 3.0+).  MLlib aggregates per-class feature sums with one
``treeAggregate``; here the same statistics are one jit'd one-hot
contraction over the row-sharded dataset — a (k, d) matmul on the MXU
whose cross-shard sum lowers to a psum — so the whole fit is a single
device pass regardless of n.

Prediction is a dense (n, k) log-likelihood matmul + argmax, the same
shape as the KMeans assignment step.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from ..parallel.sharding import DeviceDataset
from .base import Estimator, Model, as_device_dataset, check_features


@partial(jax.jit, static_argnames=("k",))
def _count_sums(x: jax.Array, y: jax.Array, w: jax.Array, k: int):
    """Per-class weighted (count, Σx) + a has-negative flag — the
    multinomial stats, one one-hot contraction (no Σx² pass)."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=x.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)                 # (k,)
    s1 = onehot.T @ x                                # (k, d)
    # ~(x >= 0) catches BOTH negatives and NaN in one reduction — a NaN
    # would otherwise pass a `< 0` check and silently poison theta
    bad = jnp.any(~(jnp.where(w[:, None] > 0, x, 0.0) >= 0))
    return counts, s1, bad


@partial(jax.jit, static_argnames=("k",))
def _gaussian_stats(x: jax.Array, y: jax.Array, w: jax.Array, k: int):
    """Per-class weighted (count, Σxc, Σxc²) of GLOBALLY CENTERED features.

    Centering kills the E[x²] − mean² catastrophic cancellation for
    features whose mean dwarfs their within-class std (e.g. a year
    column): after the shift, class means are O(within-class spread), so
    the f32 sums lose nothing that matters.  One extra cheap global-mean
    reduction buys f64-two-pass-quality variances."""
    n = jnp.maximum(jnp.sum(w), 1.0)
    # mask invalid rows BEFORE any product with x: a NaN in a w=0 row
    # would otherwise poison gmean/s2c (w=0 rows are contractually inert)
    xm = jnp.where(w[:, None] > 0, x, 0.0)
    gmean = jnp.sum(xm * w[:, None], axis=0) / n
    xc = xm - gmean[None, :]
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=x.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)
    s1c = onehot.T @ xc
    s2c = onehot.T @ (xc * xc)
    return counts, s1c, s2c, gmean


@register_model("NaiveBayesModel")
@dataclass
class NaiveBayesModel(Model):
    model_type: str                 # "multinomial" | "gaussian"
    pi: np.ndarray                  # (k,) log class priors
    theta: np.ndarray               # (k, d): log P(feat|class) | means
    sigma: np.ndarray | None = None  # (k, d) variances (gaussian only)

    @property
    def num_classes(self) -> int:
        return self.pi.shape[0]

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """(n, k) joint log-likelihoods (Spark's rawPrediction)."""
        check_features(x, self.theta.shape[1], "NaiveBayesModel")
        x = x.astype(jnp.float32)
        pi = jnp.asarray(self.pi, jnp.float32)
        th = jnp.asarray(self.theta, jnp.float32)
        if self.model_type == "multinomial":
            return x @ th.T + pi[None, :]
        var = jnp.asarray(self.sigma, jnp.float32)
        # Σ_d [ -0.5 log(2πσ²) - (x-μ)²/(2σ²) ], expanded so it's matmuls.
        # Everything is shifted by the across-class mean first: with raw
        # values like a year column (~2e3), the x² term (~4e6) would burn
        # the entire f32 mantissa and swamp the discriminative signal.
        ref = jnp.mean(th, axis=0)
        xc = x - ref[None, :]
        thc = th - ref[None, :]
        const = pi - 0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)
        inv = 1.0 / var
        quad = (
            (xc * xc) @ inv.T
            - 2.0 * xc @ (thc * inv).T
            + jnp.sum(thc * thc * inv, axis=1)[None, :]
        )
        return const[None, :] - 0.5 * quad

    def predict_proba(self, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(self.predict_raw(x), axis=1)

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_raw(x), axis=1).astype(jnp.float32)

    def _artifacts(self):
        arrays = {"pi": np.asarray(self.pi), "theta": np.asarray(self.theta)}
        if self.sigma is not None:
            arrays["sigma"] = np.asarray(self.sigma)
        return ("NaiveBayesModel", {"model_type": self.model_type}, arrays)

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            model_type=params["model_type"],
            pi=arrays["pi"],
            theta=arrays["theta"],
            sigma=arrays.get("sigma"),
        )


@dataclass(frozen=True)
class NaiveBayes(Estimator):
    model_type: str = "multinomial"   # Spark's default
    smoothing: float = 1.0            # Laplace (multinomial)
    var_smoothing: float = 1e-9       # gaussian variance floor, sklearn-style
    label_col: str = "LOS_binary"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None) -> NaiveBayesModel:
        if self.model_type not in ("multinomial", "gaussian"):
            raise ValueError(
                f"model_type must be multinomial|gaussian, got {self.model_type!r}"
            )
        ds: DeviceDataset = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        x = ds.x.astype(jnp.float32)
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        k = int(y_host[w_host > 0].max()) + 1 if np.any(w_host > 0) else 1
        if self.model_type == "multinomial":
            counts, s1, bad = _count_sums(x, ds.y, ds.w, k)
            if bool(jax.device_get(bad)):
                raise ValueError(
                    "multinomial NaiveBayes requires non-negative, non-NaN "
                    "features (counts); use model_type='gaussian' for "
                    "real-valued data"
                )
            counts = np.asarray(counts, dtype=np.float64)
            s1 = np.asarray(s1, dtype=np.float64)
            pi = np.log(
                np.maximum(counts, 1e-300) / max(counts.sum(), 1e-300)
            )
            sm = self.smoothing
            theta = np.log(
                (s1 + sm) / (s1.sum(axis=1, keepdims=True) + sm * s1.shape[1])
            )
            return NaiveBayesModel("multinomial", pi, theta)
        counts, s1c, s2c, gmean = (
            np.asarray(a, dtype=np.float64)
            for a in _gaussian_stats(x, ds.y, ds.w, k)
        )
        pi = np.log(np.maximum(counts, 1e-300) / max(counts.sum(), 1e-300))
        nk = np.maximum(counts[:, None], 1e-12)
        mean_c = s1c / nk
        var = s2c / nk - mean_c * mean_c
        if not np.isfinite(mean_c).all() or not np.isfinite(var).all():
            raise ValueError(
                "gaussian NaiveBayes saw NaN/Inf features; clean or impute "
                "first (features/imputer.py)"
            )
        # sklearn-style portion-of-largest-variance floor
        floor = self.var_smoothing * max(float(var.max()), 1e-12)
        var = np.maximum(var, floor)
        return NaiveBayesModel("gaussian", pi, mean_c + gmean[None, :], var)
