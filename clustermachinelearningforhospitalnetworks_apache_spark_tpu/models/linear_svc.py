"""LinearSVC — sharded squared-hinge Newton classifier.

Parity with ``pyspark.ml.classification.LinearSVC`` (binary ±1 margin,
L2 ``reg_param``, standardized regularization with the intercept
unpenalized, ``rawPrediction`` = signed margin).  One deliberate,
documented delta: Spark optimizes the L1 hinge with OWL-QN; here the
objective is the SQUARED hinge (sklearn ``LinearSVC``'s default), whose
generalized Hessian makes each iteration a Newton step — one jit'd pass
over the row-sharded data building the gradient and Hessian restricted to
the active set (margin < 1), two MXU matmuls whose cross-shard reduction
lowers to ``psum``, then a tiny on-device solve.  Decision boundaries
agree with the hinge solution to within the margin band; sklearn parity
is tested.

Objective (ỹ ∈ {−1, +1}, standardized-coefficient penalty β̃):

    λ/2 ‖β̃‖² + (1/Σw) Σᵢ wᵢ max(0, 1 − ỹᵢ(xᵢβ + b))²
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features
from .linear_regression import standardized_design


@partial(jax.jit, static_argnames=("fit_intercept", "standardize", "max_iter"))
def _svc_fit(x, y01, w, reg_param, tol, fit_intercept: bool, standardize: bool, max_iter: int):
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    ysign = 2.0 * y01.astype(jnp.float32) - 1.0            # {0,1} → {−1,+1}
    xa, ridge, nfeat, n = standardized_design(
        x, w, reg_param, fit_intercept, standardize
    )
    d = xa.shape[1]
    # per-sample scaling: objective divides the loss by Σw, so fold 1/n
    # into the data term and keep ridge per Spark's λ‖β̃‖² convention
    wn = w / n

    def newton_step(theta):
        margin = ysign * (xa @ theta)
        act = (margin < 1.0).astype(jnp.float32) * wn       # active set
        resid = 1.0 - margin                                # >0 on active set
        # penalty λ/2·‖β̃‖² ⇒ gradient λβ̃ (ridge already carries λ·n·scale²)
        grad = -2.0 * xa.T @ (act * ysign * resid) + ridge / n * theta
        hess = 2.0 * (xa * act[:, None]).T @ xa + jnp.diag(ridge / n)
        jitter = 1e-6 * jnp.trace(hess) / d + 1e-8
        delta = jnp.linalg.solve(hess + jitter * jnp.eye(d, dtype=x.dtype), grad)
        return theta - delta, jnp.max(jnp.abs(delta))

    def cond(carry):
        it, _, delta = carry
        return (it < max_iter) & (delta > tol)

    def body(carry):
        it, theta, _ = carry
        theta_new, delta = newton_step(theta)
        return it + 1, theta_new, delta

    theta0 = jnp.zeros((d,), x.dtype)
    it, theta, _ = lax.while_loop(
        cond, body, (jnp.int32(0), theta0, jnp.float32(jnp.inf))
    )
    coef = theta[:nfeat]
    intercept = theta[nfeat] if fit_intercept else jnp.zeros((), x.dtype)
    return coef, intercept, it


@partial(jax.jit, static_argnames=("fit_intercept",))
def _svc_block_stats(x, y01, w, theta, fit_intercept: bool):
    """One streamed block's UNNORMALIZED (Σ gradient, Σ Hessian) squared-
    hinge contributions at ``theta`` — the resident ``newton_step``'s
    active-set sums, accumulated across blocks by the out-of-core
    driver."""
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    ysign = 2.0 * y01.astype(jnp.float32) - 1.0
    xa = (
        jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
        if fit_intercept
        else x
    )
    margin = ysign * (xa @ theta)
    act = (margin < 1.0).astype(jnp.float32) * w
    resid = 1.0 - margin
    grad = -2.0 * xa.T @ (act * ysign * resid)
    hess = 2.0 * (xa * act[:, None]).T @ xa
    return grad, hess


@jax.jit
def _svc_update_from_stats(theta, grad_sum, hess_sum, ridge, n):
    """The resident Newton solve on ACCUMULATED statistics (identical
    1/n scaling, ridge handling, and jitter)."""
    d = theta.shape[0]
    grad = grad_sum / n + ridge / n * theta
    hess = hess_sum / n + jnp.diag(ridge / n)
    jitter = 1e-6 * jnp.trace(hess) / d + 1e-8
    delta = jnp.linalg.solve(hess + jitter * jnp.eye(d, dtype=hess.dtype), grad)
    return theta - delta, jnp.max(jnp.abs(delta))


@register_model("LinearSVCModel")
@dataclass
class LinearSVCModel(Model):
    coefficients: np.ndarray
    intercept: float
    n_iter: int = 0

    def predict_raw(self, x: jax.Array) -> jax.Array:
        """Signed margin (Spark's rawPrediction for the positive class)."""
        check_features(x, np.asarray(self.coefficients).shape[0], "LinearSVCModel")
        return x.astype(jnp.float32) @ jnp.asarray(
            self.coefficients, jnp.float32
        ) + jnp.float32(self.intercept)

    def predict(self, x: jax.Array) -> jax.Array:
        return (self.predict_raw(x) > 0).astype(jnp.float32)

    def _artifacts(self):
        return (
            "LinearSVCModel",
            {"intercept": float(self.intercept), "n_iter": int(self.n_iter)},
            {"coefficients": np.asarray(self.coefficients)},
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            coefficients=arrays["coefficients"],
            intercept=float(params["intercept"]),
            n_iter=int(params.get("n_iter", 0)),
        )


@dataclass(frozen=True)
class LinearSVC(Estimator):
    reg_param: float = 0.0          # Spark default
    max_iter: int = 100             # Spark default
    tol: float = 1e-6               # Spark default
    fit_intercept: bool = True
    standardize: bool = True
    label_col: str = "LOS_binary"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None) -> LinearSVCModel:
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        y_host = np.asarray(jax.device_get(ds.y))
        w_host = np.asarray(jax.device_get(ds.w))
        uniq = np.unique(y_host[w_host > 0])
        if uniq.size == 0:
            raise ValueError("LinearSVC fit on an empty dataset")
        if not np.all(np.isin(uniq, (0.0, 1.0))):
            raise ValueError(
                f"LinearSVC is binary (labels 0/1); got labels {uniq[:5]}"
            )
        coef, intercept, it = _svc_fit(
            ds.x, ds.y, ds.w,
            jnp.float32(self.reg_param), jnp.float32(self.tol),
            self.fit_intercept, self.standardize, self.max_iter,
        )
        return LinearSVCModel(
            coefficients=np.asarray(jax.device_get(coef)),
            intercept=float(intercept),
            n_iter=int(it),
        )

    def _fit_outofcore(self, hd, mesh=None) -> LinearSVCModel:
        """Rows ≫ HBM squared-hinge Newton (VERDICT r4 weak #4): every
        Newton iteration streams ``max_device_rows`` host blocks through
        the mesh accumulating the SAME active-set (gradient, Hessian)
        sums the resident jit computes in one shot, then runs the
        identical damped solve — the logistic/GLM out-of-core pattern on
        the hinge objective."""
        from ..parallel.mesh import default_mesh
        from ..parallel.outofcore import (
            add_stats,
            standardized_ridge,
            streamed_standardization,
        )

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError("LinearSVC needs labels: HostDataset(y=...)")
        y_host = np.asarray(hd.y)
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        uniq = np.unique(y_host[w_host > 0])
        if uniq.size == 0:
            raise ValueError("LinearSVC fit on an empty dataset")
        if not np.all(np.isin(uniq, (0.0, 1.0))):
            raise ValueError(
                f"LinearSVC is binary (labels 0/1); got labels {uniq[:5]}"
            )

        nfeat = hd.n_features
        dd = nfeat + (1 if self.fit_intercept else 0)
        if self.reg_param > 0:
            # pass 0: moments → standardized ridge (shared pre-pass,
            # parallel/outofcore.py — carries weighted_moments' constant-
            # feature std=1.0 rule)
            n, _, std, _ = streamed_standardization(hd, mesh)
            ridge = jnp.asarray(
                standardized_ridge(
                    n, std, self.reg_param, nfeat, self.fit_intercept,
                    self.standardize,
                )
            )
        else:
            # ridge is identically zero: n comes from the host weights —
            # no reason to stream a rows≫HBM dataset once just for Σw
            n = max(float(np.sum(w_host)), 1.0)
            ridge = jnp.zeros((dd,), jnp.float32)
        n_dev = jnp.float32(n)

        theta = jnp.zeros((dd,), jnp.float32)
        it = 0
        for it in range(1, self.max_iter + 1):
            tot = None
            for blk in hd.blocks(mesh):
                s = _svc_block_stats(
                    blk.x, blk.y, blk.w, theta, self.fit_intercept
                )
                tot = s if tot is None else add_stats(tot, s)
            theta, dmax = _svc_update_from_stats(theta, *tot, ridge, n_dev)
            if float(dmax) <= self.tol:
                break
        th = np.asarray(jax.device_get(theta))
        return LinearSVCModel(
            coefficients=th[:nfeat],
            intercept=float(th[nfeat]) if self.fit_intercept else 0.0,
            n_iter=it,
        )
