"""MultilayerPerceptronClassifier — feedforward net on the mesh.

Parity with ``pyspark.ml.classification.MultilayerPerceptronClassifier``:
``layers=[d, h₁, …, C]`` with SIGMOID hidden activations and a softmax
output trained on cross-entropy (Spark's exact topology — not ReLU), an
L-BFGS solver (Spark's default), seed-deterministic init.

This is the one estimator family where the framework's substrate IS the
reference implementation's native habitat: the forward/backward pass is
pure ``jnp`` (two matmuls per layer on the MXU), gradients come from
``jax.grad`` instead of MLlib's hand-rolled layer backprop, and the
whole L-BFGS optimization runs as one jitted ``optax.lbfgs`` scan on
device — the row-sharded data pass is the usual psum-under-the-hood
GSPMD matmul.  Sample weights follow the standard ``w``-weighted-loss
rule (pad rows carry w=0 and contribute nothing).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..io.model_io import register_model
from .base import Estimator, Model, as_device_dataset, check_features


def _init_params(layers: tuple[int, ...], seed: int):
    """Glorot-uniform weights + zero biases, seed-deterministic."""
    rng = np.random.default_rng(seed)
    params = []
    for fan_in, fan_out in zip(layers[:-1], layers[1:]):
        lim = np.sqrt(6.0 / (fan_in + fan_out))
        params.append(
            (
                rng.uniform(-lim, lim, size=(fan_in, fan_out)).astype(np.float32),
                np.zeros((fan_out,), np.float32),
            )
        )
    return [(jnp.asarray(w), jnp.asarray(b)) for w, b in params]


def _forward(params, x):
    """Sigmoid hidden layers, raw logits out (Spark's topology)."""
    h = x
    for w, b in params[:-1]:
        h = jax.nn.sigmoid(h @ w + b[None, :])
    w, b = params[-1]
    return h @ w + b[None, :]


@lru_cache(maxsize=1)
def _make_block_step():
    """The jitted out-of-core Adam step, built once per process — an
    inline per-fit ``@jax.jit`` closure recompiled every fit (ISSUE 13
    ``jit-in-function``; the PR 5 retrace-per-fit class).  Layer shapes
    are not baked in: jit re-specializes per params signature and keeps
    each specialization cached across fits."""
    import optax

    opt = optax.adam(1e-2)

    @jax.jit
    def block_step(params, state, x, y, w):
        yi = y.astype(jnp.int32)
        wsum = jnp.maximum(jnp.sum(w), 1.0)

        def loss_fn(p):
            logits = _forward(p, x)
            ll = jax.nn.log_softmax(logits, axis=1)
            nll = -jnp.take_along_axis(ll, yi[:, None], axis=1)[:, 0]
            return jnp.sum(nll * w) / wsum

        l, grads = jax.value_and_grad(loss_fn)(params)
        updates, state_new = opt.update(grads, state)
        return optax.apply_updates(params, updates), state_new, l

    return block_step


@partial(jax.jit, static_argnames=("max_iter",))
def _fit_lbfgs(params, x, y, w, max_iter: int, tol):
    """Full-batch L-BFGS — Spark's solver, via the shared harness
    (models/_opt.py) with the |Δloss| ≤ tol plateau stop."""
    from ._opt import lbfgs_minimize

    yi = y.astype(jnp.int32)
    wsum = jnp.maximum(jnp.sum(w), 1.0)

    def loss_fn(p):
        logits = _forward(p, x)
        ll = jax.nn.log_softmax(logits, axis=1)
        nll = -jnp.take_along_axis(ll, yi[:, None], axis=1)[:, 0]
        return jnp.sum(nll * w) / wsum

    return lbfgs_minimize(loss_fn, params, max_iter, tol)


@register_model("MultilayerPerceptronModel")
@dataclass
class MultilayerPerceptronModel(Model):
    weights: list                  # [(W, b), ...]
    layers: tuple[int, ...] = ()

    @property
    def num_classes(self) -> int:
        return int(self.layers[-1])

    def predict_raw(self, x: jax.Array) -> jax.Array:
        check_features(x, int(self.layers[0]), "MultilayerPerceptronModel")
        return _forward(self.weights, jnp.asarray(x, jnp.float32))

    def predict_proba(self, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(self.predict_raw(x), axis=1)

    def predict(self, x: jax.Array) -> jax.Array:
        return jnp.argmax(self.predict_raw(x), axis=1).astype(jnp.float32)

    def _artifacts(self):
        arrays = {}
        for i, (w, b) in enumerate(self.weights):
            arrays[f"w{i}"] = np.asarray(w)
            arrays[f"b{i}"] = np.asarray(b)
        return (
            "MultilayerPerceptronModel",
            {"layers": [int(v) for v in self.layers]},
            arrays,
        )

    @classmethod
    def from_artifacts(cls, params, arrays):
        layers = tuple(int(v) for v in params["layers"])
        weights = [
            (jnp.asarray(arrays[f"w{i}"]), jnp.asarray(arrays[f"b{i}"]))
            for i in range(len(layers) - 1)
        ]
        return cls(weights=weights, layers=layers)


@dataclass(frozen=True)
class MultilayerPerceptronClassifier(Estimator):
    """Spark defaults: maxIter 100, tol 1e-6, solver "l-bfgs", seed
    required (here defaulted).  ``layers`` must name the full topology
    [input, hidden..., output]; the output width is the class count."""

    layers: tuple[int, ...] = ()
    max_iter: int = 100
    tol: float = 1e-6
    seed: int = 0
    solver: str = "l-bfgs"
    label_col: str = "LOS_binary"
    features_col: str = "features"
    weight_col: str | None = None

    def fit(self, data, label_col: str | None = None, mesh=None):
        if self.solver != "l-bfgs":
            raise ValueError(
                f"solver must be 'l-bfgs' (Spark's default and the only "
                f"one implemented); got {self.solver!r}"
            )
        if len(self.layers) < 2:
            raise ValueError(
                "layers must name [input, hidden..., output] widths; got "
                f"{self.layers}"
            )
        from ..parallel.outofcore import HostDataset

        if isinstance(data, HostDataset):
            return self._fit_outofcore(data, mesh)
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        if ds.y is None:
            raise ValueError("MultilayerPerceptronClassifier needs labels")
        d_in, n_out = int(self.layers[0]), int(self.layers[-1])
        if ds.n_features != d_in:
            raise ValueError(
                f"layers[0]={d_in} but the data has {ds.n_features} features"
            )
        yv = np.asarray(jax.device_get(ds.y))
        wv = np.asarray(jax.device_get(ds.w))
        valid = yv[wv > 0]
        if valid.size and (
            (valid < 0).any()
            or (valid >= n_out).any()
            or not np.allclose(valid, np.round(valid))
        ):
            bad = valid[
                (valid < 0) | (valid >= n_out) | ~np.isclose(valid, np.round(valid))
            ]
            raise ValueError(
                f"labels must be integers in [0, layers[-1]={n_out}); got "
                f"{np.unique(bad)[:5]}"
            )
        params = _init_params(tuple(int(v) for v in self.layers), self.seed)
        params, _, _ = _fit_lbfgs(
            params, ds.x.astype(jnp.float32), ds.y, ds.w.astype(jnp.float32),
            self.max_iter, jnp.float32(self.tol),
        )
        return MultilayerPerceptronModel(
            weights=[(w, b) for w, b in params],
            layers=tuple(int(v) for v in self.layers),
        )

    def _fit_outofcore(self, hd, mesh=None):
        """Rows ≫ HBM (VERDICT r4 #5): streaming MINIBATCH Adam — each
        epoch scans the ``max_device_rows`` host blocks through the mesh,
        one Adam step per block on the block's weighted-mean cross-
        entropy.  The resident path keeps Spark's full-batch L-BFGS; this
        path trades solver parity for bounded device memory (Spark's own
        pre-3.0 MLP used minibatch GD), converging to the same optimum
        statistically rather than step-for-step.  ``max_iter`` counts
        epochs.  Plateau stop: mean epoch loss improving ≤ tol ends
        training early, mirroring the resident |Δloss| rule."""
        import optax

        from ..parallel.mesh import default_mesh

        mesh = mesh or default_mesh()
        if hd.y is None:
            raise ValueError(
                "MultilayerPerceptronClassifier needs labels: HostDataset(y=...)"
            )
        if hd.n == 0 or hd.count() == 0.0:
            raise ValueError(
                "MultilayerPerceptronClassifier fit on an empty dataset"
            )
        d_in, n_out = int(self.layers[0]), int(self.layers[-1])
        if hd.n_features != d_in:
            raise ValueError(
                f"layers[0]={d_in} but the data has {hd.n_features} features"
            )
        y_host = np.asarray(hd.y)
        w_host = (
            np.asarray(hd.w) if hd.w is not None else np.ones(hd.n, np.float32)
        )
        valid = y_host[w_host > 0]
        if valid.size and (
            (valid < 0).any()
            or (valid >= n_out).any()
            or not np.allclose(valid, np.round(valid))
        ):
            bad = valid[
                (valid < 0) | (valid >= n_out) | ~np.isclose(valid, np.round(valid))
            ]
            raise ValueError(
                f"labels must be integers in [0, layers[-1]={n_out}); got "
                f"{np.unique(bad)[:5]}"
            )

        params = _init_params(tuple(int(v) for v in self.layers), self.seed)
        # minibatch Adam at the L-BFGS-comparable default rate
        opt = optax.adam(1e-2)
        state = opt.init(params)
        block_step = _make_block_step()

        prev = np.inf
        n_blocks, _ = hd.block_shape(mesh)
        shuffle = np.random.default_rng(self.seed + 1)
        for _ in range(self.max_iter):
            losses = []
            # fresh block order per epoch — see HostDataset.blocks(order=)
            for blk in hd.blocks(mesh, order=shuffle.permutation(n_blocks)):
                params, state, l = block_step(
                    params, state,
                    blk.x.astype(jnp.float32), blk.y, blk.w.astype(jnp.float32),
                )
                losses.append(float(l))
            cur = float(np.mean(losses)) if losses else 0.0
            if abs(prev - cur) <= self.tol:
                break
            prev = cur
        return MultilayerPerceptronModel(
            weights=[(w, b) for w, b in params],
            layers=tuple(int(v) for v in self.layers),
        )
