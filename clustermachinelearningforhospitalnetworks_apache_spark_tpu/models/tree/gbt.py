"""Gradient-boosted trees — GBTRegressor / GBTClassifier.

Parity with ``pyspark.ml.regression.GBTRegressor`` (squared-error loss)
and ``pyspark.ml.classification.GBTClassifier`` (logistic loss), the
largest MLlib estimator family beyond what the reference script itself
exercises (its DT/RF call sites, ``mllearnforhospitalnetwork.py:150-158``,
share this engine).

TPU shape: boosting is inherently sequential in ROUNDS, but each round is
the level-order histogram tree of ``engine.py`` — all device work.  The
per-round pipeline keeps everything on the mesh:

    residuals (device)  →  grow one tree on (x, residual)
                        →  predict_forest on the training shard
                        →  F ← F + lr·tree(x);  new residuals (one jit)

The quantile bin thresholds AND the digitized (d, n) bin matrix depend
only on ``x``, so both are computed ONCE and reused for every round
(``bin_thresholds=``/``binned_t=`` fast path into ``grow_forest``), and
the prediction column ``F`` never leaves the device between rounds.

**Round fusion (default):** the whole M-round chain above is ONE jitted
``lax.scan`` — each scan step computes the pseudo-residual, grows the
round's tree through the engine's fused multi-level path
(``engine._make_forest_grower``), materializes its device heap arrays
(``device_tree_arrays``) and advances ``F`` by ``lr·tree(x)``, all
inside the same dispatch.  A full fit issues O(1) host syncs (the
binning sample, F₀, and ONE ``device_get`` of every round's stacked
winner tensors at the end) instead of O(M·depth) per-level round trips;
``fused_rounds=False`` restores the per-round deferred loop (identical
trees — tests/test_gbt_fused.py pins the parity), and stacking
``fused_levels=False`` on top restores the per-level dispatch loop too
— the full pre-fusion baseline the gbt20 bench A/B times.  The
validation-early-stop path keeps one host sync per round by design
(Spark's runWithValidation decides on the host), but still grows each
tree in a single fused dispatch.

Losses (Spark's set): regression "squared" — pseudo-residual y − F;
classification "logistic" on labels y∈{0,1} — F is half the log-odds
(Spark's ±1 formulation), pseudo-residual y − σ(2F).
"""

from __future__ import annotations

from contextlib import nullcontext
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ...io.model_io import register_model
from ...parallel.mesh import default_mesh
from ..base import Estimator, Model, as_device_dataset, check_features
from .engine import (
    DeferredForest,
    GrownForest,
    _bootstrap_draw,
    _make_forest_grower,
    bin_feature_matrix,
    device_tree_arrays,
    grow_forest,
    predict_forest,
)


def _stage(clock, name: str):
    """StageClock stage when a clock is attached, else a no-op context."""
    return clock.stage(name) if clock is not None else nullcontext()


@lru_cache(maxsize=16)
def _make_boost_scan(
    mesh: Mesh, d: int, B: int, max_depth: int, max_iter: int, loss: str,
    boot: bool, rate: float, use_pallas: bool,
    cat_arities: tuple[int, ...] | None,
):
    """ONE jitted executable for the whole fused boost: a ``lax.scan``
    over all ``max_iter`` rounds whose step refreshes the pseudo-
    residual, grows the round's tree through the engine's fused
    multi-level grower, materializes its device heap arrays and advances
    the margin — the tentpole dispatch of the device-resident fit.

    lru-cached on the static config (the same discipline as every
    engine factory) so repeated fits — bench timed reps, CV folds,
    refits on fresh data of the same shape — reuse the compiled scan
    instead of retracing a per-fit closure.  The bootstrap draw and
    residual math are the SHARED definitions the per-round loop uses
    (``engine._bootstrap_draw``; Spark's LogLoss pseudo-residual), so
    fused and legacy fits stay bit-identical by construction.

    → ``run(x, y, w, binned_t, f0_arr, thr_dev, is_cat_dev, seed0, lr,
    min_inst, min_gain)`` returning ``(final_margin, stacked_levels)``
    where ``stacked_levels`` is the per-level tuple of winner tensors
    with a leading round axis (``DeferredForest.level_out`` per round,
    scan-stacked)."""
    grower = _make_forest_grower(
        mesh, d, B, 3, 1, "regression", max_depth, cat_arities,
        use_pallas, None,
    )
    any_cat = cat_arities is not None and any(a > 0 for a in cat_arities)
    cat_flags_np = (
        np.asarray([a > 0 for a in cat_arities], bool) if any_cat else None
    )

    def run(
        x, y, w, binned_t, f0_arr, thr_dev, is_cat_dev, seed0, lr,
        min_inst, min_gain,
    ):
        cat_flags = (
            jnp.asarray(cat_flags_np) if cat_flags_np is not None else None
        )
        n_pad = w.shape[0]

        def round_body(f, t):
            if loss == "squared":
                r = y - f
            else:  # Spark LogLoss pseudo-residual (see _boost.residual)
                r = 4.0 * (y - jax.nn.sigmoid(2.0 * f))
            base_t = jnp.stack([jnp.ones_like(r), r, r * r], axis=0)
            if boot:
                w_tree = _bootstrap_draw(seed0 + t, rate, 1, n_pad) * w[None, :]
            else:
                w_tree = jnp.broadcast_to(w[None, :], (1, n_pad))
            level_out = grower(
                binned_t, base_t, w_tree, 0, min_inst, min_gain
            )
            sf, th, val, cm = device_tree_arrays(
                level_out, thr_dev, is_cat_dev, B
            )
            pred = predict_forest(x, sf, th, val, cm, cat_flags)[0, :, 0]
            return f + lr * pred, tuple(tuple(lv) for lv in level_out)

        return lax.scan(round_body, f0_arr, jnp.arange(max_iter))

    return jax.jit(run)


@register_model("GBTModel")
@dataclass
class GBTModel(Model):
    """Stacked boosted trees: prediction = init + lr · Σ_t tree_t(x)."""

    task: str                    # "regression" | "classification"
    split_feat: np.ndarray       # (T, total)
    threshold: np.ndarray        # (T, total)
    value: np.ndarray            # (T, total, 1)
    init: float                  # F₀ (mean | half base log-odds)
    learning_rate: float
    feature_importances: np.ndarray
    max_depth: int
    # categorical (unordered-set) splits — None for all-continuous fits
    split_catmask: np.ndarray | None = None
    cat_arities: np.ndarray | None = None

    @property
    def num_trees(self) -> int:
        return self.split_feat.shape[0]

    def _raw(self, x: jax.Array) -> jax.Array:
        check_features(x, self.feature_importances.shape[-1], "GBTModel")
        cat_mask = cat_flags = None
        if self.split_catmask is not None:
            cat_mask = jnp.asarray(self.split_catmask, jnp.uint32)
            cat_flags = jnp.asarray(np.asarray(self.cat_arities) > 0)
        out = predict_forest(
            x.astype(jnp.float32),
            jnp.asarray(self.split_feat),
            jnp.asarray(self.threshold),
            jnp.asarray(self.value),
            cat_mask,
            cat_flags,
        )[:, :, 0]                                  # (T, n)
        return self.init + self.learning_rate * jnp.sum(out, axis=0)

    def predict_raw(self, x: jax.Array) -> jax.Array:
        return self._raw(x)

    def predict_proba(self, x: jax.Array) -> jax.Array:
        if self.task != "classification":
            raise ValueError("predict_proba is classification-only")
        return jax.nn.sigmoid(2.0 * self._raw(x))   # Spark's ±1 margin

    def predict(self, x: jax.Array) -> jax.Array:
        raw = self._raw(x)
        if self.task == "regression":
            return raw
        return (raw > 0).astype(jnp.float32)

    def _artifacts(self):
        return (
            "GBTModel",
            {
                "task": self.task,
                "init": float(self.init),
                "learning_rate": float(self.learning_rate),
                "max_depth": int(self.max_depth),
            },
            self._tree_arrays(),
        )

    def _tree_arrays(self) -> dict:
        arrays = {
            "split_feat": self.split_feat,
            "threshold": self.threshold,
            "value": self.value,
            "feature_importances": self.feature_importances,
        }
        if self.split_catmask is not None:
            arrays["split_catmask"] = self.split_catmask
            arrays["cat_arities"] = np.asarray(self.cat_arities)
        return arrays

    @classmethod
    def from_artifacts(cls, params, arrays):
        return cls(
            task=params["task"],
            split_feat=arrays["split_feat"],
            threshold=arrays["threshold"],
            value=arrays["value"],
            init=float(params["init"]),
            learning_rate=float(params["learning_rate"]),
            feature_importances=arrays["feature_importances"],
            max_depth=int(params["max_depth"]),
            split_catmask=arrays.get("split_catmask"),
            cat_arities=arrays.get("cat_arities"),
        )


@dataclass(frozen=True)
class _GBTParams:
    max_iter: int = 20            # Spark's maxIter (number of trees)
    max_depth: int = 5
    max_bins: int = 32
    step_size: float = 0.1        # Spark's stepSize (learning rate)
    min_instances_per_node: int = 1
    min_info_gain: float = 0.0
    subsampling_rate: float = 1.0
    seed: int = 0
    label_col: str = "length_of_stay"
    features_col: str = "features"
    weight_col: str | None = None
    init_sample_size: int = 65536     # binning sample (engine default)
    # MLlib's categoricalFeaturesInfo (see _TreeParams) — unordered-set
    # splits on StringIndexer-style columns, shared bin matrix across rounds
    categorical_features: dict[int, int] | None = None
    # Spark's validationIndicatorCol/validationTol: rows where the named
    # boolean column is true are held out of training; boosting stops when
    # their loss stops improving (runWithValidation semantics)
    validation_indicator_col: str | None = None
    validation_tol: float = 0.01      # Spark default
    # Spark's checkpointInterval analogue for OUT-OF-CORE (HostDataset)
    # fits: commit (margin column + trees so far) every
    # `checkpoint_every` boosted rounds so a preempted streaming boost
    # resumes mid-sequence.  Resident fits ignore it.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 1
    # Device-resident boosting: ONE jitted lax.scan over all max_iter
    # rounds (residual refresh + tree growth + leaf advance in the same
    # dispatch, O(1) host syncs per fit).  False restores the per-round
    # deferred loop — identical trees, kept for parity tests and as the
    # fallback while the fused path soaks.
    fused_rounds: bool = True
    # Per-round tree growth in ONE dispatch (engine fused_levels) vs the
    # per-level dispatch loop.  Only consulted by the per-round paths
    # (fused_rounds=False or validation fits) — the fused scan grows
    # levels fused by construction.  fused_rounds=False + fused_levels=
    # False together reproduce the pre-fusion (PR 4) baseline, which is
    # what the gbt20 bench A/B times as "legacy".
    fused_levels: bool = True
    # Route the level histograms through the fused Pallas kernel
    # (ops/pallas_kernels.fused_level_hist) instead of the XLA one-hot
    # contraction — the bench A/B knob (same splits, parity-tested).
    use_pallas: bool = False
    # Optional utils.profiling.StageClock: the resident fit brackets its
    # phases ("bin", "init", "boost", "fetch_materialize") so bench.py's
    # gbt20 row can report per-stage shares.  Validation fits fold the
    # per-round fetches into "boost" (no separate fetch_materialize).
    # compare=False keeps the estimator's dataclass equality/hash
    # value-based.
    stage_clock: Any = field(default=None, compare=False, repr=False)

    def _resolve_validation(self, data, ds, mesh):
        """validation_indicator_col → (n_pad,) float device mask (or None),
        sharded on the SAME mesh as the dataset (not the process default)."""
        if self.validation_indicator_col is None:
            return None
        from ...features.assembler import AssembledTable
        from ...parallel.sharding import shard_rows

        if not isinstance(data, AssembledTable):
            raise ValueError(
                f"validation_indicator_col={self.validation_indicator_col!r} "
                "needs a table input to resolve the column; got "
                f"{type(data).__name__} — pass an AssembledTable"
            )
        ind = np.asarray(
            data.table.column(self.validation_indicator_col)
        ).astype(bool)
        pad = np.zeros((ds.n_padded,), np.float32)
        pad[: ind.shape[0]] = ind
        return shard_rows(pad, mesh)

    def _boost(self, ds, mesh, loss: str, val_ind=None):
        from ...parallel.sharding import DeviceDataset, sample_valid_rows
        from .binning import quantile_thresholds

        clock = self.stage_clock
        x = ds.x.astype(jnp.float32)
        y = ds.y.astype(jnp.float32)
        w_all = ds.w.astype(jnp.float32)
        if val_ind is not None:
            # held-out rows train nothing (weight 0) but score every round
            w = w_all * (1.0 - val_ind)
            w_val = w_all * val_ind
            if float(jax.device_get(jnp.sum(w_val))) == 0.0:
                raise ValueError(
                    "validation_indicator_col selected no validation rows"
                )
        else:
            w = w_all
            w_val = None
        n = jnp.maximum(jnp.sum(w), 1.0)

        # binning depends only on x — thresholds AND the digitized matrix
        # are computed once and reused by every boosting round.  The
        # sampling/binning dataset carries the TRAINING weights only.
        with _stage(clock, "bin"):
            ds = DeviceDataset(x=x, y=y, w=w)
            sample = sample_valid_rows(ds, self.init_sample_size, self.seed)
            if sample.shape[0] == 0:
                raise ValueError("GBT fit on an empty dataset")
            thr = quantile_thresholds(sample, self.max_bins)
            # the categorical range check covers ALL valid rows — a
            # held-out validation row with a bad category id must raise
            # too, not slip into every round's advance() as an "unseen
            # category"
            binned_t = bin_feature_matrix(
                x, thr, self.categorical_features, w=w_all
            )

        with _stage(clock, "init"):
            ybar = float(jax.device_get(jnp.sum(y * w) / n))
        if loss == "squared":
            f0 = ybar
        else:  # logistic: F₀ = ½ log(p/(1−p)) (Spark's prior margin)
            p = min(max(ybar, 1e-6), 1.0 - 1e-6)
            f0 = 0.5 * float(np.log(p / (1.0 - p)))

        # Closes over this fit's y/w by design: compiled once per fit and
        # amortized over all M rounds (the fused hot path goes through the
        # lru-cached _make_boost_scan instead).
        # cmlhn: disable=jit-in-function — per-fit closure amortized over M rounds
        @jax.jit
        def residual(f):
            if loss == "squared":
                return y - f
            # Spark's mllib LogLoss: loss = 2·log(1+e^(−2y±F)), gradient
            # −4y±/(1+e^(2y±F)) ⇒ pseudo-residual 4(y01 − σ(2F)).  The
            # factor matters for stepSize parity with Spark.
            return 4.0 * (y - jax.nn.sigmoid(2.0 * f))

        cat = self.categorical_features
        cat_flags = (
            jnp.asarray([f in cat for f in range(x.shape[1])]) if cat else None
        )
        # shared tree-materialization state for the deferred branches
        # below — ONE definition so fused and legacy cannot diverge
        d_feat = x.shape[1]
        cat_arities = (
            tuple(cat.get(f, 0) for f in range(d_feat)) if cat else None
        )
        is_cat_host = np.asarray(
            [f in cat for f in range(d_feat)] if cat
            else np.zeros((d_feat,), bool)
        )
        thr_dev = jnp.asarray(thr, jnp.float32)
        is_cat_dev = jnp.asarray(is_cat_host)

        # Closes over this fit's x / categorical masks by design; compiled
        # once per fit, amortized over M rounds.
        # cmlhn: disable=jit-in-function — per-fit closure amortized over M rounds
        @jax.jit
        def advance(f, sf, th, val, cm):
            # categorical rounds must route by the set mask here too — the
            # residuals each later round fits depend on this prediction
            pred = predict_forest(x, sf, th, val, cm, cat_flags)[0, :, 0]
            return f + jnp.float32(self.step_size) * pred

        # Validation-split path only; closes over this fit's held-out y/w.
        # cmlhn: disable=jit-in-function — per-fit closure, validation path only
        @jax.jit
        def val_err(f):
            # mean held-out loss: squared error | Spark LogLoss 2·log(1+e^(−2y±F))
            if loss == "squared":
                e = (y - f) ** 2
            else:
                ypm = 2.0 * y - 1.0
                e = 2.0 * jnp.log1p(jnp.exp(-2.0 * ypm * f))
            return jnp.sum(e * w_val) / jnp.maximum(jnp.sum(w_val), 1.0)

        best_err = np.inf
        best_m = 0
        f_cur = jnp.full(y.shape, jnp.float32(f0))
        trees, importances = [], []

        def grow_round(t, defer: bool):
            res_ds = DeviceDataset(x=x, y=residual(f_cur), w=w)
            return grow_forest(
                res_ds,
                task="regression",           # every boosting stage fits residuals
                num_trees=1,
                max_depth=self.max_depth,
                max_bins=self.max_bins,
                min_instances_per_node=self.min_instances_per_node,
                min_info_gain=self.min_info_gain,
                bootstrap=self.subsampling_rate < 1.0,
                subsampling_rate=self.subsampling_rate,
                seed=self.seed + t,
                mesh=mesh,
                bin_thresholds=thr,
                binned_t=binned_t,
                categorical_features=self.categorical_features,
                defer_fetch=defer,
                use_pallas=self.use_pallas,
                fused_levels=self.fused_levels,
            )

        if val_ind is None and self.fused_rounds:
            # Device-resident boosting (the tentpole): ONE jitted
            # lax.scan over all M rounds — each step refreshes the
            # pseudo-residual, grows the round's tree through the fused
            # multi-level grower, materializes its device heap arrays
            # and advances F, all in the SAME dispatch.  The fit's only
            # host syncs are the binning sample, F₀, and one device_get
            # of the stacked winner tensors at the end (O(1), not
            # O(M·depth) — the per-level fetches measured ~70 ms each on
            # tunneled chips; BENCH_r05 gbt20 ≈ 1× the CPU proxy).
            run_boost = _make_boost_scan(
                mesh, d_feat, self.max_bins, self.max_depth, self.max_iter,
                loss, self.subsampling_rate < 1.0,
                float(self.subsampling_rate), self.use_pallas, cat_arities,
            )

            with _stage(clock, "boost"):
                f_cur, stacked = run_boost(
                    x, y, w, binned_t, f_cur, thr_dev, is_cat_dev,
                    self.seed, jnp.float32(self.step_size),
                    jnp.float32(self.min_instances_per_node),
                    jnp.float32(self.min_info_gain),
                )
                if clock is not None:
                    # attribution only (clocked fits): drain the scan so
                    # "boost" measures device execution, not just the
                    # enqueue — otherwise async dispatch bills the whole
                    # compute to the fetch stage.  Uninstrumented fits
                    # skip it and keep the minimal sync count.
                    from ...utils.profiling import device_fence

                    device_fence(f_cur)
            with _stage(clock, "fetch_materialize"):
                # the fit's ONE bulk host sync: every round × level
                # winner tensor in a single device_get
                fetched = jax.device_get(stacked)
                template = DeferredForest(
                    level_out=[], thr=thr, task="regression",
                    num_classes=2, cat_arities=cat_arities,
                    B=self.max_bins, max_depth=self.max_depth,
                    is_cat_host=is_cat_host, T=1, d=d_feat, S=3,
                )
                trees = [
                    template.fetch_from(
                        [
                            tuple(np.asarray(a[t]) for a in level)
                            for level in fetched
                        ]
                    )
                    for t in range(self.max_iter)
                ]
                importances = [g.importances[0] for g in trees]
        elif val_ind is None:
            # Legacy per-round deferred loop (fused_rounds=False): each
            # round's tree stays a device tensor (device_tree_arrays),
            # round t+1's residuals chain off it, and every round's
            # winner tensors are fetched in one device_get at the end.
            # Legacy A/B leg (fused_rounds=False); once per fit over M rounds.
            # cmlhn: disable=jit-in-function — legacy A/B leg, per-fit closure
            @jax.jit
            def advance_deferred(f, level_out):
                # device_tree_arrays already zeroes the catmask for
                # all-continuous fits; advance() owns the update math
                return advance(
                    f, *device_tree_arrays(
                        level_out, thr_dev, is_cat_dev, self.max_bins
                    )
                )

            with _stage(clock, "boost"):
                deferred = []
                for t in range(self.max_iter):
                    dfr = grow_round(t, defer=True)
                    deferred.append(dfr)
                    f_cur = advance_deferred(f_cur, dfr.level_out)
            with _stage(clock, "fetch_materialize"):
                all_fetched = jax.device_get([d.level_out for d in deferred])
                trees = [
                    d.fetch_from(lv) for d, lv in zip(deferred, all_fetched)
                ]
                importances = [g.importances[0] for g in trees]
        else:
            # Validation early stop decides continuation on the host each
            # round, and the eager grow_round(defer=False) fetches winners
            # inside the loop — per-round fetch and growth are inseparable
            # here, so the whole loop bills to "boost" (no separate
            # fetch_materialize stage on validation fits).
            with _stage(clock, "boost"):
                for t in range(self.max_iter):
                    grown = grow_round(t, defer=False)
                    trees.append(grown)
                    importances.append(grown.importances[0])
                    f_cur = advance(
                        f_cur,
                        jnp.asarray(grown.split_feat),
                        jnp.asarray(grown.threshold),
                        jnp.asarray(grown.value),
                        (
                            jnp.asarray(grown.split_catmask, jnp.uint32)
                            if cat
                            else jnp.zeros(grown.split_feat.shape, jnp.uint32)
                        ),
                    )
                    # Spark runWithValidation: stop when the best-so-far
                    # held-out error stops improving by validationTol
                    # (relative to max(err, 0.01)); keep the best-M prefix.
                    err = float(jax.device_get(val_err(f_cur)))
                    if best_err - err < self.validation_tol * max(err, 0.01):
                        break
                    if err < best_err:
                        best_err = err
                        best_m = t + 1
            if best_m > 0:
                trees = trees[:best_m]
                importances = importances[:best_m]

        imp = np.sum(importances, axis=0)
        s = imp.sum()
        return GBTModel(
            task="regression" if loss == "squared" else "classification",
            split_feat=np.concatenate([g.split_feat for g in trees]),
            threshold=np.concatenate([g.threshold for g in trees]),
            value=np.concatenate([g.value for g in trees]),
            init=f0,
            learning_rate=self.step_size,
            feature_importances=imp / s if s > 0 else imp,
            max_depth=self.max_depth,
            split_catmask=(
                np.concatenate([g.split_catmask for g in trees]) if cat else None
            ),
            cat_arities=trees[0].cat_arities if cat else None,
        )


    def _boost_outofcore(self, hd, mesh, loss: str) -> GBTModel:
        """Rows ≫ HBM boosting (VERDICT r3 next #4): the margin column F
        lives on the HOST (n floats — never device-resident), each round
        grows one out-of-core tree (engine.grow_forest_outofcore) on the
        host-computed pseudo-residuals, then F is advanced by streaming
        blocks through the new tree only.  Quantile thresholds are
        computed once and reused across rounds like the resident path;
        ``validation_indicator_col`` needs a table input and is rejected
        up front."""
        from ...parallel.outofcore import HostDataset
        from .binning import quantile_thresholds
        from .engine import grow_forest_outofcore

        if self.validation_indicator_col is not None:
            raise ValueError(
                "validation_indicator_col needs a table input to resolve "
                "the column; out-of-core HostDataset fits train on all rows"
            )
        if hd.y is None:
            raise ValueError("GBT fit needs labels: HostDataset(y=...)")
        if hd.n == 0 or hd.count() == 0.0:
            raise ValueError("GBT fit on an empty dataset")
        y = np.asarray(hd.y, np.float32)
        w = (
            np.asarray(hd.w, np.float32)
            if hd.w is not None
            else np.ones((hd.n,), np.float32)
        )
        n = max(float(w.sum()), 1.0)

        sample = hd.sample_rows(self.init_sample_size, self.seed)
        thr = quantile_thresholds(sample, self.max_bins)

        ybar = float((y * w).sum() / n)
        if loss == "squared":
            f0 = ybar
        else:
            p = min(max(ybar, 1e-6), 1.0 - 1e-6)
            f0 = 0.5 * float(np.log(p / (1.0 - p)))

        def residual(f):
            if loss == "squared":
                return y - f
            return 4.0 * (y - 1.0 / (1.0 + np.exp(-2.0 * f)))

        cat = self.categorical_features
        cat_flags = (
            jnp.asarray([f in cat for f in range(hd.n_features)]) if cat else None
        )

        cat_arities_np = (
            np.asarray(
                [cat.get(f, 0) for f in range(hd.n_features)], np.int32
            )
            if cat
            else None
        )
        f_cur = np.full((hd.n,), np.float32(f0), np.float32)
        trees, importances = [], []

        # checkpoint at the BOOSTED-ROUND boundary (VERDICT r4 #5): the
        # host margin column + the trees grown so far are the complete
        # fit state, so a preempted streaming boost resumes at the next
        # round instead of from round 0
        ckpt = None
        start_t = 0
        if self.checkpoint_dir:
            from ...io.fit_checkpoint import FitCheckpointer, data_fingerprint

            signature = {
                "estimator": "GBT", "storage": "outofcore", "loss": loss,
                "max_iter": self.max_iter, "max_depth": self.max_depth,
                "max_bins": self.max_bins, "step_size": self.step_size,
                "min_instances_per_node": self.min_instances_per_node,
                "min_info_gain": self.min_info_gain,
                "subsampling_rate": self.subsampling_rate,
                # JSON-normalized (lists, not tuples) — the committed
                # signature is JSON round-tripped before comparison
                "seed": self.seed,
                "cat": [list(t) for t in sorted((cat or {}).items())],
                "data": data_fingerprint(hd.x, hd.w),
                "labels": data_fingerprint(y[:, None]),
                "n": hd.n,
            }
            ckpt = FitCheckpointer(self.checkpoint_dir, signature)
            resumed = ckpt.resume()
            if resumed is not None:
                step0, arrays, _ = resumed
                thr = arrays["thr"]
                f_cur = arrays["f_cur"].astype(np.float32)
                for i in range(step0 + 1):
                    grown = GrownForest(
                        split_feat=arrays["split_feat"][i : i + 1],
                        split_bin=np.zeros_like(
                            arrays["split_feat"][i : i + 1]
                        ),
                        threshold=arrays["threshold"][i : i + 1],
                        value=arrays["value"][i : i + 1],
                        importances=arrays["importances"][i : i + 1],
                        max_depth=self.max_depth,
                        bin_thresholds=thr,
                        split_catmask=(
                            arrays["split_catmask"][i : i + 1] if cat else None
                        ),
                        cat_arities=cat_arities_np,
                    )
                    trees.append(grown)
                    importances.append(grown.importances[0])
                start_t = step0 + 1

        for t in range(start_t, self.max_iter):
            res_hd = HostDataset(
                hd.x, residual(f_cur).astype(np.float32), hd.w,
                max_device_rows=hd.max_device_rows,
            )
            grown = grow_forest_outofcore(
                res_hd,
                task="regression",
                num_trees=1,
                max_depth=self.max_depth,
                max_bins=self.max_bins,
                min_instances_per_node=self.min_instances_per_node,
                min_info_gain=self.min_info_gain,
                bootstrap=self.subsampling_rate < 1.0,
                subsampling_rate=self.subsampling_rate,
                seed=self.seed + t,
                mesh=mesh,
                categorical_features=cat,
                bin_thresholds=thr,
            )
            trees.append(grown)
            importances.append(grown.importances[0])
            # advance the host margin: stream blocks through the NEW tree
            sf = jnp.asarray(grown.split_feat)
            th = jnp.asarray(grown.threshold)
            val = jnp.asarray(grown.value)
            cm = (
                jnp.asarray(grown.split_catmask, jnp.uint32)
                if cat
                else None
            )
            _, b = hd.block_shape(mesh)
            for i, blk in enumerate(hd.blocks(mesh)):
                pred = predict_forest(blk.x, sf, th, val, cm, cat_flags)[0, :, 0]
                s = i * b
                e = min(s + b, hd.n)
                f_cur[s:e] += self.step_size * np.asarray(
                    jax.device_get(pred)
                )[: e - s]
            if ckpt is not None and (t + 1) % max(self.checkpoint_every, 1) == 0:
                arrays = {
                    "thr": thr,
                    "f_cur": f_cur,
                    "split_feat": np.concatenate([g.split_feat for g in trees]),
                    "threshold": np.concatenate([g.threshold for g in trees]),
                    "value": np.concatenate([g.value for g in trees]),
                    "importances": np.concatenate(
                        [g.importances for g in trees]
                    ),
                }
                if cat:
                    arrays["split_catmask"] = np.concatenate(
                        [g.split_catmask for g in trees]
                    )
                ckpt.save(t, arrays)

        imp = np.sum(importances, axis=0)
        s = imp.sum()
        return GBTModel(
            task="regression" if loss == "squared" else "classification",
            split_feat=np.concatenate([g.split_feat for g in trees]),
            threshold=np.concatenate([g.threshold for g in trees]),
            value=np.concatenate([g.value for g in trees]),
            init=f0,
            learning_rate=self.step_size,
            feature_importances=imp / s if s > 0 else imp,
            max_depth=self.max_depth,
            split_catmask=(
                np.concatenate([g.split_catmask for g in trees]) if cat else None
            ),
            cat_arities=trees[0].cat_arities if cat else None,
        )


@dataclass(frozen=True)
class GBTRegressor(Estimator, _GBTParams):
    def fit(self, data, label_col: str | None = None, mesh=None) -> GBTModel:
        from ...parallel.outofcore import HostDataset

        mesh = mesh or default_mesh()
        if isinstance(data, HostDataset):
            return self._boost_outofcore(data, mesh, loss="squared")
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        return self._boost(
            ds, mesh, loss="squared", val_ind=self._resolve_validation(data, ds, mesh)
        )


@dataclass(frozen=True)
class GBTClassifier(Estimator, _GBTParams):
    label_col: str = "LOS_binary"

    def fit(self, data, label_col: str | None = None, mesh=None) -> GBTModel:
        from ...parallel.outofcore import HostDataset

        mesh = mesh or default_mesh()
        if isinstance(data, HostDataset):
            if data.y is None:
                raise ValueError("GBT fit needs labels: HostDataset(y=...)")
            wv = np.asarray(data.w) if data.w is not None else None
            yv = np.asarray(data.y)[wv > 0] if wv is not None else np.asarray(data.y)
            uniq = np.unique(yv)
            if not np.all(np.isin(uniq, [0.0, 1.0])):
                raise ValueError(
                    f"GBTClassifier is binary (labels 0/1); got labels {uniq[:5]}"
                )
            return self._boost_outofcore(data, mesh, loss="logistic")
        ds = as_device_dataset(
            data, label_col or self.label_col, mesh=mesh, weight_col=self.weight_col
        )
        y = np.asarray(jax.device_get(ds.y))
        w = np.asarray(jax.device_get(ds.w))
        uniq = np.unique(y[w > 0])
        if not np.all(np.isin(uniq, [0.0, 1.0])):
            raise ValueError(
                f"GBTClassifier is binary (labels 0/1); got labels {uniq[:5]}"
            )
        return self._boost(
            ds, mesh, loss="logistic", val_ind=self._resolve_validation(data, ds, mesh)
        )
