from .decision_tree import (
    DecisionTreeClassifier,
    DecisionTreeModel,
    DecisionTreeRegressor,
)
from .random_forest import (
    RandomForestClassifier,
    RandomForestModel,
    RandomForestRegressor,
)
from .engine import GrownForest, grow_forest, predict_forest
from .gbt import GBTClassifier, GBTModel, GBTRegressor
from .binning import digitize, quantile_thresholds

__all__ = [
    "GBTClassifier",
    "GBTModel",
    "GBTRegressor",
    "DecisionTreeClassifier",
    "DecisionTreeModel",
    "DecisionTreeRegressor",
    "RandomForestClassifier",
    "RandomForestModel",
    "RandomForestRegressor",
    "GrownForest",
    "grow_forest",
    "predict_forest",
    "digitize",
    "quantile_thresholds",
]
